"""Worker-side elastic machinery: notification listener + rendezvous.

Reference parity: ``horovod/runner/elastic/worker.py``
(``WorkerNotificationService`` / ``WorkerNotificationManager``) — each
worker runs a small authenticated TCP service; the elastic driver pings
it when the host set changes, and the worker raises
``HostsUpdatedInterrupt`` at the next ``state.check_host_updates()``
(called from ``state.commit()``).

The rendezvous half replaces the reference's Gloo re-rendezvous: the
worker polls the driver's message service with its (host, slot)
identity until the driver has a rank assignment for the new world
epoch, then installs the assignment into the environment and re-inits.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Any, Dict, Optional

from ..common import faultline, metrics
from ..common.envutil import env_float
from ..runner import services
from ..runner.http_client import is_transient, jittered

LOG = logging.getLogger("horovod_tpu.elastic")

# Distinguished exit code for a worker that left via the drain
# protocol (preemption SIGTERM, stall abort): the driver treats this
# rc as a PLANNED removal — no blacklist, no failure count, no
# respawn-backoff penalty — even when the drain notice itself was
# lost.  Distinct from 0 (success: the slot is done and not
# respawned) and from the last-resort exit (70).
DRAIN_EXIT_CODE = 85


def preempt_grace_secs() -> float:
    """Seconds a preempted worker has to finish the in-flight step,
    commit, send its drain notice and exit (HOROVOD_PREEMPT_GRACE_SECS,
    default 30 — inside Cloud TPU's shortest preemption warning).  The
    same window bounds the driver's SIGTERM→SIGKILL escalation in
    runner/safe_shell_exec.py, so a drain-capable worker is never
    killed mid-commit by its own driver."""
    return env_float("HOROVOD_PREEMPT_GRACE_SECS", 30.0, minimum=0.0)


def elastic_timeout() -> float:
    """The ONE rejoin deadline, from the env the driver exports.
    Single parse point for every consumer (rendezvous polls, the
    state.py rejoin loop) so a malformed value degrades the same way
    everywhere."""
    try:
        return float(os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600"))
    except ValueError:
        return 600.0


def arm_last_resort_exit(reason: str, code: int = 70,
                         delay: float = 0.0):
    """Deadline enforcement of last resort: a worker whose elastic
    deadline expired must actually die, even when teardown wedges (an
    atexit ``hvd.shutdown()`` joining threads blocked on a dead peer's
    socket — the way workers were observed alive 13x past
    ``HOROVOD_ELASTIC_TIMEOUT``).  Arms a daemon timer that
    ``os._exit``s after ``delay`` + ``HOROVOD_ELASTIC_EXIT_GRACE``
    seconds; the grace window is for normal exception propagation and
    cleanup to finish first (0 disables).  Returns the timer (or None
    when disabled) so a bounded-work caller can ``cancel()`` it on
    success — the rejoin loop arms one around each attempt, because a
    wedged ``init`` inside the attempt would otherwise escape the
    deadline entirely."""
    try:
        grace = float(os.environ.get("HOROVOD_ELASTIC_EXIT_GRACE", "10"))
    except ValueError:
        grace = 10.0
    if grace <= 0:
        return None

    def _die():
        LOG.error("elastic deadline exceeded (%s) and the process is "
                  "still alive %.0fs past it; os._exit(%d) as last "
                  "resort", reason, grace, code)
        os._exit(code)

    t = threading.Timer(delay + grace, _die)
    t.daemon = True
    t.start()
    return t


class HostsUpdatedInterrupt(RuntimeError):
    """Raised in the worker when the driver reported a host-set change
    (reference: horovod.runner.elastic.worker.HostsUpdatedInterrupt)."""

    def __init__(self, skip_sync: bool = False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync


class WorkerStopped(SystemExit):
    """The driver removed this worker's slot from the world."""

    def __init__(self):
        super().__init__(0)


class WorkerDrained(SystemExit):
    """This worker is leaving via the drain protocol: the in-flight
    step finished, the state is committed (and spilled when durability
    is on), the drain notice went to the driver.  Exits with the
    distinguished :data:`DRAIN_EXIT_CODE` so the driver treats the
    removal as planned even if the notice was lost."""

    def __init__(self):
        super().__init__(DRAIN_EXIT_CODE)


def _driver_addr() -> Optional[tuple]:
    addr = os.environ.get("HOROVOD_ELASTIC_DRIVER_ADDR")
    if not addr:
        return None
    host, port = addr.rsplit(":", 1)
    return (host, int(port))


class WorkerNotificationManager:
    """Singleton per worker process; lazily started by
    ``hvd.elastic.run`` (no-op outside an elastic launch)."""

    def __init__(self):
        self._server: Optional[services.MessageServer] = None
        self._pending_epoch: Optional[int] = None
        self._update_result: Optional[int] = None
        self.host = os.environ.get("HOROVOD_HOSTNAME", "127.0.0.1")
        self.slot = int(os.environ.get("HOROVOD_ELASTIC_SLOT", "0"))
        # Drain protocol state: reason set once (SIGTERM handler, stall
        # abort, injected preemption), notice sent at most once, and a
        # last-resort timer guarantees the process exits with the drain
        # code inside the preemption grace window even when the
        # in-flight step never reaches another commit.  RLock, NOT
        # Lock: the SIGTERM handler runs ON the main thread between
        # bytecodes, so it can interrupt a main-thread critical section
        # of this very lock (replica_blob() during sync, the notice
        # send) — a non-reentrant lock would deadlock the worker right
        # through its grace window.
        self._drain_reason: Optional[str] = None
        self._drain_timer: Optional[threading.Timer] = None
        self._drain_notice_sent = False
        self._drain_lock = threading.RLock()
        # Buddy-replica blob (newest wins): peers mirror their durable
        # commits here via the driver so a survivor can hand back a
        # dead rank's progress at the next root election.
        self._replica: Optional[Dict[str, Any]] = None

    @property
    def active(self) -> bool:
        return _driver_addr() is not None

    def init(self):
        if self._server is not None or not self.active:
            return
        secret = os.environ.get("HOROVOD_SECRET_KEY", "")
        self._server = services.MessageServer(self._handle, secret)
        port = self._server.start()
        # retries=None: a registration lost to a transient flake would
        # cost this worker every future host-update notification.
        services.send_message(
            _driver_addr(), secret,
            {"kind": "register", "host": self.host, "slot": self.slot,
             "port": port, "pid": os.getpid()}, retries=None)
        LOG.debug("worker %s:%d notification service on port %d",
                  self.host, self.slot, port)

    def _handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        if req.get("kind") == "notify":
            payload = req.get("payload") or {}
            if payload.get("type") == "hosts_updated":
                self._pending_epoch = payload.get("epoch")
                self._update_result = payload.get("update_result")
            return {"ok": True}
        if req.get("kind") == "replica":
            # A peer's durable commit, forwarded by the driver: keep
            # the newest (CRC-validated at adoption time, not here —
            # the blob is opaque bytes on this side).
            with self._drain_lock:
                cur = self._replica
                if cur is None or int(req.get("commit_id", 0)) > \
                        int(cur.get("commit_id", 0)):
                    self._replica = {
                        "commit_id": int(req.get("commit_id", 0)),
                        "source_rank": req.get("source_rank"),
                        "blob": req.get("blob")}
            return {"ok": True}
        if req.get("kind") == "ping":
            return {"ok": True, "host": self.host, "slot": self.slot}
        if req.get("kind") == "metrics":
            # Pull half of the fleet-wide scrape: the driver's
            # /metrics provider collects every worker's snapshot and
            # merges them with a rank label per source.
            return {"ok": True, "rank": os.environ.get("HOROVOD_RANK"),
                    "snapshot": metrics.snapshot()}
        return {"error": "unknown request"}

    def has_update(self) -> bool:
        return self._pending_epoch is not None

    def consume_update(self) -> Optional[int]:
        ep, self._pending_epoch = self._pending_epoch, None
        return ep

    # -- drain protocol ----------------------------------------------------

    def replica_blob(self) -> Optional[Dict[str, Any]]:
        """The newest buddy-replica record this worker holds, if any."""
        with self._drain_lock:
            return self._replica

    def request_drain(self, reason: str):
        """Enter the drain protocol: the next ``state.commit()`` (or
        rendezvous poll) sends the drain notice and exits with the
        distinguished code.  A daemon timer enforces the grace window
        (``HOROVOD_PREEMPT_GRACE_SECS``): a worker whose in-flight
        step wedges still exits as DRAINED, not as a respawn-churning
        crash, before the platform's SIGKILL lands."""
        with self._drain_lock:
            if self._drain_reason is not None:
                return
            self._drain_reason = reason
            grace = preempt_grace_secs()
            if grace > 0:
                t = threading.Timer(grace, self._drain_deadline_exit)
                t.daemon = True
                t.start()
                self._drain_timer = t
        metrics.event("drain_request", reason=reason,
                      grace_secs=preempt_grace_secs())
        LOG.warning("drain requested (%s): finishing the in-flight "
                    "step, committing, and exiting within %.0fs",
                    reason, preempt_grace_secs())

    def drain_requested(self) -> bool:
        return self._drain_reason is not None

    def _drain_deadline_exit(self):
        LOG.error("drain grace expired with the worker still alive; "
                  "exiting with the drain code now so the platform's "
                  "SIGKILL does not beat the notice")
        try:
            self.send_drain_notice(commit_id=-1, fast=True)
        except Exception:  # noqa: BLE001 — dying anyway
            pass
        os._exit(DRAIN_EXIT_CODE)

    def arm_drain_exit(self, delay: float):
        """Re-arm the force-exit timer with a SHORT teardown allowance.
        Called once the commit + drain notice are done: everything of
        value is already safe, so the remaining grace belongs to
        normal exception unwinding and user cleanup — NOT to a wedged
        engine shutdown (observed: the tcp core's clean teardown can
        block on a peer still parked in the broken collective, eating
        the whole preemption window before the timer fired).  A grace
        of 0 disables force-exits entirely, matching request_drain."""
        if preempt_grace_secs() <= 0:
            return
        with self._drain_lock:
            if self._drain_timer is not None:
                self._drain_timer.cancel()
            t = threading.Timer(max(0.5, delay), self._drain_deadline_exit)
            t.daemon = True
            t.start()
            self._drain_timer = t

    def send_drain_notice(self, commit_id: int = 0, fast: bool = False):
        """Tell the driver this slot's exit is PLANNED (idempotent;
        best-effort: the distinguished exit code is the fallback signal
        when the notice or its ack is lost).  ``fast`` is the
        last-resort-timer variant: one short attempt only — the
        SIGKILL is imminent and os._exit must not wait out an RPC
        retry loop against a driver that may itself be preempted."""
        with self._drain_lock:
            if self._drain_notice_sent:
                return
            self._drain_notice_sent = True
            reason = self._drain_reason or "drain"
        if not self.active:
            return
        secret = os.environ.get("HOROVOD_SECRET_KEY", "")
        try:
            resp = services.send_message(
                _driver_addr(), secret,
                {"kind": "drain", "host": self.host, "slot": self.slot,
                 "commit_id": commit_id, "reason": reason},
                timeout=1.5 if fast else 5.0,
                retries=0 if fast else 2,
                deadline=2.0 if fast else max(5.0, preempt_grace_secs()))
            if not resp.get("ok"):
                LOG.warning("driver did not ack the drain notice (%r); "
                            "relying on the drain exit code", resp)
            else:
                LOG.info("drain notice acked by driver (commit id %d)",
                         commit_id)
        except Exception as exc:  # noqa: BLE001 — exit code is fallback
            LOG.warning("drain notice failed (%s); relying on the "
                        "drain exit code", exc)

    def send_finished(self, commit_id: int = 0):
        """Tell the driver this worker's train function returned
        cleanly.  For a driver-OWNED process this is redundant (the
        reaped exit code 0 says the same thing), but a crash-ADOPTED
        worker has no proc handle on the new driver — this notice is
        its only completion signal (elastic/driver.py ``finished``
        handler).  Best-effort: a lost notice degrades to the external
        liveness probe noticing the exit, never to a hang."""
        if not self.active or self._server is None:
            return
        secret = os.environ.get("HOROVOD_SECRET_KEY", "")
        try:
            services.send_message(
                _driver_addr(), secret,
                {"kind": "finished", "host": self.host,
                 "slot": self.slot, "commit_id": commit_id},
                timeout=5.0, retries=1, deadline=8.0)
            LOG.debug("finished notice sent (commit id %d)", commit_id)
        except Exception as exc:  # noqa: BLE001 — exit code is fallback
            LOG.debug("finished notice failed (%s); the driver's "
                      "liveness probe will observe the exit", exc)

    def mirror_commit(self, blob: bytes, commit_id: int, replicas: int):
        """Mirror one durable commit blob to ``replicas`` buddy ranks
        via the driver (it owns the slot→address table).  Best-effort:
        replication strengthens durability, it must never stall or
        kill the training loop."""
        if not self.active or replicas <= 0:
            return
        secret = os.environ.get("HOROVOD_SECRET_KEY", "")
        try:
            resp = services.send_message(
                _driver_addr(), secret,
                {"kind": "replicate", "host": self.host,
                 "slot": self.slot, "commit_id": commit_id,
                 "source_rank": os.environ.get("HOROVOD_RANK"),
                 "replicas": replicas, "blob": blob},
                timeout=10.0, retries=1, deadline=15.0)
            if not resp.get("ok"):
                LOG.warning("commit %d replication rejected: %r",
                            commit_id, resp)
        except Exception as exc:  # noqa: BLE001 — durability best-effort
            LOG.warning("commit %d replication failed (%s); continuing",
                        commit_id, exc)

    def rendezvous(self, timeout: Optional[float] = None,
                   min_epoch: Optional[int] = None) -> Dict[str, Any]:
        """Poll the driver until it hands this (host, slot) a rank
        assignment for the current epoch (or tells it to stop).

        ``min_epoch`` gates acceptance: a worker re-rendezvousing
        because its WORLD BROKE (a member died) must not rejoin the
        stale epoch — the driver may not have noticed the failure yet,
        and re-initializing the old world would block on dead members
        until the runtime's init deadline kills the survivor.  Poll
        until the driver publishes a newer epoch instead."""
        secret = os.environ.get("HOROVOD_SECRET_KEY", "")
        deadline = time.monotonic() + (timeout or elastic_timeout())
        while True:
            # A drain request must interrupt a PARKED worker too: one
            # waiting out a "wait" status would otherwise sit past the
            # whole grace window without ever reaching a commit, and
            # only the last-resort timer would end it.
            if self.drain_requested():
                self.send_drain_notice()
                self.arm_drain_exit(min(5.0, preempt_grace_secs()))
                raise WorkerDrained()
            if faultline.site("elastic.rendezvous.poll"):
                # Injected dropped poll: the deadline still applies.
                if time.monotonic() > deadline:
                    arm_last_resort_exit("rendezvous poll deadline")
                    raise TimeoutError(
                        "elastic rendezvous timed out for worker %s:%d"
                        % (self.host, self.slot))
                time.sleep(0.25)
                continue
            try:
                msg = {"kind": "rendezvous", "host": self.host,
                       "slot": self.slot}
                if min_epoch is not None:
                    # Tell the driver WHY a stale epoch is refused:
                    # for breaks it cannot observe (all processes
                    # alive), this demand is its only world-change
                    # signal.
                    msg["min_epoch"] = min_epoch
                # retries=None: opt in to the env-tuned retry/backoff —
                # the rendezvous poll IS the self-healing path, and its
                # outer loop still owns the hard deadline.
                resp = services.send_message(_driver_addr(), secret,
                                             msg, retries=None)
            except Exception as exc:  # noqa: BLE001 — classified below
                # Transient RPC failure (the send's own bounded
                # retry/backoff already exhausted): keep polling until
                # the deadline; a persistently unreachable driver is a
                # job failure, not a clean stop (exit 0 would read as
                # success).  Fatal failures (auth rejection) raise.
                if not is_transient(exc):
                    raise
                if time.monotonic() > deadline:
                    arm_last_resort_exit("driver unreachable")
                    raise TimeoutError(
                        "elastic driver unreachable: %s" % exc)
                # Jittered: N orphaned workers must not hammer a
                # recovering driver in lockstep.
                time.sleep(jittered(1.0))
                continue
            status = resp.get("status")
            if status == "go":
                if (min_epoch is not None
                        and resp.get("epoch", 0) < min_epoch):
                    if time.monotonic() > deadline:
                        arm_last_resort_exit("stale-epoch rendezvous")
                        raise TimeoutError(
                            "elastic rendezvous: driver never advanced "
                            "past epoch %d for worker %s:%d"
                            % (min_epoch - 1, self.host, self.slot))
                    time.sleep(jittered(0.5))
                    continue
                # New epoch assignment supersedes any pending update
                # notification for an older epoch.
                if (self._pending_epoch is not None
                        and self._pending_epoch <= resp["epoch"]):
                    self._pending_epoch = None
                return resp
            if status == "stop":
                # No last-resort timer on a clean stop: the caller may
                # legitimately run post-stop work (final checkpoint,
                # eval report) longer than the grace window, and the
                # driver's process-group terminate plus the test
                # suite's orphan reaper already cover a wedged stop.
                raise WorkerStopped()
            if time.monotonic() > deadline:
                arm_last_resort_exit("rendezvous deadline")
                raise TimeoutError(
                    "elastic rendezvous timed out for worker %s:%d"
                    % (self.host, self.slot))
            # Jittered wait-state poll: workers parked on "wait"
            # otherwise synchronize their polls against the driver.
            time.sleep(jittered(0.25))

    def shutdown(self):
        if self._server is not None:
            self._server.stop()
            self._server = None


_manager: Optional[WorkerNotificationManager] = None


def notification_manager() -> WorkerNotificationManager:
    global _manager
    if _manager is None:
        _manager = WorkerNotificationManager()
    return _manager


def _on_sigterm(signum, frame):  # noqa: ARG001 — signal API
    notification_manager().request_drain(
        "SIGTERM (preemption / planned shutdown notice)")


def install_preemption_handler() -> bool:
    """Route SIGTERM into the drain protocol (Cloud TPU preemption,
    ``kubectl delete pod``, and the driver's own escalating terminate
    all lead with SIGTERM).  Python only allows this from the main
    thread; elsewhere — or with the grace window disabled — the
    default handler (immediate death) is kept and we return False."""
    if preempt_grace_secs() <= 0:
        return False
    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
        return True
    except ValueError:  # not the main thread
        LOG.debug("preemption handler not installed (non-main thread)")
        return False


def install_assignment(info: Dict[str, Any]):
    """Write a driver rank assignment into the environment so the next
    ``hvd.init()`` (tcp controller) picks it up."""
    os.environ["HOROVOD_RANK"] = str(info["rank"])
    os.environ["HOROVOD_SIZE"] = str(info["size"])
    os.environ["HOROVOD_LOCAL_RANK"] = str(info["local_rank"])
    os.environ["HOROVOD_LOCAL_SIZE"] = str(info["local_size"])
    os.environ["HOROVOD_CROSS_RANK"] = str(info["cross_rank"])
    os.environ["HOROVOD_CROSS_SIZE"] = str(info["cross_size"])
    os.environ["HOROVOD_PORT_BASE"] = str(info["port_base"])
    os.environ["HOROVOD_RENDEZVOUS_ADDR"] = info["rendezvous_addr"]
    # World-round marker: re-used by resolve_coordinator to version the
    # jax-coordinator KV entry, so a re-rendezvoused world never reads
    # the PREVIOUS world's (dead) coordinator address.
    os.environ["HOROVOD_ELASTIC_EPOCH"] = str(info.get("epoch", 0))
    # Preserve the launcher's payload-plane choice: a --multihost world
    # must re-init the device plane (jax.distributed + multihost
    # engine) after every re-rendezvous, not silently fall to the TCP
    # plane (r5 fix: this line used to pin "tcp" unconditionally, so
    # elastic multihost workers never ran device collectives at all —
    # and until this round it still clobbered every NON-multihost
    # explicit value).  Default to tcp ONLY when the launcher set
    # nothing: elastic worlds need a deterministic controller (the
    # Config default "auto" could diverge across re-spawned workers),
    # but an explicit value is the launcher's call and must survive
    # every re-rendezvous.
    os.environ.setdefault("HOROVOD_CONTROLLER", "tcp")
