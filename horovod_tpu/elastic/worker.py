"""Worker-side elastic machinery: notification listener + rendezvous.

Reference parity: ``horovod/runner/elastic/worker.py``
(``WorkerNotificationService`` / ``WorkerNotificationManager``) — each
worker runs a small authenticated TCP service; the elastic driver pings
it when the host set changes, and the worker raises
``HostsUpdatedInterrupt`` at the next ``state.check_host_updates()``
(called from ``state.commit()``).

The rendezvous half replaces the reference's Gloo re-rendezvous: the
worker polls the driver's message service with its (host, slot)
identity until the driver has a rank assignment for the new world
epoch, then installs the assignment into the environment and re-inits.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from ..common import faultline
from ..runner import services
from ..runner.http_client import is_transient, jittered

LOG = logging.getLogger("horovod_tpu.elastic")


def elastic_timeout() -> float:
    """The ONE rejoin deadline, from the env the driver exports.
    Single parse point for every consumer (rendezvous polls, the
    state.py rejoin loop) so a malformed value degrades the same way
    everywhere."""
    try:
        return float(os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600"))
    except ValueError:
        return 600.0


def arm_last_resort_exit(reason: str, code: int = 70,
                         delay: float = 0.0):
    """Deadline enforcement of last resort: a worker whose elastic
    deadline expired must actually die, even when teardown wedges (an
    atexit ``hvd.shutdown()`` joining threads blocked on a dead peer's
    socket — the way workers were observed alive 13x past
    ``HOROVOD_ELASTIC_TIMEOUT``).  Arms a daemon timer that
    ``os._exit``s after ``delay`` + ``HOROVOD_ELASTIC_EXIT_GRACE``
    seconds; the grace window is for normal exception propagation and
    cleanup to finish first (0 disables).  Returns the timer (or None
    when disabled) so a bounded-work caller can ``cancel()`` it on
    success — the rejoin loop arms one around each attempt, because a
    wedged ``init`` inside the attempt would otherwise escape the
    deadline entirely."""
    try:
        grace = float(os.environ.get("HOROVOD_ELASTIC_EXIT_GRACE", "10"))
    except ValueError:
        grace = 10.0
    if grace <= 0:
        return None

    def _die():
        LOG.error("elastic deadline exceeded (%s) and the process is "
                  "still alive %.0fs past it; os._exit(%d) as last "
                  "resort", reason, grace, code)
        os._exit(code)

    t = threading.Timer(delay + grace, _die)
    t.daemon = True
    t.start()
    return t


class HostsUpdatedInterrupt(RuntimeError):
    """Raised in the worker when the driver reported a host-set change
    (reference: horovod.runner.elastic.worker.HostsUpdatedInterrupt)."""

    def __init__(self, skip_sync: bool = False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync


class WorkerStopped(SystemExit):
    """The driver removed this worker's slot from the world."""

    def __init__(self):
        super().__init__(0)


def _driver_addr() -> Optional[tuple]:
    addr = os.environ.get("HOROVOD_ELASTIC_DRIVER_ADDR")
    if not addr:
        return None
    host, port = addr.rsplit(":", 1)
    return (host, int(port))


class WorkerNotificationManager:
    """Singleton per worker process; lazily started by
    ``hvd.elastic.run`` (no-op outside an elastic launch)."""

    def __init__(self):
        self._server: Optional[services.MessageServer] = None
        self._pending_epoch: Optional[int] = None
        self._update_result: Optional[int] = None
        self.host = os.environ.get("HOROVOD_HOSTNAME", "127.0.0.1")
        self.slot = int(os.environ.get("HOROVOD_ELASTIC_SLOT", "0"))

    @property
    def active(self) -> bool:
        return _driver_addr() is not None

    def init(self):
        if self._server is not None or not self.active:
            return
        secret = os.environ.get("HOROVOD_SECRET_KEY", "")
        self._server = services.MessageServer(self._handle, secret)
        port = self._server.start()
        # retries=None: a registration lost to a transient flake would
        # cost this worker every future host-update notification.
        services.send_message(
            _driver_addr(), secret,
            {"kind": "register", "host": self.host, "slot": self.slot,
             "port": port, "pid": os.getpid()}, retries=None)
        LOG.debug("worker %s:%d notification service on port %d",
                  self.host, self.slot, port)

    def _handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        if req.get("kind") == "notify":
            payload = req.get("payload") or {}
            if payload.get("type") == "hosts_updated":
                self._pending_epoch = payload.get("epoch")
                self._update_result = payload.get("update_result")
            return {"ok": True}
        if req.get("kind") == "ping":
            return {"ok": True, "host": self.host, "slot": self.slot}
        return {"error": "unknown request"}

    def has_update(self) -> bool:
        return self._pending_epoch is not None

    def consume_update(self) -> Optional[int]:
        ep, self._pending_epoch = self._pending_epoch, None
        return ep

    def rendezvous(self, timeout: Optional[float] = None,
                   min_epoch: Optional[int] = None) -> Dict[str, Any]:
        """Poll the driver until it hands this (host, slot) a rank
        assignment for the current epoch (or tells it to stop).

        ``min_epoch`` gates acceptance: a worker re-rendezvousing
        because its WORLD BROKE (a member died) must not rejoin the
        stale epoch — the driver may not have noticed the failure yet,
        and re-initializing the old world would block on dead members
        until the runtime's init deadline kills the survivor.  Poll
        until the driver publishes a newer epoch instead."""
        secret = os.environ.get("HOROVOD_SECRET_KEY", "")
        deadline = time.monotonic() + (timeout or elastic_timeout())
        while True:
            if faultline.site("elastic.rendezvous.poll"):
                # Injected dropped poll: the deadline still applies.
                if time.monotonic() > deadline:
                    arm_last_resort_exit("rendezvous poll deadline")
                    raise TimeoutError(
                        "elastic rendezvous timed out for worker %s:%d"
                        % (self.host, self.slot))
                time.sleep(0.25)
                continue
            try:
                msg = {"kind": "rendezvous", "host": self.host,
                       "slot": self.slot}
                if min_epoch is not None:
                    # Tell the driver WHY a stale epoch is refused:
                    # for breaks it cannot observe (all processes
                    # alive), this demand is its only world-change
                    # signal.
                    msg["min_epoch"] = min_epoch
                # retries=None: opt in to the env-tuned retry/backoff —
                # the rendezvous poll IS the self-healing path, and its
                # outer loop still owns the hard deadline.
                resp = services.send_message(_driver_addr(), secret,
                                             msg, retries=None)
            except Exception as exc:  # noqa: BLE001 — classified below
                # Transient RPC failure (the send's own bounded
                # retry/backoff already exhausted): keep polling until
                # the deadline; a persistently unreachable driver is a
                # job failure, not a clean stop (exit 0 would read as
                # success).  Fatal failures (auth rejection) raise.
                if not is_transient(exc):
                    raise
                if time.monotonic() > deadline:
                    arm_last_resort_exit("driver unreachable")
                    raise TimeoutError(
                        "elastic driver unreachable: %s" % exc)
                # Jittered: N orphaned workers must not hammer a
                # recovering driver in lockstep.
                time.sleep(jittered(1.0))
                continue
            status = resp.get("status")
            if status == "go":
                if (min_epoch is not None
                        and resp.get("epoch", 0) < min_epoch):
                    if time.monotonic() > deadline:
                        arm_last_resort_exit("stale-epoch rendezvous")
                        raise TimeoutError(
                            "elastic rendezvous: driver never advanced "
                            "past epoch %d for worker %s:%d"
                            % (min_epoch - 1, self.host, self.slot))
                    time.sleep(jittered(0.5))
                    continue
                # New epoch assignment supersedes any pending update
                # notification for an older epoch.
                if (self._pending_epoch is not None
                        and self._pending_epoch <= resp["epoch"]):
                    self._pending_epoch = None
                return resp
            if status == "stop":
                # No last-resort timer on a clean stop: the caller may
                # legitimately run post-stop work (final checkpoint,
                # eval report) longer than the grace window, and the
                # driver's process-group terminate plus the test
                # suite's orphan reaper already cover a wedged stop.
                raise WorkerStopped()
            if time.monotonic() > deadline:
                arm_last_resort_exit("rendezvous deadline")
                raise TimeoutError(
                    "elastic rendezvous timed out for worker %s:%d"
                    % (self.host, self.slot))
            # Jittered wait-state poll: workers parked on "wait"
            # otherwise synchronize their polls against the driver.
            time.sleep(jittered(0.25))

    def shutdown(self):
        if self._server is not None:
            self._server.stop()
            self._server = None


_manager: Optional[WorkerNotificationManager] = None


def notification_manager() -> WorkerNotificationManager:
    global _manager
    if _manager is None:
        _manager = WorkerNotificationManager()
    return _manager


def install_assignment(info: Dict[str, Any]):
    """Write a driver rank assignment into the environment so the next
    ``hvd.init()`` (tcp controller) picks it up."""
    os.environ["HOROVOD_RANK"] = str(info["rank"])
    os.environ["HOROVOD_SIZE"] = str(info["size"])
    os.environ["HOROVOD_LOCAL_RANK"] = str(info["local_rank"])
    os.environ["HOROVOD_LOCAL_SIZE"] = str(info["local_size"])
    os.environ["HOROVOD_CROSS_RANK"] = str(info["cross_rank"])
    os.environ["HOROVOD_CROSS_SIZE"] = str(info["cross_size"])
    os.environ["HOROVOD_PORT_BASE"] = str(info["port_base"])
    os.environ["HOROVOD_RENDEZVOUS_ADDR"] = info["rendezvous_addr"]
    # World-round marker: re-used by resolve_coordinator to version the
    # jax-coordinator KV entry, so a re-rendezvoused world never reads
    # the PREVIOUS world's (dead) coordinator address.
    os.environ["HOROVOD_ELASTIC_EPOCH"] = str(info.get("epoch", 0))
    # Preserve the launcher's payload-plane choice: a --multihost world
    # must re-init the device plane (jax.distributed + multihost
    # engine) after every re-rendezvous, not silently fall to the TCP
    # plane (r5 fix: this line used to pin "tcp" unconditionally, so
    # elastic multihost workers never ran device collectives at all —
    # and until this round it still clobbered every NON-multihost
    # explicit value).  Default to tcp ONLY when the launcher set
    # nothing: elastic worlds need a deterministic controller (the
    # Config default "auto" could diverge across re-spawned workers),
    # but an explicit value is the launcher's call and must survive
    # every re-rendezvous.
    os.environ.setdefault("HOROVOD_CONTROLLER", "tcp")
