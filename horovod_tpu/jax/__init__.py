"""``horovod_tpu.jax`` — the JAX adapter (the reference's per-framework
adapter pattern, e.g. ``horovod/torch/__init__.py``, applied to JAX; the
``horovod.jax`` adapter named by BASELINE.json's north star).

    import horovod_tpu.jax as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
"""

# Identity / lifecycle / eager collectives re-exported from the core.
from ..common.basics import (init, shutdown, is_initialized, rank, size,
                             local_rank, local_size, cross_rank, cross_size,
                             is_homogeneous, topology, start_timeline,
                             stop_timeline, xla_built, tcp_built, gloo_built,
                             mpi_built, nccl_built, ccl_built, ddl_built,
                             cuda_built, rocm_built, mpi_enabled,
                             mpi_threads_supported)
from ..common.metrics import metrics_snapshot
from ..common.process_sets import (ProcessSet, global_process_set,
                                   add_process_set, remove_process_set,
                                   process_set_by_id, process_set_ids)
from ..ops.api import (SUM, AVERAGE, MIN, MAX, PRODUCT, ADASUM,
                       allreduce, allreduce_async, grouped_allreduce,
                       grouped_allreduce_async, allgather, allgather_async,
                       grouped_allgather, grouped_allgather_async,
                       broadcast, broadcast_async, alltoall, alltoall_async,
                       reducescatter, reducescatter_async,
                       grouped_reducescatter, grouped_reducescatter_async,
                       barrier, join, synchronize, poll)
from ..ops.engine import CollectiveHandle, HorovodInternalError

# Adapter-specific surface.
from .compression import Compression
from .optimizer import (DistributedOptimizer, DistributedGradientTape,
                        allreduce_gradients)
from .functions import (broadcast_parameters, broadcast_optimizer_state,
                        broadcast_object, allgather_object)
from .sync_batch_norm import (SyncBatchNorm, sync_batch_norm_stats,
                              sync_batch_norm_apply)
from .data_parallel import (fetch,
                            make_data_parallel_step, make_sharded_jit_step,
                            shard_batch, replicate, metric_average)
from .zero import (make_zero1_step, make_zero2_step, make_zero3_step,
                   make_zero_step, zero_stage_from_env)
from .mesh import create_mesh, create_hybrid_mesh
from . import spmd
from . import callbacks
from .. import elastic

Sum = SUM
Average = AVERAGE
Min = MIN
Max = MAX
Product = PRODUCT
Adasum = ADASUM
