"""Training-loop callbacks (the Keras callback set, JAX-idiomatic).

Reference parity: ``horovod/_keras/callbacks.py`` +
``horovod/callbacks`` exposure — ``BroadcastGlobalVariablesCallback``,
``MetricAverageCallback``, ``LearningRateWarmupCallback``,
``LearningRateScheduleCallback``.  There is no Keras fit-loop here;
callbacks are small objects a JAX training loop invokes at the same
hook points, and the LR callbacks can also be lowered to an optax
schedule (``as_optax_schedule``) so the policy can live inside a jitted
update — the TPU-idiomatic form.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import numpy as np

from ..common import basics
from ..ops import api as eager
from .functions import broadcast_parameters


class Callback:
    """Hook points mirroring the Keras callback protocol."""

    def on_train_begin(self, state=None):
        pass

    def on_epoch_begin(self, epoch: int, state=None):
        pass

    def on_batch_end(self, batch: int, logs: Optional[Dict] = None):
        pass

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None):
        pass


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial parameters from ``root_rank`` at train begin so
    all replicas start identical (reference
    BroadcastGlobalVariablesCallback)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_train_begin(self, state=None):
        if state is None or self.broadcast_done:
            return state
        out = broadcast_parameters(state, self.root_rank)
        self.broadcast_done = True
        return out


class MetricAverageCallback(Callback):
    """Average epoch metrics over all ranks before they are logged
    (reference MetricAverageCallback)."""

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None):
        if not logs or not basics.is_initialized():
            return logs
        if basics.size() <= 1 or basics._controller_is_spmd():
            # In-process SPMD: the single controller already sees global
            # metrics; only multi-process worlds need the average.
            return logs
        for k in list(logs.keys()):
            v = np.asarray(logs[k], dtype=np.float64)
            logs[k] = float(np.asarray(eager.allreduce(
                v, op=eager.AVERAGE,
                name="metric.%s" % k)).reshape(()))
        return logs


class LearningRateWarmupCallback(Callback):
    """Scale LR from ``initial_lr`` to ``initial_lr * multiplier`` over
    the first ``warmup_epochs`` (reference LearningRateWarmupCallback;
    multiplier defaults to world size per the linear-scaling rule)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: Optional[int] = None,
                 multiplier: Optional[float] = None,
                 verbose: bool = False):
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.multiplier = (multiplier if multiplier is not None
                           else float(basics.size()
                                      if basics.is_initialized() else 1))
        self.verbose = verbose
        self.current_lr = initial_lr

    def lr_at(self, epoch: float) -> float:
        if epoch >= self.warmup_epochs:
            return self.initial_lr * self.multiplier
        # Exponential ramp matching the reference's per-batch warmup.
        frac = epoch / max(self.warmup_epochs, 1e-9)
        return self.initial_lr * self.multiplier ** frac

    def on_batch_end(self, batch: int, logs: Optional[Dict] = None):
        if self.steps_per_epoch is None:
            # Reference behavior: per-batch warmup cannot work without
            # knowing the epoch length — fail loudly, don't mis-ramp.
            raise ValueError(
                "LearningRateWarmupCallback needs steps_per_epoch for "
                "per-batch warmup (epoch-granular use is fine without)")
        epoch_f = getattr(self, "_epoch", 0) + \
            batch / float(self.steps_per_epoch)
        self.current_lr = self.lr_at(epoch_f)
        if logs is not None:
            logs["lr"] = self.current_lr

    def on_epoch_begin(self, epoch: int, state=None):
        self._epoch = epoch
        self.current_lr = self.lr_at(epoch)
        if self.verbose and (not basics.is_initialized()
                             or basics.rank() == 0):
            print("Epoch %d: warmup lr = %g" % (epoch, self.current_lr))

    def as_optax_schedule(self) -> Callable[[int], float]:
        """Lower to an optax-style schedule(step)->lr for use inside a
        jitted update (TPU-idiomatic form)."""
        import jax.numpy as jnp

        if self.steps_per_epoch is None:
            raise ValueError(
                "as_optax_schedule needs steps_per_epoch to convert the "
                "epoch-based warmup into a per-step schedule")
        warmup_steps = self.warmup_epochs * self.steps_per_epoch

        def schedule(step):
            frac = jnp.minimum(step / max(warmup_steps, 1), 1.0)
            return self.initial_lr * self.multiplier ** frac
        return schedule


class LearningRateScheduleCallback(Callback):
    """Piecewise LR schedule (reference LearningRateScheduleCallback):
    between ``start_epoch`` and ``end_epoch`` the LR is
    ``initial_lr * multiplier`` where ``multiplier`` is a constant or a
    function of epoch; ``staircase`` applies it at integer epochs."""

    def __init__(self, initial_lr: float, multiplier,
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        if callable(multiplier):
            self._mult = multiplier
        else:
            self._mult = lambda epoch: multiplier
        self.current_lr = initial_lr

    def _active(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def lr_at(self, epoch: float) -> float:
        e = math.floor(epoch) if self.staircase else epoch
        if self._active(e):
            return self.initial_lr * self._mult(e)
        return self.current_lr

    def on_epoch_begin(self, epoch: int, state=None):
        self.current_lr = self.lr_at(epoch)
