"""Gradient compression for bandwidth-bound allreduce.

Equivalent of the reference's ``horovod/torch/compression.py`` /
``horovod/tensorflow/compression.py``: a ``Compression`` namespace with
``none`` and ``fp16`` compressors whose ``compress``/``decompress`` bracket
the collective.  TPU additions: ``bf16``, the native low-precision format
of the MXU/ICI (fp16 is kept for API parity; bf16 is what you want on
TPU), plus the r12 quantizing wire codecs — ``int8`` (symmetric per-chunk
absmax) and ``fp8`` (e4m3 cast) — and the :class:`ErrorFeedback` wrapper
that makes quantized *reductions* convergent by folding the quantization
error back into the next step (Seide et al. 1-bit SGD / EF-SGD lineage).

The quantizing codecs are what ``HOROVOD_CROSS_HOST_COMPRESSION`` puts on
the cross-host leg of the hierarchical collectives (``ops/multihost.py``):
upstream compresses the WHOLE tensor at the framework layer; this repo
compresses only the DCN-bound leg and keeps in-host ICI full precision.
All compressors here are stateless pure functions of their inputs (usable
eagerly or inside jit); only :class:`ErrorFeedback` carries state (the
per-bucket residual pytree), which is why it is a wrapper, not a
``Compressor``.
"""

from __future__ import annotations

import collections

import jax.numpy as jnp

# e4m3 is the jax wire dtype for Compression.fp8; older jax has no
# float8 dtypes — FP8Compressor then fails loudly (and the multihost
# codec resolver falls back to a bf16 wire with an ERROR log).
FP8_WIRE_DTYPE = getattr(jnp, "float8_e4m3fn", None)
# Largest finite e4m3 value: casting past it yields NaN (ml_dtypes
# saturating-cast semantics do NOT apply through astype), so any
# engine-side fp8 wire must absmax-scale into this range first.
E4M3_MAX = 448.0


class Compressor:
    """Interface: compress(tensor) -> (compressed, ctx); decompress undoes."""

    #: True when the wire tensor may be handed to a plain summing
    #: collective (the framework bracket's compress -> allreduce ->
    #: decompress contract).  The quantizing codecs are NOT: int8
    #: addition wraps past +-127 and each rank's absmax scale differs,
    #: so summing raw wire tensors is silent corruption — they belong
    #: on the engine's cross-host leg (HOROVOD_CROSS_HOST_COMPRESSION),
    #: which dequantizes before any arithmetic.
    reduce_safe = True

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


def check_reduce_safe(compression, where: str):
    """Reject a quantizing codec handed to a bracket that sums wire
    tensors across ranks — loudly, before any collective runs."""
    if not getattr(compression, "reduce_safe", True):
        label = getattr(compression, "__name__",
                        type(compression).__name__)
        raise ValueError(
            "%s cannot use %s: the %s bracket allreduces the WIRE "
            "tensor, and quantized wire tensors must never meet "
            "reduction arithmetic (int8 wraps, per-rank scales "
            "diverge).  Set HOROVOD_CROSS_HOST_COMPRESSION=%s for "
            "quantized reductions (engine-side, dequantized before "
            "arithmetic, with error feedback), or pass "
            "Compression.fp16/bf16 here." % (
                where, label, where,
                getattr(compression, "codec_name", "int8")))


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(cls.wire_dtype), tensor.dtype
        # Integer/bool tensors ride the wire untouched: ctx None marks
        # the no-op so decompress is a TRUE identity (a dtype ctx here
        # would re-cast — a silent copy — on the way out).
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Int8Quantizer(Compressor):
    """Symmetric per-chunk absmax int8 quantization (4x wire vs fp32).

    Chunks are the rows of the leading axis: an ``[k, m]`` input gets k
    independent scales (the hierarchical engine stages one row per
    local chip, so each chip's cross-host wire carries its own scale);
    a 1-D tensor quantizes as one chunk.  ``ctx`` is ``(scale, dtype)``
    with ``scale`` broadcastable against the wire tensor; integer and
    bool tensors pass through with ``ctx=None`` (quantizing an already-
    discrete payload would corrupt it for nothing).

    Stateless and jit-compatible; math runs in f32 regardless of the
    payload dtype so bf16 payloads don't lose the absmax to rounding.
    """

    reduce_safe = False
    codec_name = "int8"

    @staticmethod
    def compress(tensor):
        if not jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor, None
        xf = tensor.astype(jnp.float32)
        axes = tuple(range(1, xf.ndim)) if xf.ndim > 1 else None
        amax = (jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
                if axes else jnp.max(jnp.abs(xf)))
        # All-zero chunks keep scale 1 so q = 0 round-trips to 0
        # without a 0/0.
        scale = jnp.where(amax > 0, amax, 1.0) / 127.0
        q = jnp.clip(jnp.rint(xf / scale), -127, 127).astype(jnp.int8)
        return q, (scale, tensor.dtype)

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is None:
            return tensor
        scale, dtype = ctx
        return (tensor.astype(jnp.float32) * scale).astype(dtype)


class FP8Compressor(Compressor):
    """e4m3 cast (4x wire vs fp32, ~2 decimal digits of mantissa).

    Plain dtype cast — no scales — so it is exactly the
    :class:`_CastCompressor` contract on jax versions that ship
    ``float8_e4m3fn``; older jax fails LOUDLY here (and the multihost
    codec resolver downgrades to a bf16 wire with an ERROR log instead
    of silently shipping full precision).

    ``reduce_safe = False``: e4m3 has ~2 significant digits — summing
    wire tensors across ranks compounds the cast error per rank and
    overflows past +-448; like int8 it belongs on the engine's
    dequantize-first cross-host leg.
    """

    reduce_safe = False
    codec_name = "fp8"

    @staticmethod
    def compress(tensor):
        if not jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor, None
        if FP8_WIRE_DTYPE is None:
            raise RuntimeError(
                "Compression.fp8 needs jax.numpy.float8_e4m3fn, which "
                "this jax version does not provide; use int8 or bf16 "
                "wire compression instead")
        return tensor.astype(FP8_WIRE_DTYPE), tensor.dtype

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class ScaledFP8Quantizer(Compressor):
    """Per-chunk absmax-scaled e4m3 — the ENGINE's fp8 wire.

    The plain-cast :class:`FP8Compressor` NaNs past ±448 (e4m3's
    finite range); scaling each chunk's absmax onto :data:`E4M3_MAX`
    guarantees in-range representation for any payload and buys the
    full mantissa near the top of the range.  Chunk semantics, ctx
    shape, and jit-compatibility match :class:`Int8Quantizer` exactly,
    so the two are interchangeable at the engine's quantize seams
    (leg-1 eager encode AND the in-program leg-2 requantize)."""

    reduce_safe = False
    codec_name = "fp8"

    @staticmethod
    def compress(tensor):
        if not jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor, None
        if FP8_WIRE_DTYPE is None:
            raise RuntimeError(
                "fp8 wire compression needs jax.numpy.float8_e4m3fn, "
                "which this jax version does not provide")
        xf = tensor.astype(jnp.float32)
        axes = tuple(range(1, xf.ndim)) if xf.ndim > 1 else None
        amax = (jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
                if axes else jnp.max(jnp.abs(xf)))
        scale = jnp.where(amax > 0, amax, 1.0) / E4M3_MAX
        q = (xf / scale).astype(FP8_WIRE_DTYPE)
        return q, (scale, tensor.dtype)

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is None:
            return tensor
        scale, dtype = ctx
        return (tensor.astype(jnp.float32) * scale).astype(dtype)


class ErrorFeedback:
    """Residual-carrying wrapper making quantized reductions convergent.

    EF-SGD / 1-bit-Adam scheme: each step compresses ``tensor +
    residual`` and keeps ``residual = compensated - dequantized(sent)``
    for the next step, so quantization error is *delayed*, never lost —
    a gradient component too small for the current absmax scale
    accumulates until it fires.  Residuals are keyed per BUCKET (the
    multihost engine keys by op + padded size class + dtype, matching
    its fusion-buffer granularity) and held in f32 so bf16 payloads
    don't round the correction away; an LRU cap bounds the state on
    shape-churning jobs (``HOROVOD_COMPRESSION_RESIDUAL_BUCKETS``).

    Only meaningful for linear reductions (Sum/Average) — min/max/
    product and the data-movement collectives get plain quantize/
    dequantize from the wrapped compressor.
    """

    def __init__(self, compressor: Compressor, max_buckets: int = 64):
        self.compressor = compressor
        self.max_buckets = max(int(max_buckets), 1)
        # A summing bracket is exactly as safe as the wrapped wire:
        # EF(int8) must be rejected by check_reduce_safe like bare
        # int8 (the residual discipline does not make int8 addition
        # stop wrapping), while EF(fp16) stays accepted.
        self.reduce_safe = getattr(compressor, "reduce_safe", True)
        self.codec_name = getattr(compressor, "codec_name", "int8")
        self._residuals: "collections.OrderedDict" = \
            collections.OrderedDict()

    def compress(self, tensor, bucket=None):
        """Compress ``tensor + residual[bucket]``, updating the
        residual; returns ``(wire, ctx)`` like a Compressor."""
        if not jnp.issubdtype(tensor.dtype, jnp.floating):
            # Discrete payloads pass through the wrapped compressor's
            # no-op path untouched — lifting them to f32 here would
            # quantize (corrupt) data the codec contract exempts.
            return self.compressor.compress(tensor)
        key = bucket if bucket is not None else (
            tuple(tensor.shape), str(tensor.dtype))
        comp = tensor.astype(jnp.float32)
        res = self._residuals.pop(key, None)
        if res is not None and res.shape == comp.shape:
            comp = comp + res
        wire, ctx = self.compressor.compress(comp)
        if ctx is None:
            # Pass-through payload (integer): nothing was lost, keep
            # no residual.
            return wire, ctx
        # ``comp`` was lifted to f32, so the inner ctx records f32 as
        # the restore dtype; rewrite it to the CALLER's dtype so
        # decompress round-trips bf16 -> bf16, not bf16 -> f32.
        ctx = ((ctx[0], tensor.dtype) if isinstance(ctx, tuple)
               else tensor.dtype)
        sent = self.compressor.decompress(wire, ctx)
        self._residuals[key] = comp - sent.astype(jnp.float32)
        while len(self._residuals) > self.max_buckets:
            self._residuals.popitem(last=False)
        return wire, ctx

    def decompress(self, tensor, ctx):
        return self.compressor.decompress(tensor, ctx)

    def reset(self):
        self._residuals.clear()


class Compression:
    """Reference-parity namespace: ``Compression.none``, ``Compression.fp16``
    (+ TPU-native ``Compression.bf16``, quantizing ``int8``/``fp8``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Quantizer
    fp8 = FP8Compressor
