"""Gradient compression for bandwidth-bound allreduce.

Equivalent of the reference's ``horovod/torch/compression.py`` /
``horovod/tensorflow/compression.py``: a ``Compression`` namespace with
``none`` and ``fp16`` compressors whose ``compress``/``decompress`` bracket
the collective.  TPU addition: ``bf16``, the native low-precision format of
the MXU/ICI (fp16 is kept for API parity; bf16 is what you want on TPU).
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: compress(tensor) -> (compressed, ctx); decompress undoes."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(ctx, jnp.floating):
            return tensor.astype(cls.wire_dtype), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Compression:
    """Reference-parity namespace: ``Compression.none``, ``Compression.fp16``
    (+ TPU-native ``Compression.bf16``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
