"""Data-parallel step builders: the idiomatic-TPU training loop.

The reference wires distribution into the optimizer because torch/TF
execute op-by-op.  Under XLA the natural unit is the whole compiled train
step, so this module provides the two TPU-native ways to run DP:

* ``make_data_parallel_step`` — explicit SPMD via ``jax.shard_map`` over
  the 'hvd' mesh axis: per-device batch shard in, psum-averaged gradients
  (through ``DistributedOptimizer``) in-program.  Collectives ride ICI and
  overlap with backward compute under XLA's scheduler.
* ``make_sharded_jit_step`` — compiler-driven: params replicated, batch
  sharded; ``jax.jit`` with those shardings makes XLA insert the gradient
  all-reduce itself.  Zero framework code in the hot path — the ceiling
  case the engine's eager path is measured against.

``shard_batch`` places a host batch so dim 0 is split across the world.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common import basics
from ..ops.xla_ops import AVERAGE
from . import spmd
from .compression import Compression
from .optimizer import DistributedOptimizer


_flat_mesh_cache = {}


def _multihost() -> bool:
    return (basics.is_initialized()
            and basics._controller_mode() == "multihost")


def _world_mesh():
    """The DP mesh: the in-process engine's device mesh, or — in
    multihost mode — ONE flat axis over every device of every process
    (the global mesh ``jax.distributed`` assembled), so the same step
    builders drive a pod the way they drive a single host."""
    if _multihost():
        import collections

        from jax.sharding import Mesh
        devs = sorted(jax.devices(),
                      key=lambda d: (d.process_index, d.id))
        counts = collections.Counter(d.process_index for d in devs)
        if len(set(counts.values())) > 1:
            # ValueError, NOT HorovodInternalError: the elastic wrapper
            # retries HorovodInternalError, and a heterogeneous slice
            # does not heal by re-rendezvousing into the same hosts —
            # this must terminate the run with the actionable message.
            raise ValueError(
                "multihost data parallelism needs EQUAL addressable-"
                "device counts on every process, got %s per process. "
                "shard_batch/make_data_parallel_step assume uniform "
                "per-process shards; rebalance the slice (or resize "
                "the elastic world to homogeneous hosts) before "
                "building the step."
                % dict(sorted(counts.items())))
        # Key by the device identities so an elastic re-init with a
        # changed world never reuses a stale mesh; same-world calls
        # keep returning the identical Mesh object for jit cache hits.
        key = tuple((d.process_index, d.id) for d in devs)
        mesh = _flat_mesh_cache.get(key)
        if mesh is None:
            _flat_mesh_cache.clear()
            mesh = Mesh(np.asarray(devs), (spmd.DEFAULT_AXIS,))
            _flat_mesh_cache[key] = mesh
        return mesh
    return basics._get_engine().collectives_for(0).mesh


def shard_batch(batch):
    """Device-put a pytree so leaf dim 0 is sharded across the world.

    In-process mode the argument is the full batch; in multihost mode
    each process passes ITS shard of the global batch (reference
    semantics: every rank loads its own data) and the pieces assemble
    into one global array.
    """
    mesh = _world_mesh()
    sharding = NamedSharding(mesh, P(spmd.DEFAULT_AXIS))
    if _multihost():
        nproc = jax.process_count()

        def put(x):
            x = np.asarray(x)
            global_shape = (x.shape[0] * nproc,) + x.shape[1:]
            return jax.make_array_from_process_local_data(
                sharding, x, global_shape)

        return jax.tree.map(put, batch)
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), batch)


def replicate(tree):
    """Device-put a pytree fully replicated across the world (every
    process must pass the same values in multihost mode)."""
    mesh = _world_mesh()
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), tree)


def fetch(tree):
    """Host values of a replicated pytree (works on global arrays whose
    shards span processes: reads this process's replica)."""
    def get(x):
        if hasattr(x, "addressable_shards"):
            return np.asarray(jax.device_get(x.addressable_shards[0].data))
        return np.asarray(x)

    return jax.tree.map(get, tree)


def make_data_parallel_step(loss_fn: Callable,
                            optimizer: optax.GradientTransformation,
                            compression=Compression.none,
                            op: str = AVERAGE,
                            backward_passes_per_step: int = 1,
                            donate: bool = True):
    """Build a jitted SPMD train step: (params, opt_state, batch) ->
    (params, opt_state, loss).

    ``loss_fn(params, batch) -> scalar`` is written per-shard; gradients
    are world-averaged by the wrapped optimizer before the update.
    """
    mesh = _world_mesh()
    axis = spmd.DEFAULT_AXIS
    dist_opt = DistributedOptimizer(
        optimizer, compression=compression, op=op,
        backward_passes_per_step=backward_passes_per_step, axis_name=axis)

    def shard_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = dist_opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Replicated outputs: loss averaged across shards.
        loss = jax.lax.pmean(loss, axis)
        return params, opt_state, loss

    mapped = jax.shard_map(
        shard_step, mesh=mesh,
        in_specs=(P(), P(), P(axis)),
        out_specs=(P(), P(), P()),
        check_vma=False)
    donate_args = (0, 1) if donate else ()
    jitted = jax.jit(mapped, donate_argnums=donate_args)

    def init(params):
        return dist_opt.init(params)

    return jitted, init


def make_sharded_jit_step(loss_fn: Callable,
                          optimizer: optax.GradientTransformation,
                          donate: bool = True):
    """Compiler-driven DP: jit with replicated params + dim0-sharded batch;
    XLA inserts the gradient all-reduce (mean over the batch axis)."""
    mesh = _world_mesh()
    rep = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P(spmd.DEFAULT_AXIS))

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jitted = jax.jit(
        step,
        in_shardings=(rep, rep, sharded),
        out_shardings=(rep, rep, rep),
        donate_argnums=(0, 1) if donate else ())

    return jitted, optimizer.init


def metric_average(value, name: Optional[str] = None):
    """Average a host-side metric across ranks (reference: the
    ``metric_average`` helper in examples/pytorch/pytorch_mnist.py)."""
    from ..ops import api as eager
    size = basics.size()
    stacked = np.tile(np.asarray(value, dtype=np.float32).reshape(-1),
                      (size, 1))
    return float(np.asarray(eager.allreduce(
        stacked, op=AVERAGE, name=name or "metric")).reshape(-1)[0])
