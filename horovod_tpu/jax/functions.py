"""Parameter/object broadcast + state sync helpers.

Reference parity: ``hvd.broadcast_parameters``,
``hvd.broadcast_optimizer_state``, ``hvd.broadcast_object`` (
``horovod/torch/functions.py`` and ``horovod/tensorflow/functions.py``
``broadcast_variables`` / ``broadcast_object``).
"""

from __future__ import annotations

import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import basics
from ..common.process_sets import ProcessSet
from ..ops import api as eager


def _replicate(tree):
    """Place every leaf replicated over the world mesh (in-process mode)."""
    eng = basics._get_engine()
    mc = eng.collectives_for(0)
    sharding = mc._replicated_sharding
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), tree)


def broadcast_parameters(params, root_rank: int = 0,
                         process_set: Optional[ProcessSet] = None):
    """Make every rank hold root's parameter pytree.

    In-process SPMD world: the single controller owns one logical copy, so
    broadcast = replicate that copy across the mesh devices (an XLA
    broadcast transfer over ICI).  Multi-process world: per-leaf engine
    broadcast from ``root_rank`` — values come back per-process (device
    arrays on the eager payload plane); to feed them into the jit DP
    step afterwards, place them on the global mesh with
    ``data_parallel.replicate`` (see examples/multihost_pod_training.py).
    """
    if basics._controller_is_spmd():
        return _replicate(params)
    leaves, treedef = jax.tree.flatten(params)
    handles = [eager.broadcast_async(
        g, root_rank, name="broadcast_parameters/%d" % i,
        process_set=process_set) for i, g in enumerate(leaves)]
    return jax.tree.unflatten(treedef, [h.wait() for h in handles])


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              process_set: Optional[ProcessSet] = None):
    """Broadcast optax optimizer state (reference
    ``broadcast_optimizer_state``); same mechanics as parameters since
    optax state is a pytree."""
    return broadcast_parameters(opt_state, root_rank, process_set)


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> Any:
    """Pickle-broadcast an arbitrary python object from root to all ranks
    (reference ``hvd.broadcast_object``): the payload travels as a uint8
    tensor through the same collective path as tensors do."""
    if basics._controller_is_spmd():
        # Single controller: root's object IS the object; round-trip the
        # bytes through a device broadcast for wire parity.
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        size = basics.size()
        stacked = np.tile(payload, (size, 1))
        out = eager.broadcast(stacked, root_rank,
                              name=name or "broadcast_object",
                              process_set=process_set)
        return pickle.loads(np.asarray(out).tobytes())
    core = basics._get_tcp_core()
    return core.broadcast_object(obj, root_rank, name=name)


def allgather_object(obj: Any, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None):
    """Gather one python object per rank into a list (reference
    ``hvd.allgather_object``)."""
    if basics._controller_is_spmd():
        return [obj] * basics.size()
    core = basics._get_tcp_core()
    return core.allgather_object(obj, name=name)


def _election_key(record: dict, keys) -> tuple:
    """The deterministic, order-independent comparison key shared by
    every election in the tree: evidence fields descending in ``keys``
    order, ties broken by the LOWEST rank."""
    return tuple(int(record.get(k, 0)) for k in keys) + \
        (-int(record.get("rank", 0)),)


def elect_newest(records, keys=("commit_id",)) -> dict:
    """Pure election over already-gathered records (no transport): the
    record with the greatest ``keys`` evidence tuple wins, ties to the
    lowest rank.  The serving plane's in-process replica sets use this
    with ``keys=("version",)`` — "newest model version wins" — over
    records gathered from their own threads; multi-process worlds
    gather via :func:`elect_state_root` instead."""
    return max(records, key=lambda r: _election_key(r, keys))


def elect_state_root(record: dict, name: Optional[str] = None,
                     keys=("commit_id",)):
    """Allgather one small evidence record per rank and elect the
    max-evidence rank as the sync root, identically on every rank: the
    greatest ``keys`` tuple wins, ties go to the LOWEST rank (so a
    fresh world with no evidence anywhere degenerates to the
    reference's rank-0 broadcast).  Used by ``elastic.state`` with the
    default ``keys=("commit_id",)`` — our driver does not guarantee
    survivors keep low ranks after a reshuffle, so the root must be
    elected, not assumed — and by the serving plane's weight hot-swap
    with ``keys=("version", "commit_id")``: after a replica death the
    survivors elect the NEWEST MODEL VERSION (progress as tiebreak) so
    a mid-roll failure can never resurrect stale weights.

    Returns ``(root_record, all_records)``; the election key is order-
    independent, so any transport ordering of the gathered records
    yields the same winner everywhere."""
    records = allgather_object(record, name=name or "elastic.sync.election")
    root = elect_newest(records, keys)
    return root, records
