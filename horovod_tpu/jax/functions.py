"""Parameter/object broadcast + state sync helpers.

Reference parity: ``hvd.broadcast_parameters``,
``hvd.broadcast_optimizer_state``, ``hvd.broadcast_object`` (
``horovod/torch/functions.py`` and ``horovod/tensorflow/functions.py``
``broadcast_variables`` / ``broadcast_object``).
"""

from __future__ import annotations

import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import basics
from ..common.process_sets import ProcessSet
from ..ops import api as eager


def _replicate(tree):
    """Place every leaf replicated over the world mesh (in-process mode)."""
    eng = basics._get_engine()
    mc = eng.collectives_for(0)
    sharding = mc._replicated_sharding
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), tree)


def broadcast_parameters(params, root_rank: int = 0,
                         process_set: Optional[ProcessSet] = None):
    """Make every rank hold root's parameter pytree.

    In-process SPMD world: the single controller owns one logical copy, so
    broadcast = replicate that copy across the mesh devices (an XLA
    broadcast transfer over ICI).  Multi-process world: per-leaf engine
    broadcast from ``root_rank`` — values come back per-process (device
    arrays on the eager payload plane); to feed them into the jit DP
    step afterwards, place them on the global mesh with
    ``data_parallel.replicate`` (see examples/multihost_pod_training.py).
    """
    if basics._controller_is_spmd():
        return _replicate(params)
    leaves, treedef = jax.tree.flatten(params)
    handles = [eager.broadcast_async(
        g, root_rank, name="broadcast_parameters/%d" % i,
        process_set=process_set) for i, g in enumerate(leaves)]
    return jax.tree.unflatten(treedef, [h.wait() for h in handles])


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              process_set: Optional[ProcessSet] = None):
    """Broadcast optax optimizer state (reference
    ``broadcast_optimizer_state``); same mechanics as parameters since
    optax state is a pytree."""
    return broadcast_parameters(opt_state, root_rank, process_set)


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None) -> Any:
    """Pickle-broadcast an arbitrary python object from root to all ranks
    (reference ``hvd.broadcast_object``): the payload travels as a uint8
    tensor through the same collective path as tensors do."""
    if basics._controller_is_spmd():
        # Single controller: root's object IS the object; round-trip the
        # bytes through a device broadcast for wire parity.
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        size = basics.size()
        stacked = np.tile(payload, (size, 1))
        out = eager.broadcast(stacked, root_rank,
                              name=name or "broadcast_object",
                              process_set=process_set)
        return pickle.loads(np.asarray(out).tobytes())
    core = basics._get_tcp_core()
    return core.broadcast_object(obj, root_rank, name=name)


def allgather_object(obj: Any, name: Optional[str] = None,
                     process_set: Optional[ProcessSet] = None):
    """Gather one python object per rank into a list (reference
    ``hvd.allgather_object``)."""
    if basics._controller_is_spmd():
        return [obj] * basics.size()
    core = basics._get_tcp_core()
    return core.allgather_object(obj, name=name)


def elect_state_root(record: dict, name: Optional[str] = None):
    """Allgather one small commit-metadata record per rank and elect
    the max-progress rank as the state-sync root, identically on every
    rank: max ``commit_id`` wins, ties go to the LOWEST rank (so a
    fresh world with no commits anywhere degenerates to the
    reference's rank-0 broadcast).  Used by ``elastic.state`` — our
    driver does not guarantee survivors keep low ranks after a
    reshuffle, so the root must be elected, not assumed.

    Returns ``(root_record, all_records)``; the election key is order-
    independent, so any transport ordering of the gathered records
    yields the same winner everywhere."""
    records = allgather_object(record, name=name or "elastic.sync.election")
    root = max(records, key=lambda r: (int(r.get("commit_id", 0)),
                                       -int(r.get("rank", 0))))
    return root, records
