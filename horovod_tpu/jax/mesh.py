"""Mesh construction for pods and multislice.

Reference parity: the role of `MPIContext`'s communicator layout
(world/local/cross comms, `horovod/common/mpi/mpi_context.cc`) — on
TPU the "communicator" is the device mesh, and how devices map onto
its axes decides whether a collective rides ICI (fast, within a
slice) or DCN (across slices/hosts).

* ``create_mesh`` — single-slice: wraps
  ``jax.experimental.mesh_utils.create_device_mesh`` so axes follow
  the physical torus (XLA's collectives then use nearest-neighbor ICI
  rings).
* ``create_hybrid_mesh`` — multislice/multi-host: outer axes span DCN
  (data parallel across slices — the reference's "cross" dimension),
  inner axes span ICI within a slice ("local" dimension).  Mirrors
  the reference's hierarchical split: cheap collectives inside, one
  aggregated hop across.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["create_mesh", "create_hybrid_mesh"]


def create_mesh(axis_shapes: Sequence[int],
                axis_names: Sequence[str],
                devices: Optional[Sequence] = None) -> Mesh:
    """Physical-topology-aware mesh over one slice.

    ``create_mesh((4, 2), ("dp", "tp"))`` on 8 chips lays ``tp`` along
    contiguous ICI neighbors.  Falls back to a simple reshape when the
    platform exposes no topology (CPU test worlds).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(axis_shapes))
    if n != len(devices):
        raise ValueError("mesh shape %r needs %d devices, have %d"
                         % (tuple(axis_shapes), n, len(devices)))
    try:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_device_mesh(
            tuple(axis_shapes), devices=devices)
    except Exception:
        if devices[0].platform == "tpu":
            raise  # real topology IS available: the config is wrong
        # cpu/test world without topology info: plain reshape
        arr = np.asarray(devices).reshape(tuple(axis_shapes))
    return Mesh(arr, tuple(axis_names))


def create_hybrid_mesh(ici_axis_shapes: Sequence[int],
                       dcn_axis_shapes: Sequence[int],
                       axis_names: Sequence[str],
                       devices: Optional[Sequence] = None) -> Mesh:
    """Multislice mesh: ``dcn_axis_shapes`` (outer, slow network) ×
    ``ici_axis_shapes`` (inner, fast interconnect).

    ``create_hybrid_mesh((1, 8), (2, 1), ("dp", "mp"))`` over 2 slices
    of 8 chips: ``dp`` crosses slices on DCN, ``mp`` stays on ICI —
    shard model axes on ICI, replicate/batch across DCN (the
    reference's hierarchical-allreduce layout as a mesh).

    Axis ``i``'s global size is ``dcn[i] * ici[i]``; names apply to
    the combined axes.  Falls back to a reshape when slice topology is
    unavailable (CPU test worlds), preserving the outer/inner order.
    """
    if len(ici_axis_shapes) != len(dcn_axis_shapes) or \
            len(ici_axis_shapes) != len(axis_names):
        raise ValueError("ici/dcn shapes and names must align per axis")
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(ici_axis_shapes)) * int(np.prod(dcn_axis_shapes))
    if n != len(devices):
        raise ValueError("hybrid mesh needs %d devices, have %d"
                         % (n, len(devices)))
    try:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_axis_shapes), tuple(dcn_axis_shapes),
            devices=devices)
    except Exception:
        if devices[0].platform == "tpu":
            raise  # slice topology IS available: the shapes are wrong
        # cpu/test world without slice metadata: outer-major reshape,
        # then merge each (dcn, ici) axis pair
        outer = np.asarray(devices).reshape(
            tuple(dcn_axis_shapes) + tuple(ici_axis_shapes))
        k = len(ici_axis_shapes)
        perm = [v for i in range(k) for v in (i, k + i)]
        arr = outer.transpose(perm).reshape(
            tuple(d * i for d, i in zip(dcn_axis_shapes,
                                        ici_axis_shapes)))
    return Mesh(arr, tuple(axis_names))
