"""DistributedOptimizer / DistributedGradientTape equivalents for JAX.

Reference parity (``horovod/torch/optimizer.py`` ``_DistributedOptimizer``,
``horovod/tensorflow/__init__.py`` ``DistributedOptimizer`` /
``DistributedGradientTape``): wrap the local optimizer so gradients are
averaged across the data-parallel world before the update, with optional
fp16/bf16 wire compression, gradient predivision, local aggregation
(``backward_passes_per_step``), and process-set scoping.

JAX re-design: the optimizer is an ``optax.GradientTransformation``; the
distributed wrapper is *another* GradientTransformation that allreduces
gradients first — composable, functional, jit-friendly.  Inside a
mesh-sharded step the reduce is a fused ``lax.psum`` (XLA overlaps it with
backward compute the way Horovod overlapped NCCL with autograd); in the
multi-process world it routes through the eager engine instead.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from ..common.process_sets import ProcessSet
from ..ops.xla_ops import ADASUM, AVERAGE, SUM
from . import spmd
from .compression import Compression


class _AggState(NamedTuple):
    inner: Any
    accum: Any
    counter: jnp.ndarray


AxisSpec = Optional[Union[str, Tuple[str, str]]]


def allreduce_gradients(grads, op: str = AVERAGE,
                        axis_name: AxisSpec = spmd.DEFAULT_AXIS,
                        compression=Compression.none,
                        process_set: Optional[ProcessSet] = None):
    """Average a gradient pytree across the world.

    ``axis_name`` set (inside shard_map/pjit): fused in-program psum; a
    ``(inner, outer)`` PAIR of axis names selects the hierarchical
    reduce over a hybrid mesh (reduce-scatter on ICI, cross-slice
    allreduce of the shards on DCN, all-gather back — the reference's
    ``HOROVOD_HIERARCHICAL_ALLREDUCE``).
    ``axis_name=None`` (eager, multi-process tcp world): engine allreduce
    per leaf, fused by the background cycle.
    """
    from .compression import check_reduce_safe
    check_reduce_safe(compression, "allreduce_gradients")
    if isinstance(axis_name, (tuple, list)):
        if compression is not Compression.none:
            raise ValueError(
                "compression is not supported on the hierarchical "
                "reduce path")
        inner, outer = axis_name
        return spmd.hierarchical_allreduce_pytree(
            grads, op=op, inner_axis=inner, outer_axis=outer)
    if axis_name is not None:
        return spmd.allreduce_pytree(grads, op=op, axis_name=axis_name,
                                     compression=compression)
    from ..ops import api as eager
    leaves, treedef = jax.tree.flatten(grads)
    handles = []
    for i, g in enumerate(leaves):
        wire, ctx = compression.compress(g)
        handles.append((eager.allreduce_async(
            wire, op=op, name="DistributedOptimizer.gradient/%d" % i,
            process_set=process_set), ctx))
    outs = [compression.decompress(h.wait(), ctx) for h, ctx in handles]
    return jax.tree.unflatten(treedef, outs)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: str = AVERAGE,
                         gradient_predivide_factor: float = 1.0,
                         axis_name: AxisSpec = spmd.DEFAULT_AXIS,
                         process_set: Optional[ProcessSet] = None
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer with cross-replica gradient reduction.

    Mirrors the reference constructor surface: ``compression``,
    ``backward_passes_per_step`` (local aggregation: gradients accumulate
    locally N steps, reduce once), ``op`` (Average/Sum/Adasum),
    ``gradient_predivide_factor`` (pre/post scaling split).
    ``named_parameters`` is accepted for API compatibility and unused (JAX
    pytrees are already named).
    """
    if gradient_predivide_factor != 1.0 and op != AVERAGE:
        raise ValueError(
            "gradient_predivide_factor only applies to Average, as in the "
            "reference")
    if op == ADASUM and axis_name is not None:
        raise ValueError(
            "Adasum runs through the eager engine (axis_name=None)")
    n_agg = int(backward_passes_per_step)
    if n_agg < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    pre = 1.0 / gradient_predivide_factor
    post = gradient_predivide_factor

    def reduce_now(grads):
        if op == AVERAGE and gradient_predivide_factor != 1.0:
            scaled = jax.tree.map(
                lambda g: g * jnp.asarray(pre, g.dtype), grads)
            red = allreduce_gradients(scaled, op=SUM, axis_name=axis_name,
                                      compression=compression,
                                      process_set=process_set)
            if isinstance(axis_name, (tuple, list)):
                denom = (spmd.size(axis_name[0])
                         * spmd.size(axis_name[1]))
            elif axis_name is not None:
                denom = spmd.size(axis_name)
            else:
                denom = (process_set.size() if process_set
                         else _world())
            return jax.tree.map(
                lambda g: g * jnp.asarray(post / denom, g.dtype), red)
        return allreduce_gradients(grads, op=op, axis_name=axis_name,
                                   compression=compression,
                                   process_set=process_set)

    def _world():
        from ..common import basics
        return basics.size()

    def init_fn(params):
        inner = optimizer.init(params)
        if n_agg == 1:
            return _AggState(inner, None, jnp.zeros((), jnp.int32))
        accum = jax.tree.map(jnp.zeros_like, params)
        return _AggState(inner, accum, jnp.zeros((), jnp.int32))

    def update_fn(grads, state: _AggState, params=None, **extra):
        if n_agg == 1:
            reduced = reduce_now(grads)
            updates, inner = optimizer.update(reduced, state.inner, params,
                                              **extra)
            return updates, _AggState(inner, None, state.counter + 1)
        # Local aggregation (backward_passes_per_step > 1): accumulate
        # locally, reduce+apply every n_agg-th call, no-op updates between.
        accum = jax.tree.map(lambda a, g: a + g, state.accum, grads)
        counter = state.counter + 1
        do_step = counter % n_agg == 0

        def apply_branch(operand):
            acc, inner = operand
            avg = jax.tree.map(lambda a: a / n_agg, acc)
            reduced = reduce_now(avg)
            updates, inner2 = optimizer.update(reduced, inner, params,
                                               **extra)
            return updates, jax.tree.map(jnp.zeros_like, acc), inner2

        def skip_branch(operand):
            acc, inner = operand
            zeros = jax.tree.map(jnp.zeros_like, acc)
            return zeros, acc, inner

        if axis_name is None:
            # Eager world: python control flow is fine.
            if int(counter) % n_agg == 0:
                updates, accum, inner = apply_branch((accum, state.inner))
            else:
                updates, accum, inner = skip_branch((accum, state.inner))
        else:
            updates, accum, inner = jax.lax.cond(
                do_step, apply_branch, skip_branch, (accum, state.inner))
        return updates, _AggState(inner, accum, counter)

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


class DistributedGradientTape:
    """Reference ``hvd.DistributedGradientTape`` analog for JAX.

    Wraps a scalar loss function; ``gradient(params, *args)`` returns
    world-averaged gradients.  Use inside a mesh-sharded jitted step::

        tape = hvd.DistributedGradientTape(loss_fn)
        loss, grads = tape.gradient(params, batch)
    """

    def __init__(self, loss_fn, compression=Compression.none,
                 op: str = AVERAGE,
                 axis_name: AxisSpec = spmd.DEFAULT_AXIS,
                 process_set: Optional[ProcessSet] = None):
        self._grad_fn = jax.value_and_grad(loss_fn)
        self.compression = compression
        self.op = op
        self.axis_name = axis_name
        self.process_set = process_set

    def gradient(self, params, *args, **kwargs):
        loss, grads = self._grad_fn(params, *args, **kwargs)
        grads = allreduce_gradients(
            grads, op=self.op, axis_name=self.axis_name,
            compression=self.compression, process_set=self.process_set)
        return loss, grads
