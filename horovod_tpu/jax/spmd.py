"""In-program (SPMD) collectives: the performance path.

These are the collectives you call *inside* a jitted, mesh-sharded train
step (``jax.shard_map`` / pjit).  XLA lowers them to ICI/DCN collective HLO
and fuses them with surrounding compute — the TPU equivalent of the
reference's NCCL-on-stream hot path (``ops/nccl_operations.cc``), with the
compiler doing the overlap that Horovod did with stream events.

The op surface mirrors the eager API (Sum/Average/Min/Max, prescale/
postscale, compression) so a reference user can move a call inside jit
without relearning semantics.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.xla_ops import AVERAGE, MAX, MIN, PRODUCT, SUM
from .compression import Compression

DEFAULT_AXIS = "hvd"


def size(axis_name: str = DEFAULT_AXIS):
    """World size along the DP axis (usable inside jit)."""
    return lax.axis_size(axis_name)


def rank(axis_name: str = DEFAULT_AXIS):
    """This shard's index along the DP axis (usable inside jit)."""
    return lax.axis_index(axis_name)


def allreduce(x, op: str = AVERAGE, axis_name: str = DEFAULT_AXIS,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              compression=Compression.none):
    """Cross-replica reduce inside an SPMD program."""
    from .compression import check_reduce_safe
    check_reduce_safe(compression, "spmd.allreduce")
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype=x.dtype)
    wire, ctx = compression.compress(x)
    if op in (SUM, AVERAGE):
        red = lax.psum(wire, axis_name)
        if op == AVERAGE:
            n = lax.axis_size(axis_name)
            red = (red / n).astype(wire.dtype)
    elif op == MIN:
        red = lax.pmin(wire, axis_name)
    elif op == MAX:
        red = lax.pmax(wire, axis_name)
    elif op == PRODUCT:
        red = jnp.prod(lax.all_gather(wire, axis_name), axis=0)
    else:
        raise NotImplementedError(op)
    out = compression.decompress(red, ctx)
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    return out


def hierarchical_allreduce(x, op: str = AVERAGE,
                           inner_axis: str = "ici",
                           outer_axis: str = "dcn"):
    """The reference's ``HOROVOD_HIERARCHICAL_ALLREDUCE``
    (``ops/nccl_operations.cc``: NCCL reduce-scatter intra-node, MPI
    allreduce across, NCCL allgather back) as mesh collectives:
    ``psum_scatter`` over the fast inner axis (ICI within a slice),
    ``psum`` of the 1/inner-sized shards over the slow outer axis
    (DCN across slices), ``all_gather`` back over inner.  Only
    ``1/inner_size`` of the bytes ever cross DCN.

    Use with a ``create_hybrid_mesh`` whose DP dimension is split into
    (outer=dcn, inner=ici) axes; for Sum/Average only (like the
    reference's hierarchical path).
    """
    if op not in (SUM, AVERAGE):
        raise NotImplementedError(
            "hierarchical allreduce supports Sum/Average (reference "
            "parity: the NCCL+MPI hierarchical path was Sum-based)")
    inner = lax.axis_size(inner_axis)
    flat = jnp.ravel(x)
    pad = (-flat.shape[0]) % inner
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)])
    s = lax.psum_scatter(flat, inner_axis, scatter_dimension=0,
                         tiled=True)
    s = lax.psum(s, outer_axis)
    if op == AVERAGE:
        # Divide the 1/inner-sized shard BEFORE the gather: inner-times
        # less work, and the division cannot fuse across the collective.
        n = inner * lax.axis_size(outer_axis)
        s = (s / n).astype(flat.dtype)
    out = lax.all_gather(s, inner_axis, tiled=True)
    return out[:x.size].reshape(x.shape).astype(x.dtype)


def _fused_reduce(xs: Sequence, reduce_flat):
    """Flatten-concat-reduce-split fusion shared by the grouped and
    hierarchical paths: one large collective instead of one per tensor
    (the explicit analog of the engine's fusion buffer)."""
    flats = [jnp.ravel(x) for x in xs]
    sizes = [f.shape[0] for f in flats]
    fused = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
    red = reduce_flat(fused)
    outs, off = [], 0
    for x, n in zip(xs, sizes):
        outs.append(red[off:off + n].reshape(x.shape).astype(x.dtype))
        off += n
    return outs


def hierarchical_allreduce_pytree(tree, op: str = AVERAGE,
                                  inner_axis: str = "ici",
                                  outer_axis: str = "dcn"):
    """Fused hierarchical reduce of a pytree: one concat, one
    RS-inner/AR-outer/AG-inner round, one split."""
    leaves, treedef = jax.tree.flatten(tree)
    outs = _fused_reduce(
        leaves, lambda fused: hierarchical_allreduce(
            fused, op=op, inner_axis=inner_axis, outer_axis=outer_axis))
    return jax.tree.unflatten(treedef, outs)


def grouped_allreduce(xs: Sequence, op: str = AVERAGE,
                      axis_name: str = DEFAULT_AXIS,
                      compression=Compression.none):
    """Reduce a list of tensors as one fused payload (one large
    all-reduce — see _fused_reduce)."""
    return _fused_reduce(
        xs, lambda fused: allreduce(fused, op=op, axis_name=axis_name,
                                    compression=compression))


def allreduce_pytree(tree, op: str = AVERAGE, axis_name: str = DEFAULT_AXIS,
                     compression=Compression.none):
    """Fused reduce of every leaf of a pytree (gradients, metrics...)."""
    leaves, treedef = jax.tree.flatten(tree)
    return jax.tree.unflatten(
        treedef, grouped_allreduce(leaves, op=op, axis_name=axis_name,
                                   compression=compression))


def allgather(x, axis_name: str = DEFAULT_AXIS, tiled: bool = True):
    """Gather shards along dim 0 (reference allgather semantics)."""
    return lax.all_gather(x, axis_name, tiled=tiled)


def broadcast(x, root_rank: int = 0, axis_name: str = DEFAULT_AXIS):
    """Replace every shard's value with ``root_rank``'s."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def alltoall(x, axis_name: str = DEFAULT_AXIS, split_axis: int = 0,
             concat_axis: int = 0):
    """Exchange: chunk j along ``split_axis`` goes to rank j."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def reducescatter(x, op: str = SUM, axis_name: str = DEFAULT_AXIS,
                  scatter_axis: int = 0):
    """Reduce then keep this rank's dim-0 shard."""
    out = lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                           tiled=True)
    if op == AVERAGE:
        out = (out / lax.axis_size(axis_name)).astype(out.dtype)
    return out


def ppermute(x, perm, axis_name: str = DEFAULT_AXIS):
    """Neighbor exchange (``collective-permute``): the ring primitive used
    by ring attention / pipeline parallelism.  Not in the reference's op
    set — exposed because on TPU it is THE ICI-topology-native collective."""
    return lax.ppermute(x, axis_name, perm=perm)


def barrier(axis_name: str = DEFAULT_AXIS):
    """In-program barrier: a 1-element psum data dependency."""
    return lax.psum(jnp.ones((), jnp.int32), axis_name)
