"""Cross-replica (synchronized) batch normalization.

Reference parity: ``horovod/torch/sync_batch_norm.py`` (``SyncBatchNorm``:
allgather of per-rank mean/var, reduced to global statistics).  TPU-native
design: the statistics reduction is a ``lax.psum`` inside the jitted step,
which XLA fuses with the surrounding normalization math — no separate
allgather round trips.

Two surfaces:

* ``sync_batch_norm_stats(x, axis_name)`` — functional: global (mean, var)
  over both the local batch axes and the cross-replica axis.
* ``SyncBatchNorm`` — a flax ``nn.Module`` drop-in wrapping
  ``nn.BatchNorm`` with the cross-replica axis bound.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from . import spmd


def sync_batch_norm_stats(x, axis_name: str = spmd.DEFAULT_AXIS,
                          reduce_axes=None):
    """Global mean/variance across local reduce axes + the replica axis.

    Uses the sum/sum-of-squares formulation so a single fused psum pair
    carries both moments (the reference gathers count/mean/var per rank).
    """
    if reduce_axes is None:
        reduce_axes = tuple(range(x.ndim - 1))
    n_local = 1
    for a in reduce_axes:
        n_local *= x.shape[a]
    s1 = jnp.sum(x, axis=reduce_axes)
    s2 = jnp.sum(jnp.square(x), axis=reduce_axes)
    count = jnp.asarray(n_local, dtype=x.dtype)
    s1 = lax.psum(s1, axis_name)
    s2 = lax.psum(s2, axis_name)
    n = lax.psum(count, axis_name)
    mean = s1 / n
    var = s2 / n - jnp.square(mean)
    return mean, jnp.maximum(var, 0.0)


def sync_batch_norm_apply(x, scale=None, bias=None, eps: float = 1e-5,
                          axis_name: str = spmd.DEFAULT_AXIS):
    """Normalize with cross-replica statistics; affine if scale/bias given."""
    mean, var = sync_batch_norm_stats(x, axis_name)
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv
    if scale is not None:
        y = y * scale
    if bias is not None:
        y = y + bias
    return y


try:
    import flax.linen as nn

    class SyncBatchNorm(nn.BatchNorm):
        """Drop-in flax BatchNorm synchronized across the DP axis.

        flax's BatchNorm already supports cross-replica reduction via
        ``axis_name``; this subclass pins it to the framework's DP axis so
        user code matches the reference's ``hvd.SyncBatchNorm`` one-liner.
        """

        axis_name: Optional[str] = spmd.DEFAULT_AXIS

except ImportError:  # flax is baked into the target image; belt-and-braces
    SyncBatchNorm = None
