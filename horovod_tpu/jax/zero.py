"""ZeRO-1: optimizer-state sharding over the data-parallel axis.

Beyond-reference extension (SURVEY.md §2.5 lists ZeRO as absent
upstream, with ``reducescatter``/``allgather`` as the primitives users
would build it from — this module builds it).  Memory per device for
optimizer state (and the fp32 work the update does) drops by the DP
world size:

    grads --reducescatter--> my 1/n shard (mean-reduced)
    optimizer.update on the shard (1/n of the state)
    params --allgather-- updated shards

With Adam the optimizer state (mu+nu = 2 of the 3 training-state
units) shards n ways: total training-state HBM drops by (2 - 2/n)/3 —
50% at n=4, approaching 2/3 as n grows.  XLA overlaps the
reduce-scatter with backward compute like any collective.

ONLY ELEMENTWISE optimizers are exact under ZeRO-1 sharding (adam,
sgd, rmsprop, adagrad, ...): each rank updates its flat shard
independently.  Optimizers that couple elements across the whole tree
— ``clip_by_global_norm``, LAMB/LARS trust ratios, Adafactor's
factored second moment — would compute their norms over 1/n of the
data and silently diverge; do not use them here.

Usage (mirrors ``make_data_parallel_step``)::

    step, init = make_zero1_step(loss_fn, optax.adam(3e-4))
    params = hvd.replicate(params)
    opt_state = init(params)              # sharded along the world axis
    params, opt_state, loss = step(params, opt_state,
                                   hvd.shard_batch(batch))
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from . import spmd
from .data_parallel import _world_mesh
from ..ops.xla_ops import AVERAGE

__all__ = ["make_zero1_step"]


def _pad_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def _flat_pad(x, n):
    flat = x.reshape(-1)
    padded = _pad_to(flat.shape[0], n)
    if padded != flat.shape[0]:
        flat = jnp.concatenate(
            [flat, jnp.zeros(padded - flat.shape[0], flat.dtype)])
    return flat


def make_zero1_step(loss_fn: Callable,
                    optimizer: optax.GradientTransformation,
                    axis_name: str = spmd.DEFAULT_AXIS):
    """Build ``(step, init)`` with ZeRO-1 sharded optimizer state.

    ``loss_fn(params, batch) -> scalar`` on the local batch shard.
    Call ``init(params)`` (params replicated) once — it derives the
    state sharding and compiles the step — then
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``;
    params stay replicated, optimizer state lives sharded.  Params and
    opt state are donated each step: keep using the returned values.

    ``optimizer`` must be elementwise (see module docstring).
    """
    mesh = _world_mesh()
    n = mesh.shape[axis_name]

    def shard_params_local(params, idx):
        def leaf(x):
            flat = _flat_pad(x, n)
            per = flat.shape[0] // n
            return jax.lax.dynamic_slice(flat, (idx * per,), (per,))
        return jax.tree.map(leaf, params)

    def local_init(params):
        idx = jax.lax.axis_index(axis_name)
        return optimizer.init(shard_params_local(params, idx))

    def local_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss, axis_name)
        idx = jax.lax.axis_index(axis_name)

        def rs(g):
            # mean-reduce + scatter my 1/n of every gradient
            return spmd.reducescatter(_flat_pad(g, n), op=AVERAGE,
                                      axis_name=axis_name)

        grad_shards = jax.tree.map(rs, grads)
        param_shards = shard_params_local(params, idx)
        updates, opt_state = optimizer.update(grad_shards, opt_state,
                                              param_shards)
        new_shards = optax.apply_updates(param_shards, updates)

        def ag(shard, like):
            full = spmd.allgather(shard, axis_name=axis_name)
            return full[:like.size].reshape(like.shape) \
                .astype(like.dtype)

        params = jax.tree.map(ag, new_shards, params)
        return params, opt_state, loss

    compiled = {}

    def init(params):
        # state sharding: array leaves are per-rank shards (dim 0
        # concatenates across the axis); scalar leaves (step counters)
        # are replicated
        state_shapes = jax.eval_shape(
            lambda p: optimizer.init(shard_params_local(p, 0)), params)
        state_spec = jax.tree.map(
            lambda s: P(axis_name) if getattr(s, "ndim", 0) >= 1
            else P(), state_shapes)
        mapped_init = jax.shard_map(
            local_init, mesh=mesh, in_specs=(P(),),
            out_specs=state_spec, check_vma=False)
        mapped_step = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), state_spec, P(axis_name)),
            out_specs=(P(), state_spec, P()), check_vma=False)
        compiled["step"] = jax.jit(mapped_step, donate_argnums=(0, 1))
        return jax.jit(mapped_init)(params)

    def step(params, opt_state, batch):
        if "step" not in compiled:
            raise RuntimeError("call init(params) before step(...)")
        return compiled["step"](params, opt_state, batch)

    return step, init
