"""ZeRO-1/2/3: sharded training state over the data-parallel axis.

Beyond-reference extension (SURVEY.md §2.5 lists ZeRO as absent
upstream, with ``reducescatter``/``allgather`` as the primitives users
would build it from — this module builds all three stages of
Rajbhandari et al., arXiv:1910.02054, on them).  The three
training-state units (params Ψ, gradients Ψ, optimizer state ~2Ψ for
Adam) shard progressively across the DP world of size n:

    stage 1   optimizer state sharded             →  2Ψ + 2Ψ/n
    stage 2   + persistent gradient shards        →   Ψ + 3Ψ/n
    stage 3   + parameter shards (gather-on-use)  →        4Ψ/n

(The gradient unit is persistent whenever gradient accumulation is on
— the normal large-model regime; ``benchmarks/zero_mem.py`` measures
exactly these rows.)

Communication shapes::

    zero-1  grads --reducescatter--> shard, update, params --allgather
            (accum_steps > 1: full-grad accumulator stays REPLICATED —
            the paper's stage-1 gradient layout)
    zero-2  grads --reducescatter--> SHARD accumulator (the persistent
            gradient state is 1/n; the full gradient tree is transient
            inside one backward), update at the boundary, allgather
    zero-3  params --allgather-on-demand--> forward/backward, grads
            --reducescatter--> shard, update shards, NO param allgather
            (the next step re-gathers; the master copy is the shard)

**Quantized DCN leg** (multihost worlds): the new cross-host
reducescatter/allgather volume routes through the r12 wire codecs
(``HOROVOD_CROSS_HOST_COMPRESSION`` = fp16/bf16/int8/fp8, or the
``wire=`` build argument).  Over the proc×local mesh the in-host leg
(ICI) stays full precision; only the cross-host exchange carries the
narrow wire.  int8/fp8 gradient reduce-scatter runs with per-tensor-name
error-feedback residuals carried in the step state (donated each step),
so the quantization error telescopes instead of biasing the optimizer;
zero-3's parameter gather-on-demand quantizes the *transient* gathered
copy only — the full-precision master is the shard, so gather noise
never accumulates.  Zero-2's parameter allgather updates the replicated
MASTER copy and therefore stays full precision (quantizing it would
integrate wire noise into the weights with no residual to correct it).
Per-(op, size_class) engagement rides the r14 ``PlanController`` when
the plan plane is active, so routing stays SPMD-identical across
members by construction.

ONLY ELEMENTWISE optimizers are exact under ZeRO sharding (adam, sgd,
rmsprop, adagrad, ...): each rank updates its flat shard
independently.  Optimizers that couple elements across the whole tree
— ``clip_by_global_norm``, LAMB/LARS trust ratios, Adafactor's
factored second moment — would compute their statistics over 1/n of
the data and silently diverge; the builders detect the known optax
offenders at build time and refuse loudly (see
:func:`_assert_elementwise`).

Usage (mirrors ``make_data_parallel_step``)::

    step, init = make_zero2_step(loss_fn, optax.adam(3e-4))
    params = hvd.replicate(params)
    carry = init(params)
    params, carry, loss = step(params, carry, hvd.shard_batch(batch))

    step3, init3, gather3 = make_zero3_step(loss_fn, optax.adam(3e-4))
    state = init3(hvd.replicate(params))     # params now live sharded
    state, loss = step3(state, hvd.shard_batch(batch))
    full = gather3(state)                    # eval/export only

Model-parallel composition: pass your own ``mesh`` (e.g. a
``create_hybrid_mesh``) plus the DP ``axes`` tuple; the loss_fn may
use ``jax/spmd.py`` collectives over the remaining model axes — ZeRO
shards along ``axes`` only.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import spmd
from .data_parallel import _multihost, _world_mesh
from ..ops.xla_ops import AVERAGE

LOG = logging.getLogger("horovod_tpu.jax.zero")

__all__ = ["make_zero1_step", "make_zero2_step", "make_zero3_step",
           "make_zero_step", "zero_stage_from_env"]

#: Axis names of the proc×local mesh the multihost builders construct
#: (the DCN leg runs over PROC_AXIS, the in-host ICI leg over
#: LOCAL_AXIS).
PROC_AXIS = "hvd_proc"
LOCAL_AXIS = "hvd_local"


def _pad_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def _flat_pad(x, n):
    flat = x.reshape(-1)
    padded = _pad_to(flat.shape[0], n)
    if padded != flat.shape[0]:
        flat = jnp.concatenate(
            [flat, jnp.zeros(padded - flat.shape[0], flat.dtype)])
    return flat


def _shard_leaf(x, n, idx):
    """Member ``idx``'s flat 1/n shard of one leaf — THE canonical
    shard slice (chunk ``idx`` of the padded flat vector) every stage
    and every wire path shares, so a stage change or codec toggle
    never reinterprets persisted state."""
    flat = _flat_pad(x, n)
    per = flat.shape[0] // n
    return lax.dynamic_slice(flat, (idx * per,), (per,))


def _shard_tree(params, n, idx):
    return jax.tree.map(lambda x: _shard_leaf(x, n, idx), params)


# -- elementwise guard ------------------------------------------------------

# Known non-elementwise optax transforms: the update-fn qualnames that
# appear in the closure graph of any optimizer built from them, mapped
# to WHY each one silently diverges under a flat 1/n shard.
_NON_ELEMENTWISE = {
    "clip_by_global_norm":
        "clip_by_global_norm computes the GLOBAL gradient norm over "
        "the whole tree; each rank would clip by the norm of its 1/n "
        "shard and the updates silently diverge across ranks",
    "scale_by_trust_ratio":
        "LAMB/LARS trust ratios divide per-layer parameter and update "
        "norms; a flat 1/n shard mixes and truncates layers, so the "
        "ratio is computed over the wrong span — silent divergence",
    "scale_by_factored_rms":
        "Adafactor's factored second moment needs each leaf's full "
        "matrix shape for its row/column statistics; a flat shard "
        "destroys the factorization — silent divergence",
}


def _closure_qualnames(roots, limit: int = 512):
    """Qualnames of every function reachable from ``roots`` through
    closures, __wrapped__ chains, and GradientTransformation-shaped
    members (``optax.chain`` holds its stages in closure cells)."""
    seen, out, stack = set(), [], list(roots)
    while stack and len(seen) < limit:
        obj = stack.pop()
        if id(obj) in seen:
            continue
        seen.add(id(obj))
        if callable(obj) and hasattr(obj, "__qualname__"):
            out.append(obj.__qualname__)
            stack.extend(getattr(obj, "__closure__", None) and
                         [c.cell_contents for c in obj.__closure__
                          if _cell_ok(c)] or [])
            wrapped = getattr(obj, "__wrapped__", None)
            if wrapped is not None:
                stack.append(wrapped)
        elif isinstance(obj, (tuple, list)):
            stack.extend(obj)
        elif hasattr(obj, "init") and hasattr(obj, "update"):
            stack.extend([obj.init, obj.update])
    return out


def _cell_ok(cell) -> bool:
    try:
        cell.cell_contents
    except ValueError:  # empty cell
        return False
    return True


def _assert_elementwise(optimizer, where: str):
    """Refuse the known non-elementwise optax transforms LOUDLY at
    build time: under ZeRO sharding they would compute tree-coupled
    statistics over 1/n of the elements and diverge silently — the
    exact failure mode the module docstring warns about."""
    for qn in _closure_qualnames((optimizer.init, optimizer.update)):
        for marker, why in _NON_ELEMENTWISE.items():
            if marker in qn:
                raise ValueError(
                    "%s: optimizer contains the non-elementwise optax "
                    "transform %r, which is NOT exact under ZeRO "
                    "sharding: %s.  Use an elementwise optimizer "
                    "(adam, sgd, rmsprop, adagrad, ...) or apply the "
                    "coupled transform outside the sharded step."
                    % (where, marker, why))


# -- mesh / axes resolution -------------------------------------------------

_zero_mesh_cache = {}


def _zero_mesh_and_axes(axis_name, mesh, axes):
    """(mesh, axes) for a ZeRO step: a caller-provided mesh wins
    (model-parallel composition — ``axes`` names its DP dims);
    multihost worlds get the proc×local 2-D mesh (the DCN leg is
    addressable as PROC_AXIS); in-process worlds use the engine's
    flat mesh."""
    if mesh is not None:
        use = tuple(axes) if axes else (axis_name,)
        for a in use:
            if a not in mesh.shape:
                raise ValueError("axis %r not in mesh axes %s"
                                 % (a, tuple(mesh.shape)))
        return mesh, use
    if axes:
        raise ValueError("axes= requires an explicit mesh=")
    if _multihost():
        flat = _world_mesh()  # validates per-process homogeneity
        devs = flat.devices.reshape(-1)
        nproc = jax.process_count()
        local = devs.size // nproc
        key = tuple((d.process_index, d.id) for d in devs)
        cached = _zero_mesh_cache.get(key)
        if cached is None:
            _zero_mesh_cache.clear()
            cached = Mesh(devs.reshape(nproc, local),
                          (PROC_AXIS, LOCAL_AXIS))
            _zero_mesh_cache[key] = cached
        return cached, (PROC_AXIS, LOCAL_AXIS)
    return _world_mesh(), (axis_name,)


def _axes_arg(axes):
    """The axis_name argument shape lax collectives want."""
    return axes if len(axes) > 1 else axes[0]


def _linear_index(axes, sizes):
    """This shard's linearized index over ``axes`` (row-major, usable
    inside jit)."""
    idx = lax.axis_index(axes[0])
    for a, s in zip(axes[1:], sizes[1:]):
        idx = idx * s + lax.axis_index(a)
    return idx


# -- cross-host wire codec --------------------------------------------------

def _resolve_wire(wire: Optional[str]):
    """The DCN-leg codec: (kind, impl, label) or None.  ``wire=None``
    reads ``HOROVOD_CROSS_HOST_COMPRESSION`` (the r12 env — one knob
    governs the eager hier legs AND the ZeRO in-program legs).  Name
    validation, the accepted codec set, and the loud fp8→bf16
    fallback all live in the ENGINE's resolver — one parser, so the
    two planes can never drift on what the knob means."""
    name = wire if wire is not None else os.environ.get(
        "HOROVOD_CROSS_HOST_COMPRESSION", "none")
    from ..ops.multihost import _resolve_codec
    codec = _resolve_codec((name or "none").strip().lower())
    if codec is None:
        return None
    if codec.kind == "cast":
        return ("cast", codec.wire, codec.name)
    if codec.wire == np.dtype(np.int8):
        from .compression import Int8Quantizer
        return ("quant", Int8Quantizer, codec.name)
    from .compression import ScaledFP8Quantizer
    return ("quant", ScaledFP8Quantizer, codec.name)


def _leg_engages(op: str, nbytes: int, n_procs: int, n_local: int) -> bool:
    """Per-(op, size_class) codec engagement under the r14
    PlanController when the plan plane is active for this topology —
    SPMD-identical on every member because the plan itself is (shared
    cache blob / KV adoption).  No controller = engage (the env codec
    asked for it)."""
    try:
        from ..utils import plancache
        kind = jax.devices()[0].device_kind
        ctl = plancache.controller_for(n_procs, n_local, kind)
    except Exception:  # noqa: BLE001 — plan plane absent/uninitialized
        return True
    if ctl is None:
        return True
    from ..ops.multihost import _pow2_class
    return ctl.route(op, _pow2_class(nbytes), True)[1]


def _leg_codec(wire: Optional[str], axes, sizes):
    """The builder's resolved DCN codec, honest about engagement: on a
    mesh with no cross-host leg (flat axis, or a 1-proc 2-level mesh)
    an EXPLICIT ``wire=`` is refused loudly — the caller asked for
    compression that can never engage, and silently training full
    precision poisons any comparison (zero_mem refuses the same way) —
    while an env-derived codec merely warns, matching the engine's
    behavior when the hier plane is unavailable."""
    if len(axes) == 2 and sizes[0] > 1:
        return _resolve_wire(wire)
    explicit = wire is not None and \
        (wire or "none").strip().lower() not in ("", "none")
    if explicit:
        raise ValueError(
            "wire=%r needs a 2-level proc x local mesh with >1 "
            "process-level groups (got axes=%s sizes=%s): there is no "
            "cross-host leg for the codec to ride, and silently "
            "training full precision would misattribute the results"
            % (wire, tuple(axes), tuple(sizes)))
    env = os.environ.get("HOROVOD_CROSS_HOST_COMPRESSION", "none")
    if (env or "none").strip().lower() not in ("", "none"):
        LOG.warning(
            "HOROVOD_CROSS_HOST_COMPRESSION=%s is set but this ZeRO "
            "mesh has no cross-host leg (axes=%s sizes=%s); the "
            "in-program legs stay full precision", env, tuple(axes),
            tuple(sizes))
    return None


# -- hierarchical collectives (traced) --------------------------------------
#
# Canonical shard order shared by EVERY path (plain and wire): the flat
# padded vector cuts into n = P*L chunks and device (p, l) owns chunk
# p*L + l — identical to lax.psum_scatter/all_gather tiled over the
# (PROC_AXIS, LOCAL_AXIS) tuple, so optimizer-state shards mean the
# same thing whether or not the codec engages (a codec toggle or a
# restore never reinterprets state).

def _rs_world(flat, axes, n):
    """Full-precision mean reduce-scatter over all of ``axes``."""
    s = lax.psum_scatter(flat, _axes_arg(axes), scatter_dimension=0,
                         tiled=True)
    return (s / n).astype(flat.dtype)


def _ag_world(shard, axes):
    """Full-precision allgather over all of ``axes``."""
    return lax.all_gather(shard, _axes_arg(axes), tiled=True)


def _rs_hier_wire(flat, paxis, laxis, pn, ln, codec, residual):
    """Mean reduce-scatter with the cross-host leg on the narrow wire:
    in-host psum_scatter full precision (ICI), then the host-partial
    chunks quantize/cast and cross DCN as an all_to_all exchange of
    [pn, S] wire rows + per-row f32 scales (the 1-bit-Adam compressed
    reduce-scatter shape), dequant-summed far side.  Returns
    (shard, new_residual) — the residual is this member's
    error-feedback state for this tensor (quant codecs only)."""
    s = flat.shape[0] // (pn * ln)
    # View [P, L, S] → local-major rows so the in-host scatter hands
    # local device l the [P, S] partial of every chunk (·, l).
    g2 = flat.reshape(pn, ln, s).transpose(1, 0, 2).reshape(ln, pn * s)
    chunk = lax.psum_scatter(g2, laxis, scatter_dimension=0, tiled=True)
    rows = chunk.reshape(pn, s)
    kind = codec[0]
    new_res = None
    if kind == "cast":
        wx = lax.all_to_all(rows.astype(codec[1]), paxis, 0, 0,
                            tiled=True)
        deq = wx.astype(jnp.float32)
    else:
        quantizer = codec[1]
        if residual is not None:
            rows = rows + residual.astype(rows.dtype)
        wire, ctx = quantizer.compress(rows)
        if residual is not None:
            sent = quantizer.decompress(wire, ctx)
            new_res = (rows - sent.astype(rows.dtype))
        wx = lax.all_to_all(wire, paxis, 0, 0, tiled=True)
        sx = lax.all_to_all(ctx[0], paxis, 0, 0, tiled=True)
        deq = wx.astype(jnp.float32) * sx
    shard = jnp.sum(deq, axis=0) / (pn * ln)
    return shard.astype(flat.dtype), new_res


def _ag_hier_wire(shard, paxis, laxis, codec):
    """Allgather with the cross-host leg on the narrow wire: my [S]
    chunk quantizes/casts, crosses DCN once (1/L of the bytes per
    chip), dequants far side, and the in-host all_gather reassembles
    full precision in canonical (p·L + l) order."""
    if codec[0] == "cast":
        wg = lax.all_gather(shard.astype(codec[1]), paxis, tiled=False)
        deq = wg.astype(jnp.float32)
    else:
        wire, ctx = codec[1].compress(shard)
        wg = lax.all_gather(wire, paxis, tiled=False)
        sg = lax.all_gather(jnp.reshape(ctx[0], (1,)), paxis,
                            tiled=False)
        deq = wg.astype(jnp.float32) * sg
    full = lax.all_gather(deq, laxis, tiled=False)  # [L, P, S]
    return full.transpose(1, 0, 2).reshape(-1).astype(shard.dtype)


# -- per-leaf build-time metadata -------------------------------------------

class _Leaf:
    __slots__ = ("name", "shape", "dtype", "size", "padded",
                 "rs_codec", "ag_codec")

    def __init__(self, name, shape, dtype, size, padded):
        self.name, self.shape, self.dtype = name, shape, dtype
        self.size, self.padded = size, padded
        self.rs_codec = None
        self.ag_codec = None


def _leaf_meta(params, n, codec, sizes, ops=("reducescatter",)):
    """Static per-leaf records (tree order): flat/padded sizes plus the
    codec engagement decision per op, resolved once at build time
    through the plan plane.  Returns (treedef, [_Leaf...])."""
    from jax.tree_util import keystr, tree_flatten_with_path
    paths, treedef = tree_flatten_with_path(
        jax.eval_shape(lambda p: p, params))
    metas = []
    two_level = len(sizes) == 2 and sizes[0] > 1
    for path, leaf in paths:
        name = keystr(path) or "/"
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        m = _Leaf(name, tuple(leaf.shape), leaf.dtype, size,
                  _pad_to(size, n))
        if codec is not None and two_level \
                and jnp.issubdtype(leaf.dtype, jnp.floating):
            nbytes = m.padded * np.dtype(leaf.dtype).itemsize
            if "reducescatter" in ops and _leg_engages(
                    "reducescatter", nbytes, sizes[0], sizes[1]):
                m.rs_codec = codec
            if "allgather" in ops and _leg_engages(
                    "allgather", nbytes, sizes[0], sizes[1]):
                m.ag_codec = codec
        metas.append(m)
    return treedef, metas


def _rs_leaf(meta, grad, axes, sizes, n, ef):
    """Reduce-scatter one gradient leaf into this member's shard,
    through the wire leg when engaged.  Returns (shard, new_residual
    or None)."""
    flat = _flat_pad(grad, n)
    if meta.rs_codec is not None:
        res = ef.get(meta.name)
        shard, new_res = _rs_hier_wire(
            flat, axes[0], axes[1], sizes[0], sizes[1],
            meta.rs_codec, res[0] if res is not None else None)
        return shard, (None if new_res is None else new_res[None])
    return _rs_world(flat, axes, n), None


def _ef_spec_and_init(metas, axes, sizes, n):
    """(spec, local_shapes) for the error-feedback residual dict: one
    global [n, P, S] f32 leaf per quant-engaged tensor name, dim 0
    sharded across the world — each member carries its own [1, P, S]
    residual block, donated through the step."""
    spec, local_shapes = {}, {}
    if len(sizes) != 2:
        return spec, local_shapes
    pn = sizes[0]
    for m in metas:
        if m.rs_codec is not None and m.rs_codec[0] == "quant":
            spec[m.name] = P(tuple(axes))
            local_shapes[m.name] = (1, pn, m.padded // n)
    return spec, local_shapes


# -- stage 1 ----------------------------------------------------------------

def make_zero1_step(loss_fn: Callable,
                    optimizer: optax.GradientTransformation,
                    accum_steps: int = 1,
                    axis_name: str = spmd.DEFAULT_AXIS):
    """Build ``(step, init)`` with ZeRO-1 sharded optimizer state.

    ``loss_fn(params, batch) -> scalar`` on the local batch shard.
    Call ``init(params)`` (params replicated) once — it derives the
    state sharding and compiles the step — then
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``;
    params stay replicated, optimizer state lives sharded.  Params and
    opt state are donated each step: keep using the returned values.

    ``accum_steps > 1`` adds the paper-faithful stage-1 gradient
    accumulator: FULL and replicated (stage 1 does not shard
    gradients), filled by a pmean allreduce each microbatch; the
    optimizer applies every ``accum_steps``-th call.  The opt_state
    argument becomes ``(opt_state, acc_tree, micro)`` — treat it as
    opaque carry.

    ``optimizer`` must be elementwise (see module docstring); the
    known optax offenders are refused loudly at build time.
    """
    _assert_elementwise(optimizer, "make_zero1_step")
    if accum_steps < 1:
        raise ValueError("accum_steps must be >= 1")
    mesh = _world_mesh()
    n = mesh.shape[axis_name]

    def shard_params_local(params, idx):
        return _shard_tree(params, n, idx)

    def local_init(params):
        idx = jax.lax.axis_index(axis_name)
        opt = optimizer.init(shard_params_local(params, idx))
        if accum_steps == 1:
            return opt
        acc = jax.tree.map(jnp.zeros_like, params)
        return (opt, acc, jnp.zeros((), jnp.int32))

    def _apply(params, opt_state, grad_shards):
        idx = jax.lax.axis_index(axis_name)
        param_shards = shard_params_local(params, idx)
        updates, opt_state = optimizer.update(grad_shards, opt_state,
                                              param_shards)
        new_shards = optax.apply_updates(param_shards, updates)

        def ag(shard, like):
            full = spmd.allgather(shard, axis_name=axis_name)
            return full[:like.size].reshape(like.shape) \
                .astype(like.dtype)

        return jax.tree.map(ag, new_shards, params), opt_state

    def local_step(params, carry, batch):  # graftlint: schedule-entry=zero1 -- per-step collective order of the ZeRO-1 sharded-state plane
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss, axis_name)

        if accum_steps == 1:
            def rs(g):
                return spmd.reducescatter(_flat_pad(g, n), op=AVERAGE,
                                          axis_name=axis_name)
            params, opt_state = _apply(params, carry,
                                       jax.tree.map(rs, grads))
            return params, opt_state, loss

        opt_state, acc, micro = carry
        # Stage-1 gradient layout: the accumulator is FULL and
        # replicated (pmean allreduce per microbatch) — sharding it is
        # stage 2's move.
        acc = jax.tree.map(
            lambda a, g: a + jax.lax.pmean(g, axis_name) / accum_steps,
            acc, grads)
        micro = micro + 1

        def boundary(args):
            params, opt_state, acc = args
            idx = jax.lax.axis_index(axis_name)

            params, opt_state = _apply(
                params, opt_state,
                jax.tree.map(lambda a: _shard_leaf(a, n, idx), acc))
            return params, opt_state, jax.tree.map(jnp.zeros_like, acc)

        params, opt_state, acc = jax.lax.cond(
            micro >= accum_steps, boundary, lambda a: a,
            (params, opt_state, acc))
        micro = jnp.where(micro >= accum_steps, 0, micro)
        return params, (opt_state, acc, micro), loss

    compiled = {}

    def init(params):
        # state sharding: array leaves are per-rank shards (dim 0
        # concatenates across the axis); scalar leaves (step counters)
        # are replicated
        state_shapes = jax.eval_shape(
            lambda p: optimizer.init(shard_params_local(p, 0)), params)
        opt_spec = jax.tree.map(
            lambda s: P(axis_name) if getattr(s, "ndim", 0) >= 1
            else P(), state_shapes)
        if accum_steps == 1:
            carry_spec = opt_spec
        else:
            acc_spec = jax.tree.map(lambda _: P(), params)
            carry_spec = (opt_spec, acc_spec, P())
        mapped_init = jax.shard_map(
            local_init, mesh=mesh, in_specs=(P(),),
            out_specs=carry_spec, check_vma=False)
        mapped_step = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), carry_spec, P(axis_name)),
            out_specs=(P(), carry_spec, P()), check_vma=False)
        compiled["step"] = jax.jit(mapped_step, donate_argnums=(0, 1))
        return jax.jit(mapped_init)(params)

    def step(params, opt_state, batch):
        if "step" not in compiled:
            raise RuntimeError("call init(params) before step(...)")
        return compiled["step"](params, opt_state, batch)

    return step, init


# -- stage 2 ----------------------------------------------------------------

def make_zero2_step(loss_fn: Callable,
                    optimizer: optax.GradientTransformation,
                    accum_steps: int = 1,
                    axis_name: str = spmd.DEFAULT_AXIS,
                    mesh: Optional[Mesh] = None,
                    axes: Optional[Sequence[str]] = None,
                    wire: Optional[str] = None):
    """Build ``(step, init)`` with ZeRO-2 sharding: optimizer state AND
    the persistent gradient state live as 1/n shards.

    Gradients are reduce-scattered straight into this member's shard —
    the full gradient tree is transient inside one backward, and with
    ``accum_steps > 1`` the accumulator holds SHARDS (1/n of stage 1's
    replicated buffer).  Params stay replicated; the boundary update
    runs on shards and allgathers the new params (full precision — the
    replicated copy is the master, see module docstring).

    ``init(params) -> carry`` (opaque: opt state, shard accumulator,
    micro counter, EF residuals); ``step(params, carry, batch) ->
    (params, carry, loss)`` with params and carry donated.

    Multihost worlds run over the proc×local mesh and the gradient
    reduce-scatter's DCN leg rides the configured wire codec with
    per-tensor-name error feedback (``wire=`` overrides the env).
    """
    _assert_elementwise(optimizer, "make_zero2_step")
    if accum_steps < 1:
        raise ValueError("accum_steps must be >= 1")
    mesh, axes = _zero_mesh_and_axes(axis_name, mesh, axes)
    sizes = tuple(mesh.shape[a] for a in axes)
    n = int(np.prod(sizes))
    codec = _leg_codec(wire, axes, sizes)
    axes_arg = _axes_arg(axes)
    shard_spec = P(axes_arg if len(axes) > 1 else axes[0])

    def shard_params_local(params, idx):
        return _shard_tree(params, n, idx)

    build = {}

    def local_init(params):
        idx = _linear_index(axes, sizes)
        metas = build["metas"]
        pshards = jax.tree.leaves(shard_params_local(params, idx))
        pshards = {m.name: s for m, s in zip(metas, pshards)}
        carry = {"opt": optimizer.init(pshards),
                 "ef": {k: jnp.zeros(shape, jnp.float32)
                        for k, shape in build["ef_shapes"].items()}}
        if accum_steps > 1:
            carry["acc"] = {m.name: jnp.zeros((m.padded // n,), m.dtype)
                            for m in metas}
            carry["micro"] = jnp.zeros((), jnp.int32)
        return carry

    def _grad_shards(grads, ef):
        metas = build["metas"]
        leaves = jax.tree.leaves(grads)
        shards, new_ef = {}, dict(ef)
        for m, g in zip(metas, leaves):
            shard, res = _rs_leaf(m, g, axes, sizes, n, ef)
            shards[m.name] = shard
            if res is not None:
                new_ef[m.name] = res
        return shards, new_ef

    def _apply(params, opt_state, gshards):
        metas = build["metas"]
        idx = _linear_index(axes, sizes)
        pshards = jax.tree.leaves(shard_params_local(params, idx))
        pshards = {m.name: s for m, s in zip(metas, pshards)}
        updates, opt_state = optimizer.update(gshards, opt_state,
                                              pshards)
        new_shards = optax.apply_updates(pshards, updates)
        pleaves = jax.tree.leaves(params)
        out = []
        for m, like in zip(metas, pleaves):
            full = _ag_world(new_shards[m.name], axes)
            out.append(full[:m.size].reshape(m.shape)
                       .astype(like.dtype))
        return (jax.tree.unflatten(build["treedef"], out), opt_state)

    def local_step(params, carry, batch):  # graftlint: schedule-entry=zero2 -- per-step collective order of the ZeRO-2 sharded-state plane
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss, axes_arg)
        gshards, new_ef = _grad_shards(grads, carry["ef"])

        if accum_steps == 1:
            params, opt = _apply(params, carry["opt"], gshards)
            return params, {"opt": opt, "ef": new_ef}, loss

        acc = {k: a + gshards[k].astype(a.dtype) / accum_steps
               for k, a in carry["acc"].items()}
        micro = carry["micro"] + 1

        def boundary(args):
            params, opt, acc = args
            params, opt = _apply(params, opt, acc)
            return params, opt, {k: jnp.zeros_like(a)
                                 for k, a in acc.items()}

        params, opt, acc = jax.lax.cond(
            micro >= accum_steps, boundary, lambda a: a,
            (params, carry["opt"], acc))
        micro = jnp.where(micro >= accum_steps, 0, micro)
        return params, {"opt": opt, "acc": acc, "micro": micro,
                        "ef": new_ef}, loss

    compiled = {}

    def init(params):
        treedef, metas = _leaf_meta(params, n, codec, sizes,
                                    ops=("reducescatter",))
        ef_spec, ef_shapes = _ef_spec_and_init(metas, axes, sizes, n)
        build.update(treedef=treedef, metas=metas, ef_shapes=ef_shapes)
        opt_shapes = jax.eval_shape(
            lambda p: optimizer.init(
                {m.name: s for m, s in zip(
                    metas, jax.tree.leaves(shard_params_local(p, 0)))}),
            params)
        opt_spec = jax.tree.map(
            lambda s: shard_spec if getattr(s, "ndim", 0) >= 1
            else P(), opt_shapes)
        carry_spec = {"opt": opt_spec, "ef": ef_spec}
        if accum_steps > 1:
            carry_spec["acc"] = {m.name: shard_spec for m in metas}
            carry_spec["micro"] = P()
        mapped_init = jax.shard_map(
            local_init, mesh=mesh, in_specs=(P(),),
            out_specs=carry_spec, check_vma=False)
        mapped_step = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), carry_spec, shard_spec),
            out_specs=(P(), carry_spec, P()), check_vma=False)
        compiled["step"] = jax.jit(mapped_step, donate_argnums=(0, 1))
        return jax.jit(mapped_init)(params)

    def step(params, carry, batch):
        if "step" not in compiled:
            raise RuntimeError("call init(params) before step(...)")
        return compiled["step"](params, carry, batch)

    return step, init


# -- stage 3 ----------------------------------------------------------------

def make_zero3_step(loss_fn: Callable,
                    optimizer: optax.GradientTransformation,
                    accum_steps: int = 1,
                    axis_name: str = spmd.DEFAULT_AXIS,
                    mesh: Optional[Mesh] = None,
                    axes: Optional[Sequence[str]] = None,
                    wire: Optional[str] = None):
    """Build ``(step, init, gather)`` with ZeRO-3 sharding: params,
    gradients AND optimizer state all live as 1/n shards — total
    persistent training state is ~4Ψ/n per device.

    ``init(params)`` consumes a replicated param tree ONCE and returns
    the sharded ``state`` dict (param shards, opt shards, accumulator,
    EF residuals); ``step(state, batch) -> (state, loss)`` gathers
    each parameter leaf on demand (allgather before use; XLA frees the
    gathered copy after its last use — nothing full-size persists),
    reduce-scatters gradients into shards, and updates shards in
    place.  There is NO trailing parameter allgather: the next step
    re-gathers, and the full-precision master copy is the shard — so
    a quantized gather (DCN leg on the wire codec) perturbs only the
    transient per-step copy, never the master.  ``gather(state)``
    materializes the replicated params for eval/export.
    """
    _assert_elementwise(optimizer, "make_zero3_step")
    if accum_steps < 1:
        raise ValueError("accum_steps must be >= 1")
    mesh, axes = _zero_mesh_and_axes(axis_name, mesh, axes)
    sizes = tuple(mesh.shape[a] for a in axes)
    n = int(np.prod(sizes))
    codec = _leg_codec(wire, axes, sizes)
    axes_arg = _axes_arg(axes)
    shard_spec = P(axes_arg if len(axes) > 1 else axes[0])

    build = {}

    def _gather_full(shards):
        """Gathered (transient) replicated params from shard dict."""
        metas = build["metas"]
        out = []
        for m in metas:
            s = shards[m.name]
            if m.ag_codec is not None:
                full = _ag_hier_wire(s, axes[0], axes[1], m.ag_codec)
            else:
                full = _ag_world(s, axes)
            out.append(full[:m.size].reshape(m.shape).astype(m.dtype))
        return jax.tree.unflatten(build["treedef"], out)

    def local_init(params):
        idx = _linear_index(axes, sizes)
        metas = build["metas"]
        leaves = jax.tree.leaves(params)
        shards = {m.name: _shard_leaf(x, n, idx)
                  for m, x in zip(metas, leaves)}
        state = {"shards": shards,
                 "opt": optimizer.init(shards),
                 "ef": {k: jnp.zeros(shape, jnp.float32)
                        for k, shape in build["ef_shapes"].items()}}
        if accum_steps > 1:
            state["acc"] = {k: jnp.zeros_like(v)
                            for k, v in shards.items()}
            state["micro"] = jnp.zeros((), jnp.int32)
        return state

    def local_step(state, batch):  # graftlint: schedule-entry=zero3 -- per-step collective order of the ZeRO-3 sharded-state plane
        metas = build["metas"]
        params = _gather_full(state["shards"])
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        loss = jax.lax.pmean(loss, axes_arg)
        gleaves = jax.tree.leaves(grads)
        gshards, new_ef = {}, dict(state["ef"])
        for m, g in zip(metas, gleaves):
            shard, res = _rs_leaf(m, g, axes, sizes, n, state["ef"])
            gshards[m.name] = shard
            if res is not None:
                new_ef[m.name] = res

        def update(shards, opt, g):
            updates, opt = optimizer.update(g, opt, shards)
            return optax.apply_updates(shards, updates), opt

        if accum_steps == 1:
            shards, opt = update(state["shards"], state["opt"], gshards)
            return {"shards": shards, "opt": opt, "ef": new_ef}, loss

        acc = {k: a + gshards[k].astype(a.dtype) / accum_steps
               for k, a in state["acc"].items()}
        micro = state["micro"] + 1

        def boundary(args):
            shards, opt, acc = args
            shards, opt = update(shards, opt, acc)
            return shards, opt, {k: jnp.zeros_like(a)
                                 for k, a in acc.items()}

        shards, opt, acc = jax.lax.cond(
            micro >= accum_steps, boundary, lambda a: a,
            (state["shards"], state["opt"], acc))
        micro = jnp.where(micro >= accum_steps, 0, micro)
        return {"shards": shards, "opt": opt, "acc": acc,
                "micro": micro, "ef": new_ef}, loss

    def local_gather(state):
        # Full-precision gather for eval/export: the wire codec is a
        # step-time lever, not an export-time one.
        metas = build["metas"]
        out = []
        for m in metas:
            full = _ag_world(state["shards"][m.name], axes)
            out.append(full[:m.size].reshape(m.shape).astype(m.dtype))
        return jax.tree.unflatten(build["treedef"], out)

    compiled = {}

    def init(params):
        treedef, metas = _leaf_meta(params, n, codec, sizes,
                                    ops=("reducescatter", "allgather"))
        ef_spec, ef_shapes = _ef_spec_and_init(metas, axes, sizes, n)
        build.update(treedef=treedef, metas=metas, ef_shapes=ef_shapes)
        shards_spec = {m.name: shard_spec for m in metas}
        opt_shapes = jax.eval_shape(
            lambda p: optimizer.init(
                {m.name: _flat_pad(x, n)[:m.padded // n]
                 for m, x in zip(metas, jax.tree.leaves(p))}),
            params)
        opt_spec = jax.tree.map(
            lambda s: shard_spec if getattr(s, "ndim", 0) >= 1
            else P(), opt_shapes)
        state_spec = {"shards": shards_spec, "opt": opt_spec,
                      "ef": ef_spec}
        if accum_steps > 1:
            state_spec["acc"] = dict(shards_spec)
            state_spec["micro"] = P()
        mapped_init = jax.shard_map(
            local_init, mesh=mesh, in_specs=(P(),),
            out_specs=state_spec, check_vma=False)
        mapped_step = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(state_spec, shard_spec),
            out_specs=(state_spec, P()), check_vma=False)
        mapped_gather = jax.shard_map(
            local_gather, mesh=mesh, in_specs=(state_spec,),
            out_specs=P(), check_vma=False)
        compiled["step"] = jax.jit(mapped_step, donate_argnums=(0,))
        compiled["gather"] = jax.jit(mapped_gather)
        return jax.jit(mapped_init)(params)

    def step(state, batch):
        if "step" not in compiled:
            raise RuntimeError("call init(params) before step(...)")
        return compiled["step"](state, batch)

    def gather(state):
        if "gather" not in compiled:
            raise RuntimeError("call init(params) before gather(...)")
        return compiled["gather"](state)

    return step, init, gather


# -- stage dispatch ---------------------------------------------------------

def zero_stage_from_env() -> int:
    """``HOROVOD_ZERO_STAGE`` (0-3, default 0 = plain data parallel);
    malformed or out-of-range values are refused loudly — a typo'd
    stage silently training plain DP is exactly the drift this env
    exists to prevent."""
    raw = os.environ.get("HOROVOD_ZERO_STAGE")
    if raw is None or not raw.strip():
        return 0
    try:
        stage = int(raw)
    except ValueError:
        raise ValueError(
            "HOROVOD_ZERO_STAGE=%r is not an integer (known stages: "
            "0 (off), 1, 2, 3)" % raw)
    if not 0 <= stage <= 3:
        raise ValueError(
            "HOROVOD_ZERO_STAGE=%d: known stages are 0 (off), 1, 2, 3"
            % stage)
    return stage


def make_zero_step(loss_fn: Callable,
                   optimizer: optax.GradientTransformation,
                   stage: Optional[int] = None, **kwargs):
    """Stage-dispatched builder: ``stage=None`` reads
    ``HOROVOD_ZERO_STAGE`` (default 0 = ``make_data_parallel_step``).
    Returns each stage's native tuple — ``(step, init)`` for stages
    0-2, ``(step, init, gather)`` for stage 3; the carry argument is
    stage-opaque by design."""
    stage = zero_stage_from_env() if stage is None else int(stage)
    if stage < 2:
        # Stages 0/1 have no mesh/axes/wire surface; dropping an
        # explicit argument silently would change training semantics
        # under an env flip, so refuse instead.
        for k in ("mesh", "axes", "wire"):
            if kwargs.pop(k, None) is not None:
                raise ValueError(
                    "make_zero_step: %s= is a stage-2/3 argument but "
                    "the resolved stage is %d (HOROVOD_ZERO_STAGE?)"
                    % (k, stage))
    if stage == 0:
        from .data_parallel import make_data_parallel_step
        kwargs.pop("axis_name", None)
        accum = int(kwargs.pop("accum_steps", 1) or 1)
        if accum > 1:
            # Same one-update-per-accum semantics the sharded stages
            # give: accumulate through optax.MultiSteps rather than
            # silently applying every microbatch.
            optimizer = optax.MultiSteps(optimizer, accum)
        return make_data_parallel_step(loss_fn, optimizer, **kwargs)
    if stage == 1:
        return make_zero1_step(loss_fn, optimizer, **kwargs)
    if stage == 2:
        return make_zero2_step(loss_fn, optimizer, **kwargs)
    if stage == 3:
        return make_zero3_step(loss_fn, optimizer, **kwargs)
    raise ValueError("unknown ZeRO stage %r" % stage)
