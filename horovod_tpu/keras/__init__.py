"""Keras adapter: ``import horovod_tpu.keras as hvd``.

Reference parity: ``horovod/keras/__init__.py`` +
``horovod/tensorflow/keras/__init__.py`` — ``DistributedOptimizer``
for Keras models, the collectives, broadcast helpers, and the
training callbacks (``horovod_tpu.keras.callbacks``).
"""

from ..tensorflow import (  # noqa: F401
    ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM, Adasum, Average, Compression,
    DistributedOptimizer, HorovodInternalError, Max, Min, Product,
    ProcessSet, Sum, add_process_set, allgather, allgather_object,
    allreduce, alltoall, barrier, broadcast, broadcast_object,
    broadcast_variables, cross_rank, cross_size, global_process_set,
    SyncBatchNormalization, grouped_allgather, grouped_allreduce,
    grouped_reducescatter, init, is_initialized, join, local_rank,
    local_size, rank, reducescatter, remove_process_set, shutdown,
    size, start_timeline, stop_timeline)
from . import callbacks  # noqa: F401
from ..tensorflow import elastic as _tf_elastic


class elastic(_tf_elastic):
    """Reference ``horovod.keras.elastic``: ``KerasState`` is the
    tf.keras state under its keras-adapter name."""

    KerasState = _tf_elastic.TensorFlowKerasState


def broadcast_global_variables(root_rank: int = 0, model=None):
    """Broadcast a model's variables from ``root_rank`` (reference
    ``hvd.callbacks.BroadcastGlobalVariablesCallback`` / the TF1-style
    ``broadcast_global_variables``)."""
    if model is None:
        raise ValueError("pass model= (Keras 3 has no global graph "
                         "variable collection)")
    broadcast_variables(model.weights, root_rank)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a Keras model wrapping its optimizer in
    ``DistributedOptimizer`` (reference ``hvd.load_model``, which
    injects wrapped optimizer classes into ``custom_objects`` so the
    checkpoint's optimizer state survives the wrap).

    ``custom_optimizers`` — extra optimizer classes the checkpoint may
    reference (merged into ``custom_objects`` by class name, as in the
    reference).
    """
    import keras
    custom_objects = dict(custom_objects or {})
    for cls in custom_optimizers or ():
        custom_objects.setdefault(cls.__name__, cls)
    model = keras.models.load_model(filepath,
                                    custom_objects=custom_objects)
    loaded = getattr(model, "optimizer", None)
    if loaded is not None:
        dist = DistributedOptimizer(loaded, compression=compression)
        # Carry the checkpoint's slot state (moments, iteration count)
        # into the wrapped optimizer instead of recompiling, which
        # would also drop compiled metrics.
        if getattr(loaded, "built", False):
            dist.build(model.trainable_variables)
            for src, dst in zip(loaded.variables, dist.variables):
                dst.assign(src)
        model.optimizer = dist
    return model
