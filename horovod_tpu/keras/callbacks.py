"""Keras training callbacks.

Reference parity: ``horovod/_keras/callbacks.py`` (shared impl behind
``horovod/keras/callbacks.py`` and ``horovod/tensorflow/keras/callbacks.py``):
``BroadcastGlobalVariablesCallback``, ``MetricAverageCallback``,
``LearningRateWarmupCallback``, ``LearningRateScheduleCallback``.
Written against Keras 3 (`keras.callbacks.Callback`,
``optimizer.learning_rate``).
"""

from __future__ import annotations

import math
from typing import Optional

import keras
import numpy as np

from .. import tensorflow as hvd


def _get_lr(optimizer) -> float:
    return float(keras.ops.convert_to_numpy(optimizer.learning_rate))


def _set_lr(optimizer, lr: float):
    optimizer.learning_rate = lr


class _MomentumCorrectionMixin:
    """Momentum correction (reference ``_keras/callbacks.py``, after
    Goyal et al. A.1): Keras SGD folds the LR into the velocity
    (``v = m*v - lr*g``), so when the LR changes the accumulated
    velocity is scaled by ``new_lr / old_lr`` to keep the history
    consistent with the new rate.  Scaling the slot *variables* (not
    the ``momentum`` hyperparameter, a Python float baked into the
    traced train step) works under compiled Keras training.
    """

    momentum_correction = False

    def _adjust_lr(self, new_lr: float):
        opt = self.model.optimizer
        old_lr = _get_lr(opt)
        _set_lr(opt, new_lr)
        if (self.momentum_correction and old_lr > 0
                and new_lr != old_lr):
            slots = getattr(opt, "momentums", None)
            if slots:
                scale = new_lr / old_lr
                for v in slots:
                    v.assign(v * scale)

    def _restore_momentum_if_needed(self):
        # Velocity scaling is a one-time correction at the LR change —
        # nothing to restore (the reference's hyperparameter variant
        # restores; the slot-scaling formulation does not need to).
        pass


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast model + optimizer state from ``root_rank`` before the
    first batch so all ranks start identical."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        weights = hvd.broadcast_object(
            [keras.ops.convert_to_numpy(w) for w in self.model.weights],
            root_rank=self.root_rank,
            name="BroadcastGlobalVariablesCallback.model")
        for v, val in zip(self.model.weights, weights):
            v.assign(val)
        if self.model.optimizer is not None:
            opt_vars = self.model.optimizer.variables
            vals = hvd.broadcast_object(
                [keras.ops.convert_to_numpy(v) for v in opt_vars],
                root_rank=self.root_rank,
                name="BroadcastGlobalVariablesCallback.optimizer")
            for v, val in zip(opt_vars, vals):
                v.assign(val)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics over ranks (reference: wraps logs at
    epoch end with an allreduce per metric)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or hvd.size() <= 1:
            return
        for k in sorted(logs.keys()):
            v = logs[k]
            if isinstance(v, (int, float, np.floating, np.integer)):
                logs[k] = float(hvd.allreduce(
                    np.asarray(v, np.float64), op=hvd.Average,
                    name="MetricAverageCallback.%s.%d" % (k, epoch)))


class LearningRateWarmupCallback(_MomentumCorrectionMixin,
                                 keras.callbacks.Callback):
    """Ramp LR from ``initial_lr / size`` (or given start) to
    ``initial_lr`` over ``warmup_epochs`` (reference: gradual warmup of
    the linearly-scaled learning rate, Goyal et al.)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        super().__init__()
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self.current_epoch = 0
        self._steps = None

    def on_train_begin(self, logs=None):
        self._steps = self.steps_per_epoch or self.params.get("steps")
        if self._steps is None:
            raise ValueError(
                "LearningRateWarmupCallback needs steps_per_epoch when "
                "Keras cannot infer steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def _warmup_lr(self, step_in_warmup: float) -> float:
        # size^(progress): exponential interpolation from lr/size to lr.
        total = self.warmup_epochs * self._steps
        progress = min(1.0, step_in_warmup / max(1, total))
        return self.initial_lr / hvd.size() * \
            math.pow(hvd.size(), progress)

    def on_batch_begin(self, batch, logs=None):
        if self.current_epoch >= self.warmup_epochs:
            return
        step = self.current_epoch * self._steps + batch
        self._adjust_lr(self._warmup_lr(step))

    def on_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        if epoch == self.warmup_epochs - 1:
            _set_lr(self.model.optimizer, self.initial_lr)
            if self.verbose and hvd.rank() == 0:
                print("LearningRateWarmupCallback: warmup complete, "
                      "lr=%g" % self.initial_lr)


class LearningRateScheduleCallback(_MomentumCorrectionMixin,
                                   keras.callbacks.Callback):
    """Multiply LR by ``multiplier`` within ``[start_epoch, end_epoch)``
    (reference: piecewise/exponential decay schedules; ``multiplier``
    may be a constant or a function of epoch)."""

    def __init__(self, initial_lr: float, multiplier,
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self.current_epoch = 0
        self._steps = None
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_train_begin(self, logs=None):
        self._steps = self.steps_per_epoch or self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            self._adjust_lr(self.initial_lr * self.multiplier(epoch))
            if self.verbose and hvd.rank() == 0:
                print("LearningRateScheduleCallback: epoch %d lr=%g"
                      % (epoch, _get_lr(self.model.optimizer)))

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_range(self.current_epoch):
            return
        if self._steps is None:
            return
        epoch = self.current_epoch + batch / float(self._steps)
        self._adjust_lr(self.initial_lr * self.multiplier(epoch))

    def on_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()
