"""models subpackage."""
