"""BERT-style bidirectional encoder, TPU-first SPMD.

Covers the reference's "PyTorch BERT-large fine-tune" flagship config
(BASELINE.json configs[2]) as a native model family: a pure-function
encoder over a params pytree with layer-stacked ``[L, ...]`` leaves
consumed by ``lax.scan`` (single-layer trace, static shapes, bf16
activations on the MXU), sharded Megatron-style over a (dp, tp) mesh:

* **dp** — batch sharding; gradient psum fused into the step.
* **tp** — attention heads / FFN columns column-row sharded (one psum
  after ``wo`` and one after ``w_out``); vocab-sharded word embedding
  and vocab-parallel MLM cross entropy (never materializes the full
  vocab on one shard).

Architectural choices vs the decoder flagship (``transformer.py``):
bidirectional attention (the Pallas flash kernel with ``causal=False``
when no padding mask is given; masked attention falls back to the XLA
path with an additive bias), learned position + token-type embeddings,
post-LN residual blocks and GELU — the original BERT recipe.  The
attention-mask contract matches ``transformers``' ``attention_mask``
(1 = attend, 0 = padding).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import (_sharded_embed_lookup, _use_flash_attention,
                          opt_spec_tree, vocab_parallel_cross_entropy)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    type_vocab: int = 2
    n_classes: int = 2            # sequence-classification head width
    norm_eps: float = 1e-12
    dtype: str = "bfloat16"       # activation dtype (MXU-native)
    param_dtype: str = "float32"
    remat: bool = False
    dp_axis: str = "dp"
    tp_axis: str = "tp"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def act_dtype(self):
        return jnp.dtype(self.dtype)


def init_params(key, cfg: BertConfig):
    """Layer-stacked parameter pytree (host-side, full/unsharded)."""
    pd = jnp.dtype(cfg.param_dtype)
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    ks = jax.random.split(key, 12)

    def norm(k, shape, fan_in):
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(pd)

    return {
        "word_embed": norm(ks[0], (cfg.vocab_size, d), d),
        "pos_embed": norm(ks[1], (cfg.max_seq, d), d),
        "type_embed": norm(ks[2], (cfg.type_vocab, d), d),
        "ln_embed_g": jnp.ones((d,), pd),
        "ln_embed_b": jnp.zeros((d,), pd),
        "layers": {
            # Separate projections: a fused [d, 3d] param sharded
            # P(..., tp) would hand shard 0 all of Q plus part of K
            # (contiguous column slices cross the q/k/v boundary); the
            # per-shard compute below concatenates the LOCAL slices,
            # which is exact for any tp.
            "wq": norm(ks[3], (L, d, d), d),
            "wk": norm(ks[10], (L, d, d), d),
            "wv": norm(ks[11], (L, d, d), d),
            "bq": jnp.zeros((L, d), pd),
            "bk": jnp.zeros((L, d), pd),
            "bv": jnp.zeros((L, d), pd),
            "wo": norm(ks[4], (L, d, d), d),
            "bo": jnp.zeros((L, d), pd),
            "ln1_g": jnp.ones((L, d), pd),
            "ln1_b": jnp.zeros((L, d), pd),
            "w_in": norm(ks[5], (L, d, f), d),
            "b_in": jnp.zeros((L, f), pd),
            "w_out": norm(ks[6], (L, f, d), f),
            "b_out": jnp.zeros((L, d), pd),
            "ln2_g": jnp.ones((L, d), pd),
            "ln2_b": jnp.zeros((L, d), pd),
        },
        "pooler_w": norm(ks[7], (d, d), d),
        "pooler_b": jnp.zeros((d,), pd),
        "cls_w": norm(ks[8], (d, cfg.n_classes), d),
        "cls_b": jnp.zeros((cfg.n_classes,), pd),
        # MLM head: transform + layernorm; decoder weight is TIED to
        # word_embed (the BERT recipe), only a vocab bias is stored.
        "mlm_w": norm(ks[9], (d, d), d),
        "mlm_b": jnp.zeros((d,), pd),
        "mlm_ln_g": jnp.ones((d,), pd),
        "mlm_ln_b": jnp.zeros((d,), pd),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), pd),
    }


def param_specs(cfg: BertConfig):
    """Megatron (dp, tp) sharding: vocab-sharded word embedding +
    MLM bias, column/row-sharded attention and FFN, everything else
    replicated."""
    from jax.sharding import PartitionSpec as P
    tp = cfg.tp_axis
    rep1, rep2 = P(None), P(None, None)
    return {
        "word_embed": P(tp, None),
        "pos_embed": rep2,
        "type_embed": rep2,
        "ln_embed_g": rep1, "ln_embed_b": rep1,
        "layers": {
            "wq": P(None, None, tp), "wk": P(None, None, tp),
            "wv": P(None, None, tp),
            "bq": P(None, tp), "bk": P(None, tp), "bv": P(None, tp),
            "wo": P(None, tp, None), "bo": P(None, None),
            "ln1_g": rep2, "ln1_b": rep2,
            "w_in": P(None, None, tp), "b_in": P(None, tp),
            "w_out": P(None, tp, None), "b_out": P(None, None),
            "ln2_g": rep2, "ln2_b": rep2,
        },
        "pooler_w": rep2, "pooler_b": rep1,
        "cls_w": rep2, "cls_b": rep1,
        "mlm_w": rep2, "mlm_b": rep1,
        "mlm_ln_g": rep1, "mlm_ln_b": rep1,
        "mlm_bias": P(tp),
    }


def layer_norm(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    return (y.astype(x.dtype) * g.astype(x.dtype)
            + b.astype(x.dtype))


def _attention(h, lp, cfg: BertConfig, mask):
    """Bidirectional self-attention; per-shard code (tp slice of the
    heads).  ``mask`` is [B, S] with 1 = attend (transformers
    contract) or None for dense sequences."""
    b, s, _ = h.shape
    hd = cfg.head_dim
    q = (h @ lp["wq"].astype(h.dtype)
         + lp["bq"].astype(h.dtype)).reshape(b, s, -1, hd)
    k = (h @ lp["wk"].astype(h.dtype)
         + lp["bk"].astype(h.dtype)).reshape(b, s, -1, hd)
    v = (h @ lp["wv"].astype(h.dtype)
         + lp["bv"].astype(h.dtype)).reshape(b, s, -1, hd)
    if mask is None and _use_flash_attention():
        from ..ops.pallas_kernels import flash_attention
        attn = flash_attention(q, k, v, causal=False)
    else:
        # XLA path with an additive bias for padding keys.
        qf = q.astype(jnp.float32) / math.sqrt(hd)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k.astype(jnp.float32))
        if mask is not None:
            bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e9)
            scores = scores + bias
        p = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p,
                          v.astype(jnp.float32)).astype(h.dtype)
    out = attn.reshape(b, s, -1) @ lp["wo"].astype(h.dtype)
    # Row-sharded wo: partial sums live on each tp shard; the bias is
    # replicated, so add it AFTER the psum exactly once.
    return lax.psum(out, cfg.tp_axis) + lp["bo"].astype(h.dtype)


def _ffn(h, lp, cfg: BertConfig):
    a = jax.nn.gelu(h @ lp["w_in"].astype(h.dtype)
                    + lp["b_in"].astype(h.dtype))
    out = a @ lp["w_out"].astype(h.dtype)
    return lax.psum(out, cfg.tp_axis) + lp["b_out"].astype(h.dtype)


def encode(params, tokens, cfg: BertConfig, token_type=None, mask=None):
    """Per-shard encoder: tokens [B_loc, S] -> hidden [B_loc, S, d].
    Must run inside a shard_map over a mesh containing (dp, tp)."""
    s = tokens.shape[1]
    x = _sharded_embed_lookup(params["word_embed"], tokens, cfg.tp_axis)
    x = x + params["pos_embed"][:s][None]
    tt = (token_type if token_type is not None
          else jnp.zeros_like(tokens))
    x = x + jnp.take(params["type_embed"], tt, axis=0)
    x = layer_norm(x, params["ln_embed_g"], params["ln_embed_b"],
                   cfg.norm_eps).astype(cfg.act_dtype)

    def layer(x, lp):
        # Post-LN residual blocks (original BERT).
        x = layer_norm(x + _attention(x, lp, cfg, mask),
                       lp["ln1_g"], lp["ln1_b"], cfg.norm_eps)
        x = layer_norm(x + _ffn(x, lp, cfg),
                       lp["ln2_g"], lp["ln2_b"], cfg.norm_eps)
        return x, None

    layer_fn = jax.checkpoint(layer) if cfg.remat else layer
    x, _ = lax.scan(layer_fn, x, params["layers"])
    return x


def mlm_logits_local(params, hidden, cfg: BertConfig):
    """Vocab-parallel MLM head: [B, S, d] -> [B, S, V/tp] f32 (tied
    decoder = the word-embedding shard, so the matmul stays
    vocab-sharded like the lookup)."""
    h = jax.nn.gelu(hidden.astype(jnp.float32)
                    @ params["mlm_w"].astype(jnp.float32)
                    + params["mlm_b"].astype(jnp.float32))
    h = layer_norm(h, params["mlm_ln_g"].astype(jnp.float32),
                   params["mlm_ln_b"].astype(jnp.float32), cfg.norm_eps)
    return (h @ params["word_embed"].astype(jnp.float32).T
            + params["mlm_bias"].astype(jnp.float32))


def cls_logits(params, hidden):
    """[CLS] pooled sequence-classification head: [B, S, d] -> [B, C]."""
    pooled = jnp.tanh(hidden[:, 0].astype(jnp.float32)
                      @ params["pooler_w"].astype(jnp.float32)
                      + params["pooler_b"].astype(jnp.float32))
    return pooled @ params["cls_w"].astype(jnp.float32) \
        + params["cls_b"].astype(jnp.float32)


def mlm_loss(params, batch, cfg: BertConfig):
    """Masked-LM loss: mean nll over GLOBAL masked positions.

    Numerator and denominator are psum'ed over dp separately before
    the division — a per-shard masked mean then pmean'ed would weight
    shards with few masked positions as heavily as full ones (uneven
    ~15% masking makes per-shard counts differ every batch), breaking
    mesh invariance of the loss and gradients."""
    hidden = encode(params, batch["tokens"], cfg,
                    batch.get("token_type"), batch.get("mask"))
    logits = mlm_logits_local(params, hidden, cfg)
    nll = vocab_parallel_cross_entropy(logits, batch["targets"],
                                       cfg.tp_axis)
    m = batch["mlm_mask"].astype(jnp.float32)
    num = lax.psum((nll * m).sum(), cfg.dp_axis)
    den = lax.psum(m.sum(), cfg.dp_axis)
    return num / jnp.maximum(den, 1.0)


def classification_loss(params, batch, cfg: BertConfig):
    """Per-shard [CLS] cross entropy (fine-tune objective)."""
    hidden = encode(params, batch["tokens"], cfg,
                    batch.get("token_type"), batch.get("mask"))
    logits = cls_logits(params, hidden)
    nll = -jax.nn.log_softmax(logits)[
        jnp.arange(logits.shape[0]), batch["labels"]]
    return lax.pmean(nll.mean(), cfg.dp_axis)


def make_finetune_step(cfg: BertConfig, mesh, optimizer,
                       objective: str = "classification",
                       donate: bool = True):
    """Jitted SPMD fine-tune step over a (dp, tp) mesh.

    Returns ``(build, shard_batch)``;
    ``build(params_host) -> (step, params, opt_state)`` with
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``.
    Gradients are psum'ed over dp inside the compiled program (the
    framework's DP story fused into the step — what the reference's
    DistributedOptimizer does from the outside)."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    loss_fn = (classification_loss if objective == "classification"
               else mlm_loss)
    specs = param_specs(cfg)
    dp = cfg.dp_axis
    batch_specs = {"tokens": P(dp, None), "targets": P(dp, None),
                   "token_type": P(dp, None), "mask": P(dp, None),
                   "mlm_mask": P(dp, None), "labels": P(dp)}

    def local_step(params, opt_state, batch):
        # vma-tracked AD (check_vma=True below) differentiates the dp
        # pmean in the loss with exact collective transposes, so the
        # per-shard grads ARE the global-batch gradient — no manual
        # combine (verified by the sharded-vs-single gradient test).
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg))(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def build(params_host):
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params_host, specs)
        opt_state = optimizer.init(params)
        # Optimizer subtrees isomorphic to params inherit param specs.
        o_specs = opt_spec_tree(opt_state, params_host, specs)
        opt_state = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x),
                                        NamedSharding(mesh, s))
            if hasattr(x, "shape") else x, opt_state, o_specs)

        def make(batch_keys):
            bspec = {k: batch_specs[k] for k in batch_keys}
            mapped = jax.shard_map(
                local_step, mesh=mesh,
                in_specs=(specs, o_specs, bspec),
                out_specs=(specs, o_specs, P()),
                check_vma=True)
            return jax.jit(mapped,
                           donate_argnums=(0, 1) if donate else ())

        compiled = {}

        def step(params, opt_state, batch):
            key = tuple(sorted(batch))
            if key not in compiled:
                compiled[key] = make(key)
            return compiled[key](params, opt_state, batch)

        return step, params, opt_state

    def shard_batch(batch):
        from jax.sharding import NamedSharding
        return {k: jax.device_put(jnp.asarray(v),
                                  NamedSharding(mesh, batch_specs[k]))
                for k, v in batch.items()}

    return build, shard_batch
