"""MNIST-scale MLP: the minimal end-to-end DP workload.

Parity with the reference's canonical example
(``examples/pytorch/pytorch_mnist.py``, the BASELINE.json CPU config):
a small classifier trained data-parallel through
``hvd.make_data_parallel_step`` + ``DistributedOptimizer``.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp


def init_mlp(key, sizes: Sequence[int] = (784, 128, 64, 10),
             dtype=jnp.float32):
    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, fan_in, fan_out in zip(keys, sizes[:-1], sizes[1:]):
        params.append({
            "w": (jax.random.normal(k, (fan_in, fan_out))
                  / math.sqrt(fan_in)).astype(dtype),
            "b": jnp.zeros((fan_out,), dtype),
        })
    return params


def mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params, batch):
    logits = mlp_apply(params, batch["x"])
    labels = batch["y"]
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()
    return nll


def accuracy(params, batch):
    logits = mlp_apply(params, batch["x"])
    return jnp.mean(jnp.argmax(logits, -1) == batch["y"])


def synthetic_mnist(rng, n: int):
    """Deterministic MNIST stand-in (zero-egress environment: no dataset
    downloads): 10 gaussian class prototypes + noise."""
    protos = rng.randn(10, 784).astype("float32")
    y = rng.randint(0, 10, size=n)
    x = protos[y] + 0.5 * rng.randn(n, 784).astype("float32")
    return {"x": x.astype("float32"), "y": y.astype("int32")}
