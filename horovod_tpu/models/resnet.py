"""ResNet-v1.5 family (ResNet-50 flagship) in flax.

Parity with the reference's headline benchmark workload
(``examples/pytorch/pytorch_imagenet_resnet50.py`` +
``pytorch_synthetic_benchmark.py``; BASELINE.md metric
"ResNet-50 images/sec/chip").  TPU-first choices: NHWC layout (XLA's
native conv layout on TPU), bf16 activations on the MXU, optional
cross-replica SyncBatchNorm via the framework's DP axis.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

STAGE_SIZES = {
    18: [2, 2, 2, 2],
    34: [3, 4, 6, 3],
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}


def _pallas_bn_enabled() -> bool:
    """Opt-in fused Pallas BN kernels (HVD_TPU_PALLAS_BN=1 on TPU,
    =force off-TPU via the interpreter).

    Default OFF after measurement: mid-CNN custom calls constrain
    operand layouts to plain row-major, and XLA brackets every kernel
    with full-activation layout copies (323 copy ops vs 7, measured on
    the ResNet-50 train step -> 112 ms/step vs 47 ms).  XLA's own
    fused BN+relu+add is within ~2x of the HBM floor, so the copies
    cost far more than the fusion saves.  The kernels stay correct and
    tested (tests/test_pallas_bn.py) for standalone use, where no
    layout boundary exists.  See docs/benchmarks.md."""
    v = os.environ.get("HVD_TPU_PALLAS_BN", "0").lower()
    if v in ("0", "false", "no", ""):
        return False
    if v == "force":
        return True
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


class NormAct(nn.Module):
    """BatchNorm + optional residual add + optional ReLU, as ONE op.

    Train mode on TPU runs the fused Pallas kernels
    (``ops/pallas_bn.py``: single-read stats, fused
    normalize+add+relu, fused dbeta/dgamma reductions, fused
    dx+dresidual); eval mode, sync-BN (``axis_name``), and non-tiling
    shapes use the plain XLA path.  Parameter/stat names match flax
    ``nn.BatchNorm`` (scale/bias, batch_stats mean/var).
    """

    relu: bool = True
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x, residual=None):
        c = x.shape[-1]
        scale = self.param("scale", self.scale_init, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))

        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
            y = self._xla_apply(x, mean, var, scale, bias, residual)
            return y

        out = None
        if self.axis_name is None and _pallas_bn_enabled():
            from ..ops.pallas_bn import batch_norm_act
            out = batch_norm_act(x, scale, bias, residual,
                                 eps=self.epsilon, relu=self.relu)
        if out is not None:
            y, mean, var = out
        else:
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axes)
            sq = jnp.mean(jnp.square(xf), axes)
            if self.axis_name is not None:
                mean = jax.lax.pmean(mean, self.axis_name)
                sq = jax.lax.pmean(sq, self.axis_name)
            var = jnp.maximum(sq - jnp.square(mean), 0.0)
            y = self._xla_apply(x, mean, var, scale, bias, residual)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * var
        return y

    def _xla_apply(self, x, mean, var, scale, bias, residual):
        # [C]-sized math stays f32; the activation-sized elementwise
        # pass runs in the compute dtype (flax semantics — bf16 keeps
        # the HBM traffic at half width).
        mul = (jax.lax.rsqrt(var + self.epsilon) * scale).astype(
            self.dtype)
        add = (bias - mean * jax.lax.rsqrt(var + self.epsilon)
               * scale).astype(self.dtype)
        z = x.astype(self.dtype) * mul + add
        if residual is not None:
            z = z + residual.astype(self.dtype)
        if self.relu:
            z = jnp.maximum(z, 0)
        return z.astype(self.dtype)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    norm: Callable  # NormAct factory; kwargs: relu, scale_init
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype)(y)
        y = self.norm()(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        if residual.shape[-1] != self.filters * 4 or \
                self.strides != (1, 1):
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(relu=False)(residual)
        # One fused op: BN(y) + residual, then ReLU.
        return self.norm(scale_init=nn.initializers.zeros)(y, residual)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    norm: Callable
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False,
                    dtype=self.dtype)(y)
        if residual.shape[-1] != self.filters or self.strides != (1, 1):
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm(relu=False)(residual)
        return self.norm(scale_init=nn.initializers.zeros)(y, residual)


class ResNet(nn.Module):
    depth: int = 50
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    sync_batch_norm: bool = False
    axis_name: Optional[str] = "hvd"

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(
            NormAct, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
            axis_name=self.axis_name if (self.sync_batch_norm and train)
            else None)
        block = BottleneckBlock if self.depth >= 50 else BasicBlock
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = norm()(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(STAGE_SIZES[self.depth]):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block(64 * 2 ** i, strides, norm, self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def create_resnet50(num_classes: int = 1000, dtype=jnp.bfloat16,
                    sync_batch_norm: bool = False):
    return ResNet(depth=50, num_classes=num_classes, dtype=dtype,
                  sync_batch_norm=sync_batch_norm)


def create_resnet101(num_classes: int = 1000, dtype=jnp.bfloat16,
                     sync_batch_norm: bool = False):
    """The reference's published ~90% scaling-efficiency row pairs
    ResNet-101 with Inception-V3 (BASELINE.md); depth 101 reuses the
    same bottleneck stack ([3, 4, 23, 3] stages)."""
    return ResNet(depth=101, num_classes=num_classes, dtype=dtype,
                  sync_batch_norm=sync_batch_norm)


def resnet_loss_fn(model: ResNet, variables, batch, train: bool = True):
    """Cross-entropy + batch-stat update handling for flax BatchNorm."""
    if train:
        logits, new_state = model.apply(
            variables, batch["x"], train=True, mutable=["batch_stats"])
    else:
        logits = model.apply(variables, batch["x"], train=False)
        new_state = {}
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, batch["y"][:, None], axis=1).mean()
    return nll, new_state
