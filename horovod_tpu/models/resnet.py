"""ResNet-v1.5 family (ResNet-50 flagship) in flax.

Parity with the reference's headline benchmark workload
(``examples/pytorch/pytorch_imagenet_resnet50.py`` +
``pytorch_synthetic_benchmark.py``; BASELINE.md metric
"ResNet-50 images/sec/chip").  TPU-first choices: NHWC layout (XLA's
native conv layout on TPU), bf16 activations on the MXU, optional
cross-replica SyncBatchNorm via the framework's DP axis.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

STAGE_SIZES = {
    18: [2, 2, 2, 2],
    34: [3, 4, 6, 3],
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    norm: Callable
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    norm: Callable
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, use_bias=False,
                    dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), use_bias=False,
                    dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    depth: int = 50
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    sync_batch_norm: bool = False
    axis_name: Optional[str] = "hvd"

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
            axis_name=self.axis_name if (self.sync_batch_norm and train)
            else None)
        block = BottleneckBlock if self.depth >= 50 else BasicBlock
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = norm()(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(STAGE_SIZES[self.depth]):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block(64 * 2 ** i, strides, norm, self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def create_resnet50(num_classes: int = 1000, dtype=jnp.bfloat16,
                    sync_batch_norm: bool = False):
    return ResNet(depth=50, num_classes=num_classes, dtype=dtype,
                  sync_batch_norm=sync_batch_norm)


def resnet_loss_fn(model: ResNet, variables, batch, train: bool = True):
    """Cross-entropy + batch-stat update handling for flax BatchNorm."""
    if train:
        logits, new_state = model.apply(
            variables, batch["x"], train=True, mutable=["batch_stats"])
    else:
        logits = model.apply(variables, batch["x"], train=False)
        new_state = {}
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, batch["y"][:, None], axis=1).mean()
    return nll, new_state
