"""Flagship model: llama-style decoder-only transformer, TPU-first SPMD.

Plays the role of the reference's flagship workloads (BASELINE.json
configs: "PyTorch BERT-large fine-tune", "Elastic Llama-3-8B",
"horovod.jax adapter: Llama-3-70B data-parallel") — but built natively for
a TPU mesh rather than wrapped around a torch model:

* **dp** — batch sharding; gradient psum (what the reference's
  DistributedOptimizer did) fused into the step.
* **tp** — Megatron-style tensor parallelism: attention heads and FFN
  columns sharded, one psum after wo and one after w2; vocab-sharded
  embedding + vocab-parallel cross entropy (max/psum over tp).
* **sp** — ring attention over the sequence axis
  (``horovod_tpu.parallel.ring_attention``): KV blocks rotate on the ICI
  ring; activations stay sequence-sharded everywhere else.
* **ep** — optional MoE FFN with all-to-all expert dispatch
  (``horovod_tpu.parallel.moe``); the sequence axis doubles as the expert
  axis (sequence-sharded MoE layout).

Everything is a pure function over a params pytree with layer-stacked
leaves ``[L, ...]`` consumed by ``lax.scan`` (single-layer trace, static
shapes, bf16 activations on the MXU, optional ``jax.checkpoint`` remat).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common import jax_compat  # noqa: F401 - installs shard_map/axis_size shims
from ..parallel.moe import MoeConfig, moe_ffn
from ..parallel.ring_attention import local_attention, ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1344
    max_seq: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"          # activation dtype (MXU-native)
    param_dtype: str = "float32"
    remat: bool = False
    # Remat policy when remat=True: "full" recomputes everything in
    # the backward (default jax.checkpoint), "dots" saves matmul
    # outputs and recomputes only elementwise work, "dots_no_batch"
    # saves only no-batch-dim dots (weights-side products).  Measured
    # per-policy on the flagship config in docs/benchmarks.md.
    remat_policy: str = "full"
    # MoE (0 experts = dense).
    n_experts: int = 0
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    # Mesh axis names (mesh must contain all of them; size 1 is fine).
    dp_axis: str = "dp"
    sp_axis: str = "sp"
    tp_axis: str = "tp"
    # sequence-parallel strategy when sp>1: "ring" (ppermute KV
    # rotation, any head count) or "ulysses" (alltoall head/sequence
    # exchange; the PER-TP-SHARD head counts — n_heads/tp and
    # n_kv_heads/tp — must both divide by sp; composes with flash
    # attention)
    sp_mode: str = "ring"
    # Projection fusion: concatenate the per-shard wq|wk|wv (and
    # w1|w3) weight slices ONCE per step before the layer scan, so
    # each layer issues one [d, (q+2kv)·hd] (resp. [d, 2f]) matmul
    # instead of three (two).  Host param layout is unchanged — the
    # packing happens inside the shard_map body on the local slices,
    # so it is correct for any tp degree.
    fused_qkv: bool = False
    fused_gate: bool = False
    # Vocab-projection dtype: "f32" (safe default), "bf16" (bf16
    # operands, f32 accumulation), or "auto" = bf16 only when the
    # Pallas flash-attention path is active — a bf16 vocab einsum
    # measured ~3% faster on the flash path but collapses the
    # chunked-XLA attention fallback ~12x (an XLA fusion/layout
    # interaction, docs/benchmarks.md), so it must never ride with it.
    logits_dtype: str = "auto"
    # lax.scan unroll factor over the layer stack (1 = no unroll).
    scan_unroll: int = 1
    # Latency-hiding TP matmuls (parallel/collective_matmul.py): the
    # row-parallel wo / w2 products run as an overlapped
    # matmul+reduce-scatter ring followed by a tiled all_gather (same
    # bytes as the plain psum, but the reduce leg hides behind MXU
    # work).  No-op at tp=1, so single-chip programs are unchanged.
    collective_matmul: bool = False

    def __post_init__(self):
        if self.sp_mode not in ("ring", "ulysses"):
            raise ValueError("sp_mode must be 'ring' or 'ulysses', "
                             "got %r" % (self.sp_mode,))
        if self.remat_policy not in ("full", "dots", "dots_no_batch"):
            raise ValueError("remat_policy must be 'full', 'dots' or "
                             "'dots_no_batch', got %r"
                             % (self.remat_policy,))
        if self.logits_dtype not in ("auto", "bf16", "f32"):
            raise ValueError("logits_dtype must be 'auto', 'bf16' or "
                             "'f32', got %r" % (self.logits_dtype,))

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def act_dtype(self):
        return jnp.dtype(self.dtype)

    def moe_config(self) -> MoeConfig:
        return MoeConfig(n_experts=self.n_experts, d_model=self.d_model,
                         d_ff=self.d_ff, top_k=self.top_k,
                         capacity_factor=self.capacity_factor)


# --------------------------------------------------------------------------
# Parameters
# --------------------------------------------------------------------------

def init_params(key, cfg: TransformerConfig):
    """Layer-stacked parameter pytree (host-side, full/unsharded)."""
    pd = jnp.dtype(cfg.param_dtype)
    d, hd = cfg.d_model, cfg.head_dim
    qh, kvh, f, L = cfg.n_heads, cfg.n_kv_heads, cfg.d_ff, cfg.n_layers
    keys = jax.random.split(key, 12)

    def norm(k, shape, fan_in):
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(pd)

    params = {
        "embed": norm(keys[0], (cfg.vocab_size, d), d),
        "ln_f": jnp.ones((d,), pd),
        "layers": {
            "ln1": jnp.ones((L, d), pd),
            "ln2": jnp.ones((L, d), pd),
            "wq": norm(keys[1], (L, d, qh * hd), d),
            "wk": norm(keys[2], (L, d, kvh * hd), d),
            "wv": norm(keys[3], (L, d, kvh * hd), d),
            "wo": norm(keys[4], (L, qh * hd, d), qh * hd),
        },
    }
    if cfg.n_experts == 0:
        params["layers"].update({
            "w1": norm(keys[5], (L, d, f), d),
            "w3": norm(keys[6], (L, d, f), d),
            "w2": norm(keys[7], (L, f, d), f),
        })
    else:
        e = cfg.n_experts
        params["layers"].update({
            "router": norm(keys[8], (L, d, e), d),
            "we1": norm(keys[9], (L, e, d, f), d),
            "we3": norm(keys[10], (L, e, d, f), d),
            "we2": norm(keys[11], (L, e, f, d), f),
        })
    return params


def param_specs(cfg: TransformerConfig):
    """PartitionSpec pytree: Megatron TP sharding + expert sharding.

    Vocab-sharded embedding over tp; attention/FFN column-row sharded over
    tp; experts sharded over the sequence/expert axis; norms replicated.
    """
    from jax.sharding import PartitionSpec as P
    tp, sp = cfg.tp_axis, cfg.sp_axis
    specs = {
        "embed": P(tp, None),
        "ln_f": P(None),
        "layers": {
            "ln1": P(None, None),
            "ln2": P(None, None),
            "wq": P(None, None, tp),
            "wk": P(None, None, tp),
            "wv": P(None, None, tp),
            "wo": P(None, tp, None),
        },
    }
    if cfg.n_experts == 0:
        specs["layers"].update({
            "w1": P(None, None, tp),
            "w3": P(None, None, tp),
            "w2": P(None, tp, None),
        })
    else:
        specs["layers"].update({
            "router": P(None, None, None),
            "we1": P(None, sp, None, None),
            "we3": P(None, sp, None, None),
            "we2": P(None, sp, None, None),
        })
    return specs


# --------------------------------------------------------------------------
# Building blocks (run inside the shard_map body; shapes are per-shard)
# --------------------------------------------------------------------------

def rms_norm(x, scale, eps):
    xf = x.astype(jnp.float32)
    inv = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * inv).astype(x.dtype) * scale.astype(x.dtype)


def _rope(cos, sin, x):
    """Rotate pairs (x interleaved as [..., 2*k])."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def rope_tables(positions, head_dim: int, theta: float, dtype):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    ang = positions[:, None].astype(jnp.float32) * inv_freq[None, :]
    return (jnp.cos(ang)[None, :, None, :].astype(dtype),
            jnp.sin(ang)[None, :, None, :].astype(dtype))


def _sharded_embed_lookup(embed_local, tokens, tp_axis: str):
    """Vocab-sharded embedding gather: local lookup + psum over tp."""
    v_local = embed_local.shape[0]
    start = lax.axis_index(tp_axis) * v_local
    adj = tokens - start
    valid = (adj >= 0) & (adj < v_local)
    adj = jnp.clip(adj, 0, v_local - 1)
    out = jnp.take(embed_local, adj, axis=0)
    out = jnp.where(valid[..., None], out, 0)
    return lax.psum(out, tp_axis)


def vocab_parallel_cross_entropy(logits_local, targets, tp_axis: str):
    """Cross entropy with the vocab axis sharded over tp.

    logits_local: [B, S, V/tp] f32; targets: [B, S] global vocab ids.
    One pmax + two psums over tp — never materializes the full vocab.
    """
    v_local = logits_local.shape[-1]
    start = lax.axis_index(tp_axis) * v_local
    # Per-shard logsumexp FIRST, then combine across tp.  Never write
    # `logits - logits.max(-1)[..., None]` here: XLA fuses the row-max
    # broadcast back into the consumer reduction and recomputes the max
    # per element — measured 24.8 ms vs 2.3 ms for builtin logsumexp on
    # a [4, 2048, 8192] f32 block (v5e).  stop_gradient on the shift:
    # numerical-stability only, its gradient contribution cancels
    # exactly (and pmax has no AD rule).
    lse_local = jax.scipy.special.logsumexp(logits_local, axis=-1)
    m = lax.pmax(lax.stop_gradient(lse_local), tp_axis)
    lse = jnp.log(lax.psum(jnp.exp(lse_local - m), tp_axis)) + m
    adj = targets - start
    valid = (adj >= 0) & (adj < v_local)
    adj = jnp.clip(adj, 0, v_local - 1)
    tgt = jnp.take_along_axis(logits_local, adj[..., None], axis=-1)[..., 0]
    tgt = lax.psum(jnp.where(valid, tgt, 0.0), tp_axis)
    return lse - tgt  # [B, S] per-token nll


def _use_flash_attention() -> bool:
    """Pallas flash attention is the TPU default; interpret-mode is too
    slow for training loops elsewhere (set HOROVOD_FLASH_ATTENTION=0/1
    to force)."""
    import os
    flag = os.environ.get("HOROVOD_FLASH_ATTENTION")
    if flag is not None:
        return flag not in ("0", "false", "False")
    from ..ops.pallas_kernels import _on_tpu
    return _on_tpu()


def _attention_block(x, lp, cfg: TransformerConfig, cos, sin, sp_size):
    b, s, _ = x.shape
    hd = cfg.head_dim
    if "wqkv" in lp:
        # Fused projection: one matmul, split at the LOCAL q/k/v
        # boundaries (exact for any tp: the per-shard fused width is
        # (qh + 2·kvh)·hd/tp and the ratios are preserved).
        qkv = x @ lp["wqkv"].astype(x.dtype)
        tot = qkv.shape[-1]
        q_sz = tot * cfg.n_heads // (cfg.n_heads + 2 * cfg.n_kv_heads)
        kv_sz = (tot - q_sz) // 2
        q = qkv[..., :q_sz].reshape(b, s, -1, hd)
        k = qkv[..., q_sz:q_sz + kv_sz].reshape(b, s, -1, hd)
        v = qkv[..., q_sz + kv_sz:].reshape(b, s, -1, hd)
    else:
        q = (x @ lp["wq"].astype(x.dtype)).reshape(b, s, -1, hd)
        k = (x @ lp["wk"].astype(x.dtype)).reshape(b, s, -1, hd)
        v = (x @ lp["wv"].astype(x.dtype)).reshape(b, s, -1, hd)
    q = _rope(cos, sin, q)
    k = _rope(cos, sin, k)
    if sp_size > 1 and cfg.sp_mode == "ulysses":
        from ..parallel.ulysses import ulysses_attention
        attn_fn = None
        if _use_flash_attention():
            from ..ops.pallas_kernels import flash_attention as attn_fn
        attn = ulysses_attention(q, k, v, axis_name=cfg.sp_axis,
                                 causal=True, attn_fn=attn_fn)
    elif sp_size > 1:
        attn = ring_attention(q, k, v, axis_name=cfg.sp_axis, causal=True)
    elif _use_flash_attention():
        # Pallas fused attention on TPU (ops/pallas_kernels.py):
        # O(seq) HBM forward + Pallas backward kernels (dq, dk/dv);
        # measured ~5x over XLA autodiff at seq 8192 on one chip
        # (docs/benchmarks.md)
        from ..ops.pallas_kernels import flash_attention
        attn = flash_attention(q, k, v, causal=True)
    else:
        attn = local_attention(q, k, v, causal=True)
    attn = attn.reshape(b, s, -1)
    # Row-sharded wo: partial sums live on each tp shard.
    return _row_parallel_product(attn, lp["wo"].astype(x.dtype), cfg)


def _row_parallel_product(x, w, cfg: TransformerConfig):
    """``psum(x @ w, tp)`` for a row-sharded weight, optionally as the
    latency-hiding matmul+reduce-scatter ring + tiled all_gather
    (``cfg.collective_matmul``): identical math and total bytes, but
    the reduce leg overlaps the MXU work instead of serializing after
    it.  Plain psum at tp=1 or when rows do not divide the axis."""
    b, s, _ = x.shape
    tp = lax.axis_size(cfg.tp_axis)
    if cfg.collective_matmul and tp > 1 and (b * s) % tp == 0:
        from ..parallel.collective_matmul import matmul_reduce_scatter
        flat = x.reshape(b * s, x.shape[-1])
        part = matmul_reduce_scatter(flat, w, cfg.tp_axis)
        full = lax.all_gather(part, cfg.tp_axis, tiled=True)
        return full.reshape(b, s, w.shape[-1])
    return lax.psum(x @ w, cfg.tp_axis)


def _dense_ffn(h, lp, cfg: TransformerConfig):
    if "w13" in lp:
        ag = h @ lp["w13"].astype(h.dtype)
        a, g = jnp.split(ag, 2, axis=-1)
        a = jax.nn.silu(a)
    else:
        a = jax.nn.silu(h @ lp["w1"].astype(h.dtype))
        g = h @ lp["w3"].astype(h.dtype)
    return _row_parallel_product(a * g, lp["w2"].astype(h.dtype), cfg)


def _moe_block(h, lp, cfg: TransformerConfig, sp_size):
    b, s, d = h.shape
    flat = h.reshape(b * s, d)
    moe_params = {"router": lp["router"], "w1": lp["we1"],
                  "w3": lp["we3"], "w2": lp["we2"]}
    axis = cfg.sp_axis if sp_size > 1 else None
    y, aux = moe_ffn(moe_params, flat, cfg.moe_config(), axis_name=axis)
    return y.reshape(b, s, d), aux


def forward(params, tokens, cfg: TransformerConfig):
    """Per-shard forward: tokens [B_loc, S_loc] -> (logits_local, aux).

    Must run inside a shard_map over a mesh containing
    (dp_axis, sp_axis, tp_axis).  logits are [B, S, V/tp] in f32.
    """
    sp_size = lax.axis_size(cfg.sp_axis)
    s_loc = tokens.shape[1]
    pos = lax.axis_index(cfg.sp_axis) * s_loc + jnp.arange(s_loc)
    cos, sin = rope_tables(pos, cfg.head_dim, cfg.rope_theta, cfg.act_dtype)

    x = _sharded_embed_lookup(params["embed"], tokens, cfg.tp_axis)
    x = x.astype(cfg.act_dtype)
    if cfg.collective_matmul:
        # The RS+AG ring's all_gather output is vma-varying over tp
        # (identical values, but the tracker cannot prove it); the
        # scan carry must enter with the same varying axes.
        from ..parallel.ring_attention import pvary_missing
        x = pvary_missing(x, (cfg.tp_axis,))

    layers = params["layers"]
    if cfg.fused_qkv:
        layers = dict(layers)
        layers["wqkv"] = jnp.concatenate(
            [layers.pop("wq"), layers.pop("wk"), layers.pop("wv")],
            axis=-1)
    if cfg.fused_gate and cfg.n_experts == 0:
        layers = dict(layers)
        layers["w13"] = jnp.concatenate(
            [layers.pop("w1"), layers.pop("w3")], axis=-1)

    def layer(carry, lp):
        x, aux = carry
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        x = x + _attention_block(h, lp, cfg, cos, sin, sp_size)
        h = rms_norm(x, lp["ln2"], cfg.norm_eps)
        if cfg.n_experts == 0:
            x = x + _dense_ffn(h, lp, cfg)
        else:
            y, a = _moe_block(h, lp, cfg, sp_size)
            x = x + y
            aux = aux + a
        return (x, aux), None

    if cfg.remat:
        pol = {"full": None,
               "dots": jax.checkpoint_policies.dots_saveable,
               "dots_no_batch":
                   jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
               }[cfg.remat_policy]
        layer_fn = (jax.checkpoint(layer, policy=pol) if pol is not None
                    else jax.checkpoint(layer))
    else:
        layer_fn = layer
    # The MoE aux accumulator acquires V:(dp, sp) from the routed
    # tokens; the carry must enter with the same varying axes under
    # vma tracking (guarded no-op in untracked traces).
    from ..parallel.ring_attention import pvary_missing
    aux0 = pvary_missing(jnp.zeros((), jnp.float32),
                         (cfg.dp_axis, cfg.sp_axis)) \
        if cfg.n_experts else jnp.zeros((), jnp.float32)
    (x, aux), _ = lax.scan(layer_fn, (x, aux0), layers,
                           unroll=max(1, cfg.scan_unroll))
    x = rms_norm(x, params["ln_f"], cfg.norm_eps)
    # Vocab projection dtype: bf16 operands with f32 accumulation only
    # on the flash path ("auto"); with the chunked-XLA attention
    # fallback the bf16 form collapses throughput ~12x (159k -> 13.6k
    # tok/s at seq 2048, v5e — an XLA fusion/layout interaction), so
    # f32 stays the fallback-path form.
    bf16_logits = (cfg.logits_dtype == "bf16"
                   or (cfg.logits_dtype == "auto"
                       and _use_flash_attention()))
    if bf16_logits:
        logits = jnp.matmul(
            x.astype(cfg.act_dtype),
            params["embed"].astype(cfg.act_dtype).T,
            preferred_element_type=jnp.float32)
    else:
        logits = (x.astype(jnp.float32)
                  @ params["embed"].astype(jnp.float32).T)
    return logits, aux / cfg.n_layers


def loss_fn(params, batch, cfg: TransformerConfig):
    """Per-shard mean nll (+ MoE aux); psum-averaged over dp and sp."""
    tokens, targets = batch["tokens"], batch["targets"]
    logits, aux = forward(params, tokens, cfg)
    nll = vocab_parallel_cross_entropy(logits, targets, cfg.tp_axis)
    loss = nll.mean() + cfg.aux_loss_weight * aux
    return lax.pmean(loss, (cfg.dp_axis, cfg.sp_axis))


# --------------------------------------------------------------------------
# Train step over the mesh
# --------------------------------------------------------------------------

def opt_spec_tree(opt_state, params_host, specs):
    """Sharding specs for optimizer state: any subtree isomorphic to
    the params tree (adam mu/nu, etc.) inherits the param ``specs``;
    everything else (step counters...) is replicated.  Shared by every
    model family's step builder."""
    from jax.sharding import PartitionSpec as P
    pdef = jax.tree.structure(params_host)

    def rec(node):
        try:
            if jax.tree.structure(node) == pdef:
                return specs
        except Exception:  # noqa: BLE001 - non-pytree leaves
            pass
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*[rec(c) for c in node])
        if isinstance(node, tuple):
            return tuple(rec(c) for c in node)
        if isinstance(node, list):
            return [rec(c) for c in node]
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return P()

    return rec(opt_state)


def make_train_step(cfg: TransformerConfig, mesh, optimizer,
                    donate: bool = True, split_optimizer: bool = False):
    """Jitted SPMD train step over ``mesh`` (axes dp/sp/tp as configured).

    Returns ``(build, shard_batch)``; ``build(params_host)`` returns
    ``(step, params, opt_state)`` with
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``.
    Gradients are psum'ed over (dp, sp) — tp/ep-sharded leaves stay
    sharded, the framework's DP story fused into the compiled program.

    ``split_optimizer=True`` compiles the backward and the optimizer
    update as TWO programs called back to back — the anti-lever: it
    exists to MEASURE what fusing the update into the step is worth
    (the fused default lets XLA overlap the elementwise update with
    the tail of the backward and skip materializing the full gradient
    pytree between programs).
    """
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    specs = param_specs(cfg)
    batch_spec = {"tokens": P(cfg.dp_axis, cfg.sp_axis),
                  "targets": P(cfg.dp_axis, cfg.sp_axis)}
    opt_specs = None  # filled after init

    def local_grad(params, batch):
        # vma-tracked AD (check_vma=True below) differentiates the
        # dp/sp pmean in loss_fn with the exact collective transposes,
        # so the per-shard grads ARE the global-batch gradient — no
        # manual combine.  (The previous check_vma=False form psum'ed
        # grads over (dp, sp) on top of already-combined cotangents,
        # scaling the update by dp*sp: r4 correctness fix, verified by
        # the sharded-vs-single-device gradient test.)
        return jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg))(params)

    def local_update(params, opt_state, grads):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def local_step(params, opt_state, batch):
        # Composed from the same two pieces the split path jits
        # separately, so the fused/split A/B always measures program
        # structure, never diverged math.
        loss, grads = local_grad(params, batch)
        params, opt_state = local_update(params, opt_state, grads)
        return params, opt_state, loss

    def _opt_spec_tree(opt_state, params_host):
        return opt_spec_tree(opt_state, params_host, specs)

    def build(params_host):
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params_host, specs)
        opt_state = optimizer.init(params_host)
        o_specs = _opt_spec_tree(opt_state, params_host)
        opt_state = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x),
                                        NamedSharding(mesh, s))
            if hasattr(x, "shape") else x,
            opt_state, o_specs)
        if split_optimizer:
            g_mapped = jax.shard_map(
                local_grad, mesh=mesh,
                in_specs=(specs, batch_spec),
                out_specs=(P(), specs), check_vma=True)
            u_mapped = jax.shard_map(
                local_update, mesh=mesh,
                in_specs=(specs, o_specs, specs),
                out_specs=(specs, o_specs), check_vma=True)
            g_step = jax.jit(g_mapped)
            u_step = jax.jit(u_mapped,
                             donate_argnums=(0, 1, 2) if donate else ())

            def step(params, opt_state, batch):
                loss, grads = g_step(params, batch)
                params, opt_state = u_step(params, opt_state, grads)
                return params, opt_state, loss
            return step, params, opt_state
        mapped = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(specs, o_specs, batch_spec),
            out_specs=(specs, o_specs, P()),
            check_vma=True)
        step = jax.jit(mapped, donate_argnums=(0, 1) if donate else ())
        return step, params, opt_state

    def shard_batch(batch):
        from jax.sharding import NamedSharding
        return jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x),
                                        NamedSharding(mesh, s)),
            batch, batch_spec)

    return build, shard_batch
