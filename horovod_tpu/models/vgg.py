"""VGG family (VGG-16 flagship) in flax.

Parity with the reference's bandwidth-bound benchmark workload: its
scaling study (``docs/benchmarks.rst``) singles out VGG-16 as the model
whose ~138M dense parameters stress the allreduce path (~68–79%
scaling efficiency vs ~90% for ResNet) — the workload that makes
tensor fusion and hierarchical/compressed allreduce earn their keep.
TPU-first choices: NHWC, bf16 on the MXU, the classifier folded to
matmuls.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# conv widths per stage; 'M' = 2x2 max pool (the torchvision cfgs)
CFGS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512,
         "M"),
    13: (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512,
         512, "M", 512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512,
         512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    classifier_width: int = 4096

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for item in self.cfg:
            if item == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(item, (3, 3), padding=1,
                            dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        for _ in range(2):
            x = nn.relu(nn.Dense(self.classifier_width,
                                 dtype=self.dtype)(x))
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


def create_vgg16(num_classes: int = 1000, dtype=jnp.bfloat16) -> VGG:
    return VGG(cfg=CFGS[16], num_classes=num_classes, dtype=dtype)


def create_vgg(depth: int, num_classes: int = 1000,
               dtype=jnp.bfloat16) -> VGG:
    return VGG(cfg=CFGS[depth], num_classes=num_classes, dtype=dtype)


def vgg_loss_fn(model: VGG, variables, batch, train: bool = True):
    """Cross-entropy on {'x','y'}.  Same ``(nll, new_state)`` contract
    as ``resnet_loss_fn`` so the benchmark harnesses take either model
    (VGG has no mutable batch-norm state, so new_state is empty)."""
    logits = model.apply(variables, batch["x"], train=train)
    one_hot = jax.nn.one_hot(batch["y"], logits.shape[-1])
    nll = -jnp.mean(jnp.sum(one_hot *
                            jax.nn.log_softmax(logits), axis=-1))
    return nll, {}
