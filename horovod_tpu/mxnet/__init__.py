"""MXNet adapter: ``import horovod_tpu.mxnet as hvd``.

Reference parity: ``horovod/mxnet/__init__.py`` + ``mpi_ops.py`` (native
extension ``horovod/mxnet/mpi_ops.cc``/``adapter.cc``) — the same
surface: init/rank/size, the collectives with async/in-place variants,
``DistributedOptimizer`` (wraps an ``mx.optimizer.Optimizer``,
allreducing gradients inside ``update``/``update_multi_precision``),
``DistributedTrainer`` (gluon ``Trainer`` whose ``_allreduce_grads``
averages over the world), and ``broadcast_parameters``.

MXNet is optional in this environment: every entry point that does not
strictly need the mxnet runtime (the collectives, the optimizer wrapper,
parameter broadcast) is duck-typed over NDArray-likes; only
``DistributedTrainer`` requires gluon and raises ImportError without it.
"""

from __future__ import annotations

from ..common.basics import (shutdown, is_initialized, rank, size,
                             local_rank, local_size, cross_rank,
                             cross_size, is_homogeneous, topology,
                             start_timeline, stop_timeline, xla_built,
                             tcp_built, gloo_built, mpi_built,
                             nccl_built, ccl_built, ddl_built,
                             cuda_built, rocm_built, mpi_enabled,
                             mpi_threads_supported)
from ..common.basics import init as _base_init
from ..common.process_sets import (ProcessSet, global_process_set,
                                   add_process_set, remove_process_set,
                                   process_set_by_id, process_set_ids)
from ..ops.engine import HorovodInternalError
from ..ops.xla_ops import ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM
from .functions import (allgather_object, broadcast_object,
                        broadcast_parameters)
from .mpi_ops import (allgather, allgather_async, allreduce, allreduce_,
                      allreduce_async, allreduce_async_, alltoall,
                      alltoall_async, barrier, broadcast, broadcast_,
                      broadcast_async, broadcast_async_,
                      grouped_allgather, grouped_allgather_async,
                      grouped_allreduce, grouped_allreduce_async,
                      grouped_reducescatter,
                      grouped_reducescatter_async, join,
                      poll, reducescatter, reducescatter_async,
                      synchronize)

try:  # optional dependency
    import mxnet as _mx  # type: ignore
except ImportError:  # pragma: no cover
    _mx = None

Sum = SUM
Average = AVERAGE
Min = MIN
Max = MAX
Product = PRODUCT
Adasum = ADASUM


def init(*args, **kwargs):
    """``hvd.init()`` — multi-process (tcp) controller by default, like
    the torch adapter: mxnet semantics are per-process NDArrays."""
    kwargs.setdefault("controller", "tcp")
    return _base_init(*args, **kwargs)


class DistributedOptimizer:
    """Wraps an ``mx.optimizer.Optimizer``: gradients are averaged over
    the world before the inner update (reference
    ``horovod/mxnet/__init__.py`` ``DistributedOptimizer``).

    Duck-typed: the inner optimizer only needs ``update`` (and
    optionally ``update_multi_precision``); works with mxnet optimizers
    and with test doubles alike.
    """

    def __init__(self, optimizer, gradient_predivide_factor: float = 1.0,
                 num_groups: int = 0, process_set=None):
        self._optimizer = optimizer
        self._predivide = float(gradient_predivide_factor)
        self._num_groups = num_groups
        self._process_set = process_set

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _allreduce_grads(self, grads, names):
        ps_size = (self._process_set.size()
                   if self._process_set is not None else size())
        if ps_size <= 1:
            return
        # predivide factor splits the averaging between pre/post scaling
        # (reference torch/mxnet semantics): Sum with prescale 1/f,
        # postscale f/size.
        pre = 1.0 / self._predivide
        post = self._predivide / ps_size
        if self._num_groups > 0:
            pairs = list(zip(grads, names))
            groups = [pairs[i::self._num_groups]
                      for i in range(self._num_groups)]
            for gi, group in enumerate(g for g in groups if g):
                tensors = [g for g, _ in group]
                outs = grouped_allreduce(
                    tensors, op=SUM, prescale_factor=pre,
                    postscale_factor=post,
                    name="DistributedOptimizer.grad_group.%d" % gi,
                    process_set=self._process_set)
                for (g, _), o in zip(group, outs):
                    g[:] = o
        else:
            handles = [allreduce_async_(
                g, op=SUM, prescale_factor=pre, postscale_factor=post,
                name="DistributedOptimizer.gradient.%s" % n,
                process_set=self._process_set)
                for g, n in zip(grads, names)]
            for h in handles:
                h.wait()

    def _do_update(self, method, index, weight, grad, state):
        if isinstance(index, (tuple, list)):
            grads = list(grad)
            names = [str(i) for i in index]
        else:
            grads = [grad]
            names = [str(index)]
        self._allreduce_grads(grads, names)
        return method(index, weight, grad, state)

    def update(self, index, weight, grad, state):
        return self._do_update(self._optimizer.update, index, weight,
                               grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        method = getattr(self._optimizer, "update_multi_precision",
                         self._optimizer.update)
        return self._do_update(method, index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


if _mx is not None:  # pragma: no cover - needs mxnet runtime

    class DistributedTrainer(_mx.gluon.Trainer):
        """gluon ``Trainer`` averaging gradients over the world
        (reference ``DistributedTrainer``): scales the loss down by
        ``size()`` via ``rescale_grad`` and allreduces with Sum."""

        def __init__(self, params, optimizer, optimizer_params=None,
                     gradient_predivide_factor: float = 1.0,
                     process_set=None, **kwargs):
            if isinstance(optimizer, DistributedOptimizer):
                optimizer = optimizer._optimizer
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params, **kwargs)
            self._hvd_predivide = float(gradient_predivide_factor)
            self._hvd_process_set = process_set
            self._scale /= (process_set.size() if process_set is not None
                            else size())

        def _allreduce_grads(self):
            ps = self._hvd_process_set
            ps_size = ps.size() if ps is not None else size()
            if ps_size <= 1:
                return
            pre = 1.0 / self._hvd_predivide
            post = self._hvd_predivide  # _scale already divided by size
            handles = []
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for g in param.list_grad():
                        handles.append(allreduce_async_(
                            g, op=SUM, prescale_factor=pre,
                            postscale_factor=post,
                            name="DistributedTrainer.grad.%d" % i,
                            process_set=ps))
            for h in handles:
                h.wait()

else:

    def DistributedTrainer(*args, **kwargs):  # type: ignore[misc]
        raise ImportError(
            "DistributedTrainer requires mxnet (gluon); mxnet is not "
            "installed in this environment. The rest of the "
            "horovod_tpu.mxnet surface works without it.")
