"""MXNet parameter/object broadcast helpers.

Reference parity: ``horovod/mxnet/__init__.py`` —
``broadcast_parameters`` accepts a gluon ``ParameterDict`` or a plain
``dict`` of NDArrays (the reference dispatches on both), and
``broadcast_object`` pickles arbitrary Python state across the wire.
"""

from __future__ import annotations

from typing import Any, Optional

from ..jax.functions import allgather_object as _allgather_object
from ..jax.functions import broadcast_object as _broadcast_object
from . import mpi_ops


def _is_parameter_dict(params) -> bool:
    # gluon ParameterDict / gluon2 dict-of-Parameter: values expose
    # list_data()/data() rather than being NDArrays themselves.
    try:
        vals = list(params.values())
    except AttributeError:
        return False
    return bool(vals) and all(hasattr(v, "data") and not hasattr(v, "asnumpy")
                              for v in vals)


def broadcast_parameters(params, root_rank: int = 0, prefix: str = ""):
    """In-place broadcast of model parameters from ``root_rank``.

    Accepts a ``dict`` name→NDArray (e.g. from ``get_params``), or a
    gluon ``ParameterDict``-like mapping name→Parameter.
    """
    handles = []
    if _is_parameter_dict(params):
        for name in sorted(params.keys()):
            p = params[name]
            try:
                tensors = p.list_data()
            except Exception:
                tensors = [p.data()]
            for i, t in enumerate(tensors):
                handles.append(mpi_ops.broadcast_async_(
                    t, root_rank,
                    name="%sbroadcast_parameters.%s.%d" % (prefix, name, i)))
    elif isinstance(params, dict):
        for name in sorted(params.keys()):
            t = params[name]
            if t is None:
                continue
            handles.append(mpi_ops.broadcast_async_(
                t, root_rank,
                name="%sbroadcast_parameters.%s" % (prefix, name)))
    else:
        raise ValueError("invalid params of type %r" % type(params))
    for h in handles:
        h.wait()


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    return _broadcast_object(obj, root_rank, name=name)


def allgather_object(obj: Any, name: Optional[str] = None):
    return _allgather_object(obj, name=name)
