"""MXNet collective ops over the native core.

Reference parity: ``horovod/mxnet/mpi_ops.py`` (+ the native extension
``horovod/mxnet/mpi_ops.cc`` / ``adapter.cc`` / ``tensor_util.cc``) —
every op has a synchronous form, an ``*_async`` form returning a handle,
and in-place ``*_`` variants.  The reference integrates with MXNet's
dependency engine; here NDArrays cross the wire as their numpy
realization (``asnumpy()``), which is the correct host-side view for a
TPU build whose device compute path is the JAX adapter.

MXNet itself is an optional dependency: the ops are duck-typed over
"NDArray-like" values (anything with ``asnumpy()``; plain numpy arrays
also work), so the adapter logic is fully testable without an mxnet
runtime, and binds to real ``mx.nd.NDArray`` when mxnet is installed.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..ops import api as _api
from ..ops.xla_ops import AVERAGE, SUM

try:  # optional dependency
    import mxnet as _mx  # type: ignore
except ImportError:  # pragma: no cover - exercised when mxnet missing
    _mx = None

__all__ = [
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_async",
    "grouped_allgather", "grouped_allgather_async",
    "grouped_reducescatter", "grouped_reducescatter_async",
    "allgather", "allgather_async", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_", "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "barrier", "join",
    "synchronize", "poll",
]


def _to_np(t) -> np.ndarray:
    if hasattr(t, "asnumpy"):
        return t.asnumpy()
    return np.asarray(t)


def _from_np(arr: np.ndarray, like):
    """Rebuild an output in the input's container type."""
    arr = np.ascontiguousarray(arr)
    if _mx is not None and isinstance(like, _mx.nd.NDArray):
        return _mx.nd.array(arr, ctx=like.context, dtype=arr.dtype)
    if hasattr(like, "_from_numpy_"):  # test shims / custom containers
        return like._from_numpy_(arr)
    return arr


def _write_inplace(out, arr: np.ndarray):
    out[:] = _from_np(arr.reshape(_to_np(out).shape), out)
    return out


class MXHandle:
    """Async handle returning NDArray-likes (reference handle table in
    ``horovod/mxnet/mpi_ops.cc``)."""

    def __init__(self, inner, like=None, out=None):
        self._inner = inner
        self._like = like
        self._out = out

    def poll(self) -> bool:
        return self._inner.poll()

    def wait(self, timeout: Optional[float] = None):
        res = self._inner.wait(timeout)
        splits = None
        if isinstance(res, tuple):
            res, splits = res
        if isinstance(res, list):
            # Ragged result (in-process uneven reducescatter, or
            # alltoall with per-rank shapes): one array per rank; no
            # in-place target applies.  Keep (output, recv_splits).
            converted = [_from_np(np.ascontiguousarray(np.asarray(r)),
                                  self._like) for r in res]
            return (converted, splits) if splits is not None else converted
        arr = np.ascontiguousarray(np.asarray(res))
        if self._out is not None:
            t = _write_inplace(self._out, arr)
        else:
            t = _from_np(arr, self._like)
        return (t, splits) if splits is not None else t


def synchronize(handle: MXHandle):
    return handle.wait()


def poll(handle: MXHandle) -> bool:
    return handle.poll()


# -- allreduce -------------------------------------------------------------

def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set=None) -> MXHandle:
    h = _api.allreduce_async(_to_np(tensor), average, name, op,
                             prescale_factor, postscale_factor,
                             process_set)
    return MXHandle(h, like=tensor)


def allreduce_async_(tensor, average=None, name: Optional[str] = None,
                     op=None, prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     process_set=None) -> MXHandle:
    h = _api.allreduce_async(_to_np(tensor), average, name, op,
                             prescale_factor, postscale_factor,
                             process_set)
    return MXHandle(h, like=tensor, out=tensor)


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              process_set=None):
    return allreduce_async(tensor, average, name, op, prescale_factor,
                           postscale_factor, process_set).wait()


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0,
               process_set=None):
    return allreduce_async_(tensor, average, name, op, prescale_factor,
                            postscale_factor, process_set).wait()


def grouped_allreduce_async(tensors: Sequence, average=None,
                            name: Optional[str] = None, op=None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set=None) -> List[MXHandle]:
    hs = _api.grouped_allreduce_async(
        [_to_np(t) for t in tensors], average, name, op,
        prescale_factor, postscale_factor, process_set)
    return [MXHandle(h, like=t) for h, t in zip(hs, tensors)]


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set=None) -> List:
    return [h.wait() for h in grouped_allreduce_async(
        tensors, average, name, op, prescale_factor, postscale_factor,
        process_set)]


def grouped_allgather_async(tensors: Sequence,
                            name: Optional[str] = None,
                            process_set=None) -> List[MXHandle]:
    hs = _api.grouped_allgather_async(
        [_to_np(t) for t in tensors], name, process_set)
    return [MXHandle(h, like=t) for h, t in zip(hs, tensors)]


def grouped_allgather(tensors, name=None, process_set=None) -> List:
    return [h.wait() for h in grouped_allgather_async(
        tensors, name, process_set)]


def grouped_reducescatter_async(tensors: Sequence, op=None,
                                name: Optional[str] = None,
                                process_set=None) -> List[MXHandle]:
    hs = _api.grouped_reducescatter_async(
        [_to_np(t) for t in tensors], op, name, process_set)
    return [MXHandle(h, like=t) for h, t in zip(hs, tensors)]


def grouped_reducescatter(tensors, op=None, name=None,
                          process_set=None) -> List:
    return [h.wait() for h in grouped_reducescatter_async(
        tensors, op, name, process_set)]


# -- allgather -------------------------------------------------------------

def allgather_async(tensor, name: Optional[str] = None,
                    process_set=None) -> MXHandle:
    h = _api.allgather_async(_to_np(tensor), name, process_set)
    return MXHandle(h, like=tensor)


def allgather(tensor, name=None, process_set=None):
    return allgather_async(tensor, name, process_set).wait()


# -- broadcast -------------------------------------------------------------

def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set=None) -> MXHandle:
    h = _api.broadcast_async(_to_np(tensor), root_rank, name,
                             process_set)
    return MXHandle(h, like=tensor)


def broadcast_async_(tensor, root_rank: int, name: Optional[str] = None,
                     process_set=None) -> MXHandle:
    h = _api.broadcast_async(_to_np(tensor), root_rank, name,
                             process_set)
    return MXHandle(h, like=tensor, out=tensor)


def broadcast(tensor, root_rank: int, name=None, process_set=None):
    return broadcast_async(tensor, root_rank, name, process_set).wait()


def broadcast_(tensor, root_rank: int, name=None, process_set=None):
    return broadcast_async_(tensor, root_rank, name, process_set).wait()


# -- alltoall / reducescatter ----------------------------------------------

def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set=None) -> MXHandle:
    if splits is not None and hasattr(splits, "asnumpy"):
        splits = splits.asnumpy().tolist()
    h = _api.alltoall_async(_to_np(tensor), splits, name, process_set)
    return MXHandle(h, like=tensor)


def alltoall(tensor, splits=None, name=None, process_set=None):
    res = alltoall_async(tensor, splits, name, process_set).wait()
    if splits is None and isinstance(res, tuple):
        return res[0]
    return res


def reducescatter_async(tensor, op=SUM, name: Optional[str] = None,
                        process_set=None) -> MXHandle:
    h = _api.reducescatter_async(_to_np(tensor), op, name, process_set)
    return MXHandle(h, like=tensor)


def reducescatter(tensor, op=SUM, name=None, process_set=None):
    return reducescatter_async(tensor, op, name, process_set).wait()


# -- barrier / join --------------------------------------------------------

def barrier(process_set=None):
    return _api.barrier(process_set)


def join(device=None) -> int:
    return _api.join(device)
