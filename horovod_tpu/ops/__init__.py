"""Collective op layer: XLA executables + async fusion engine (reference:
horovod/common/ops/)."""
