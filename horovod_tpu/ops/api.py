"""Eager (host-side) collective API — the 8-op surface.

Reference parity: ``hvd.allreduce / grouped_allreduce / allgather /
broadcast / alltoall / reducescatter / join / barrier`` plus their
``*_async`` variants and ``synchronize``/``poll`` (reference:
``horovod/torch/mpi_ops.py`` + ``horovod/tensorflow/mpi_ops.py`` surfaces,
backed by ``EnqueueTensor*`` in ``horovod/common/operations.cc``).

In the in-process SPMD world, a collective's input is "rank-major
stacked": ``x[r]`` is rank r's contribution (a list of per-rank tensors is
also accepted; allgather may be ragged in dim 0).  Ops are enqueued to the
background engine, fused, and executed as compiled XLA collectives; the
``*_async`` forms return handles resolved by the cycle thread.

In the multi-process (tcp) world the same calls route through the native
C++ core, which negotiates readiness across ranks before executing.
"""

from __future__ import annotations

import collections
import itertools
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..common import basics
from ..common.process_sets import ProcessSet, global_process_set
from . import xla_ops
from .engine import CollectiveHandle
from .xla_ops import (ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM,
                      handle_average_backwards_compatibility)

__all__ = [
    "SUM", "AVERAGE", "MIN", "MAX", "PRODUCT", "ADASUM",
    "allreduce", "allreduce_async", "grouped_allreduce",
    "grouped_allreduce_async", "allgather", "allgather_async",
    "broadcast", "broadcast_async", "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "barrier", "join",
    "synchronize", "poll",
]


# Auto names must be identical across ranks: the multi-process
# controller negotiates collectives by exact name match, so unnamed ops
# get a per-op-type sequence number (deterministic when all ranks issue
# the same call sequence — the reference's contract for unnamed ops).
_name_counters = collections.defaultdict(itertools.count)


def _auto_name(prefix: str, name: Optional[str]) -> str:
    return name if name else \
        "%s.noname.%d" % (prefix, next(_name_counters[prefix]))


def _ps_id(process_set: Optional[ProcessSet]) -> int:
    ps = process_set or global_process_set
    if ps.process_set_id is None:
        raise ValueError("process set %r is not registered" % ps)
    return ps.process_set_id


def _stack(tensor, ps_size: int):
    """Accept a rank-major stacked array or a list of per-rank tensors."""
    if isinstance(tensor, (list, tuple)):
        arr = jnp.stack([jnp.asarray(t) for t in tensor])
    else:
        arr = jnp.asarray(tensor)
    if arr.shape[0] != ps_size:
        raise ValueError(
            "expected rank-major stacked input with leading dim %d (one "
            "slice per rank), got shape %s" % (ps_size, arr.shape))
    return arr


def _engine():
    return basics._get_engine()


def _tcp_mode() -> bool:
    """Multi-process world, host payload plane: collectives route
    through the native core and each call passes THIS rank's tensor
    (reference semantics), not a rank-major stack."""
    return (basics.is_initialized()
            and basics._controller_mode() == "tcp")


def _mh_mode() -> bool:
    """Multi-process world, device payload plane: the native core
    negotiates order, the multihost engine executes XLA collectives
    over the global mesh.  Per-rank tensor semantics like tcp mode."""
    return (basics.is_initialized()
            and basics._controller_mode() == "multihost")


def _np(tensor):
    return np.ascontiguousarray(np.asarray(tensor))


# -- allreduce -------------------------------------------------------------

def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: Optional[ProcessSet] = None
                    ) -> CollectiveHandle:
    red_op = handle_average_backwards_compatibility(op, average)
    ps = process_set or global_process_set
    if _mh_mode() and red_op != ADASUM:
        return basics._get_mh_engine().enqueue_allreduce(
            _auto_name("allreduce", name), tensor, red_op=red_op,
            prescale=prescale_factor, postscale=postscale_factor,
            process_set_id=_ps_id(process_set))
    if _tcp_mode() or _mh_mode():
        # Adasum in multihost mode rides the host plane: the native
        # core's TreeAdasum is the projection-math implementation.
        return basics._get_tcp_core().allreduce_async(
            _np(tensor), _auto_name("allreduce", name), op=red_op,
            prescale=prescale_factor, postscale=postscale_factor,
            process_set_id=_ps_id(process_set))
    if red_op == ADASUM:
        from ..utils.adasum import adasum_reduce_stacked
        stacked = _stack(tensor, ps.size())
        h = CollectiveHandle(_auto_name("allreduce", name))
        try:
            h._set_result(adasum_reduce_stacked(stacked))
        except Exception as exc:  # noqa: BLE001
            h._set_error(exc)
        return h
    stacked = _stack(tensor, ps.size())
    return _engine().enqueue_allreduce(
        _auto_name("allreduce", name), stacked, red_op,
        prescale_factor, postscale_factor, _ps_id(process_set))


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: Optional[ProcessSet] = None):
    """Reduce across ranks; returns the reduced tensor (replicated)."""
    return allreduce_async(tensor, average, name, op, prescale_factor,
                           postscale_factor, process_set).wait()


# -- grouped allreduce -----------------------------------------------------

def grouped_allreduce_async(tensors: Sequence, average=None,
                            name: Optional[str] = None, op=None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: Optional[ProcessSet] = None
                            ) -> List[CollectiveHandle]:
    """Enqueue a group atomically so fusion packs them together
    (reference: group_table.cc / hvd.grouped_allreduce)."""
    red_op = handle_average_backwards_compatibility(op, average)
    ps_id = _ps_id(process_set)
    ps = process_set or global_process_set
    base = _auto_name("grouped_allreduce", name)
    names = ["%s.%d" % (base, i) for i in range(len(tensors))]
    if _mh_mode() and red_op != ADASUM:
        core = basics._get_tcp_core()
        core.register_group(names)
        eng = basics._get_mh_engine()
        return [eng.enqueue_allreduce(
            n, t, red_op=red_op, prescale=prescale_factor,
            postscale=postscale_factor, process_set_id=ps_id)
            for t, n in zip(tensors, names)]
    if _tcp_mode() or _mh_mode():
        core = basics._get_tcp_core()
        # Register the group so the controller negotiates/fuses it
        # atomically (reference: group_table.cc).
        core.register_group(names)
        return [core.allreduce_async(
            _np(t), n, op=red_op, prescale=prescale_factor,
            postscale=postscale_factor, process_set_id=ps_id)
            for t, n in zip(tensors, names)]
    handles = []
    for t, n in zip(tensors, names):
        handles.append(_engine().enqueue_allreduce(
            n, _stack(t, ps.size()), red_op,
            prescale_factor, postscale_factor, ps_id))
    return handles


def grouped_allreduce(tensors: Sequence, average=None, name=None, op=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set: Optional[ProcessSet] = None):
    return [h.wait() for h in grouped_allreduce_async(
        tensors, average, name, op, prescale_factor, postscale_factor,
        process_set)]


# -- allgather -------------------------------------------------------------

def allgather_async(tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None
                    ) -> CollectiveHandle:
    ps = process_set or global_process_set
    if _mh_mode():
        return basics._get_mh_engine().enqueue_allgather(
            _auto_name("allgather", name), tensor,
            process_set_id=_ps_id(process_set))
    if _tcp_mode():
        return basics._get_tcp_core().allgather_async(
            _np(tensor), _auto_name("allgather", name),
            process_set_id=_ps_id(process_set))
    if isinstance(tensor, (list, tuple)):
        per_rank = [jnp.asarray(t) for t in tensor]
        if len(per_rank) != ps.size():
            raise ValueError("need one tensor per rank")
    else:
        arr = jnp.asarray(tensor)
        per_rank = [arr[r] for r in range(ps.size())]
    return _engine().enqueue_allgather(
        _auto_name("allgather", name), per_rank, _ps_id(process_set))


def allgather(tensor, name=None, process_set: Optional[ProcessSet] = None):
    """Gather per-rank tensors, concatenated on dim 0 (ragged dim-0 ok)."""
    return allgather_async(tensor, name, process_set).wait()


# -- broadcast -------------------------------------------------------------

def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None
                    ) -> CollectiveHandle:
    ps = process_set or global_process_set
    if _mh_mode():
        return basics._get_mh_engine().enqueue_broadcast(
            _auto_name("broadcast", name), tensor, root_rank=root_rank,
            process_set_id=_ps_id(process_set))
    if _tcp_mode():
        return basics._get_tcp_core().broadcast_async(
            _np(tensor), _auto_name("broadcast", name),
            root_rank=root_rank, process_set_id=_ps_id(process_set))
    return _engine().enqueue_broadcast(
        _auto_name("broadcast", name), _stack(tensor, ps.size()),
        root_rank, _ps_id(process_set))


def broadcast(tensor, root_rank: int, name=None,
              process_set: Optional[ProcessSet] = None):
    """Every rank receives rank ``root_rank``'s tensor."""
    return broadcast_async(tensor, root_rank, name, process_set).wait()


# -- alltoall --------------------------------------------------------------

def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None
                   ) -> CollectiveHandle:
    ps = process_set or global_process_set
    if _mh_mode():
        return basics._get_mh_engine().enqueue_alltoall(
            _auto_name("alltoall", name), tensor,
            splits=None if splits is None else list(np.asarray(splits)),
            process_set_id=_ps_id(process_set))
    if _tcp_mode():
        return basics._get_tcp_core().alltoall_async(
            _np(tensor), _auto_name("alltoall", name),
            splits=None if splits is None else list(np.asarray(splits)),
            process_set_id=_ps_id(process_set))
    if isinstance(tensor, (list, tuple)):
        tensor = jnp.stack([jnp.asarray(t) for t in tensor]) \
            if splits is None else [jnp.asarray(t) for t in tensor]
    if splits is not None:
        splits = np.asarray(splits)
        if isinstance(tensor, list):
            tensor = jnp.stack(tensor) if len(
                {t.shape for t in tensor}) == 1 else tensor
    return _engine().enqueue_alltoall(
        _auto_name("alltoall", name), tensor, splits, _ps_id(process_set))


def alltoall(tensor, splits=None, name=None,
             process_set: Optional[ProcessSet] = None):
    """Exchange: rank r sends slice j of its tensor to rank j.

    Returns the stacked received tensors; with ``splits`` also returns the
    received splits (reference AlltoallOp semantics).
    """
    out, recv_splits = alltoall_async(tensor, splits, name,
                                      process_set).wait()
    return out if splits is None else (out, recv_splits)


# -- reducescatter ---------------------------------------------------------

def reducescatter_async(tensor, op=SUM, name: Optional[str] = None,
                        process_set: Optional[ProcessSet] = None
                        ) -> CollectiveHandle:
    ps = process_set or global_process_set
    if _mh_mode():
        return basics._get_mh_engine().enqueue_reducescatter(
            _auto_name("reducescatter", name), tensor, red_op=op,
            process_set_id=_ps_id(process_set))
    if _tcp_mode():
        return basics._get_tcp_core().reducescatter_async(
            _np(tensor), _auto_name("reducescatter", name), op=op,
            process_set_id=_ps_id(process_set))
    return _engine().enqueue_reducescatter(
        _auto_name("reducescatter", name), _stack(tensor, ps.size()),
        op, _ps_id(process_set))


def reducescatter(tensor, op=SUM, name=None,
                  process_set: Optional[ProcessSet] = None):
    """Reduce then scatter dim-0 shards; row r of the result is rank r's."""
    return reducescatter_async(tensor, op, name, process_set).wait()


# -- barrier / join --------------------------------------------------------

def barrier(process_set: Optional[ProcessSet] = None):
    """Block until all ranks (and all previously enqueued collectives on
    this process set) have arrived (reference BarrierOp)."""
    if _tcp_mode() or _mh_mode():
        # Control-plane sync: negotiation itself is the barrier, so the
        # host path serves both multi-process modes.  The name must be
        # the deterministic sequence name — a per-rank unique default
        # would never match across ranks.
        return basics._get_tcp_core().barrier(
            name=_auto_name("barrier", None),
            process_set_id=_ps_id(process_set))
    return _engine().enqueue_barrier(
        _auto_name("barrier", None), _ps_id(process_set)).wait()


def join(device=None) -> int:
    """Signal this rank is out of data (reference JoinOp, ``hvd.join``).

    Returns the last rank that joined.  In the in-process SPMD world all
    device-ranks share one data stream, so join degenerates to a barrier
    and returns size-1; the TCP multi-process core implements the full
    zero-contribution protocol for uneven data.
    """
    if not basics._controller_is_spmd():
        return basics._get_tcp_core().join()
    barrier()
    return basics.size() - 1


# -- handle helpers --------------------------------------------------------

def synchronize(handle: CollectiveHandle):
    """Wait on an async handle and return its output (reference
    ``hvd.synchronize``)."""
    return handle.wait()


def poll(handle: CollectiveHandle) -> bool:
    """True if the async op has completed (reference ``hvd.poll``)."""
    return handle.poll()
