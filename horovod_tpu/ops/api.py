"""Eager (host-side) collective API — the 8-op surface.

Reference parity: ``hvd.allreduce / grouped_allreduce / allgather /
broadcast / alltoall / reducescatter / join / barrier`` plus their
``*_async`` variants and ``synchronize``/``poll`` (reference:
``horovod/torch/mpi_ops.py`` + ``horovod/tensorflow/mpi_ops.py`` surfaces,
backed by ``EnqueueTensor*`` in ``horovod/common/operations.cc``).

In the in-process SPMD world, a collective's input is "rank-major
stacked": ``x[r]`` is rank r's contribution (a list of per-rank tensors is
also accepted; allgather may be ragged in dim 0).  Ops are enqueued to the
background engine, fused, and executed as compiled XLA collectives; the
``*_async`` forms return handles resolved by the cycle thread.

In the multi-process (tcp) world the same calls route through the native
C++ core, which negotiates readiness across ranks before executing.
"""

from __future__ import annotations

import collections
import itertools
from typing import List, Optional, Sequence

from ..common import basics
from ..common.process_sets import ProcessSet, global_process_set
from . import xla_ops
from .engine import CollectiveHandle, HorovodInternalError
from .xla_ops import (ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM,
                      handle_average_backwards_compatibility)

__all__ = [
    "SUM", "AVERAGE", "MIN", "MAX", "PRODUCT", "ADASUM",
    "allreduce", "allreduce_async", "grouped_allreduce",
    "grouped_allreduce_async", "allgather", "allgather_async",
    "grouped_allgather", "grouped_allgather_async",
    "broadcast", "broadcast_async", "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async",
    "grouped_reducescatter", "grouped_reducescatter_async",
    "barrier", "join", "synchronize", "poll",
]


# Auto names must be identical across ranks: the multi-process
# controller negotiates collectives by exact name match, so unnamed ops
# get a per-op-type sequence number (deterministic when all ranks issue
# the same call sequence — the reference's contract for unnamed ops).
_name_counters = collections.defaultdict(itertools.count)


def _auto_name(prefix: str, name: Optional[str]) -> str:
    return name if name else \
        "%s.noname.%d" % (prefix, next(_name_counters[prefix]))


def _ps_id(process_set: Optional[ProcessSet]) -> int:
    ps = process_set or global_process_set
    if ps.process_set_id is None:
        raise ValueError("process set %r is not registered" % ps)
    return ps.process_set_id


def _engine():
    return basics._get_engine()


def _tcp_mode() -> bool:
    """Multi-process world, host payload plane: collectives route
    through the native core and each call passes THIS rank's tensor
    (reference semantics), not a rank-major stack."""
    return (basics.is_initialized()
            and basics._controller_mode() == "tcp")


def _mh_mode() -> bool:
    """Multi-process world, device payload plane: the native core
    negotiates order, the multihost engine executes XLA collectives
    over the global mesh.  Per-rank tensor semantics like tcp mode."""
    return (basics.is_initialized()
            and basics._controller_mode() == "multihost")


def _submit(op_type, tensors, names, process_set, **kw):
    """Route through the op-manager's backend priority walk
    (reference operation_manager.cc); marshaling (stacking, numpy
    copies) lives in each backend."""
    from .op_manager import OpRequest
    ps = process_set or global_process_set
    req = OpRequest(op_type, tensors, names,
                    process_set_id=_ps_id(process_set),
                    ps_size=ps.size(), **kw)
    return basics._get_op_manager().submit(req)


# -- allreduce -------------------------------------------------------------

def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: Optional[ProcessSet] = None
                    ) -> CollectiveHandle:
    red_op = handle_average_backwards_compatibility(op, average)
    return _submit("allreduce", [tensor],
                   [_auto_name("allreduce", name)], process_set,
                   red_op=red_op, prescale=prescale_factor,
                   postscale=postscale_factor)


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: Optional[ProcessSet] = None):
    """Reduce across ranks; returns the reduced tensor (replicated)."""
    return allreduce_async(tensor, average, name, op, prescale_factor,
                           postscale_factor, process_set).wait()


# -- grouped allreduce -----------------------------------------------------

def grouped_allreduce_async(tensors: Sequence, average=None,
                            name: Optional[str] = None, op=None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: Optional[ProcessSet] = None
                            ) -> List[CollectiveHandle]:
    """Enqueue a group atomically so fusion packs them together
    (reference: group_table.cc / hvd.grouped_allreduce)."""
    red_op = handle_average_backwards_compatibility(op, average)
    base = _auto_name("grouped_allreduce", name)
    names = ["%s.%d" % (base, i) for i in range(len(tensors))]
    return _submit("allreduce", list(tensors), names, process_set,
                   red_op=red_op, prescale=prescale_factor,
                   postscale=postscale_factor, is_group=True)


def grouped_allreduce(tensors: Sequence, average=None, name=None, op=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set: Optional[ProcessSet] = None):
    return [h.wait() for h in grouped_allreduce_async(
        tensors, average, name, op, prescale_factor, postscale_factor,
        process_set)]


def _check_reducescatter_op(op):
    if op == ADASUM:
        # Adasum is an allreduce algorithm (dot-product combine of full
        # gradients); a scattered variant does not exist in the
        # reference either.  Reject here so every backend agrees
        # instead of some silently computing a plain Sum.
        raise ValueError(
            "reducescatter supports Sum/Average/Min/Max/Product; "
            "Adasum is allreduce-only")


# -- grouped allgather / reducescatter (reference v0.28 additions) ---------

def grouped_allgather_async(tensors: Sequence,
                            name: Optional[str] = None,
                            process_set: Optional[ProcessSet] = None
                            ) -> List[CollectiveHandle]:
    """Gather a group atomically (all members negotiate together)."""
    base = _auto_name("grouped_allgather", name)
    names = ["%s.%d" % (base, i) for i in range(len(tensors))]
    return _submit("allgather", list(tensors), names, process_set,
                   is_group=True)


def grouped_allgather(tensors: Sequence, name=None,
                      process_set: Optional[ProcessSet] = None):
    return [h.wait() for h in grouped_allgather_async(
        tensors, name, process_set)]


def grouped_reducescatter_async(tensors: Sequence, op=None,
                                name: Optional[str] = None,
                                process_set: Optional[ProcessSet] = None
                                ) -> List[CollectiveHandle]:
    """Reduce-scatter a group atomically."""
    red_op = SUM if op is None else op
    _check_reducescatter_op(red_op)
    base = _auto_name("grouped_reducescatter", name)
    names = ["%s.%d" % (base, i) for i in range(len(tensors))]
    return _submit("reducescatter", list(tensors), names, process_set,
                   red_op=red_op, is_group=True)


def grouped_reducescatter(tensors: Sequence, op=None, name=None,
                          process_set: Optional[ProcessSet] = None):
    return [h.wait() for h in grouped_reducescatter_async(
        tensors, op, name, process_set)]


# -- allgather -------------------------------------------------------------

def allgather_async(tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None
                    ) -> CollectiveHandle:
    return _submit("allgather", [tensor],
                   [_auto_name("allgather", name)], process_set)


def allgather(tensor, name=None, process_set: Optional[ProcessSet] = None):
    """Gather per-rank tensors, concatenated on dim 0 (ragged dim-0 ok)."""
    return allgather_async(tensor, name, process_set).wait()


# -- broadcast -------------------------------------------------------------

def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None
                    ) -> CollectiveHandle:
    return _submit("broadcast", [tensor],
                   [_auto_name("broadcast", name)], process_set,
                   root_rank=root_rank)


def broadcast(tensor, root_rank: int, name=None,
              process_set: Optional[ProcessSet] = None):
    """Every rank receives rank ``root_rank``'s tensor."""
    return broadcast_async(tensor, root_rank, name, process_set).wait()


# -- alltoall --------------------------------------------------------------

def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None
                   ) -> CollectiveHandle:
    return _submit("alltoall", [tensor],
                   [_auto_name("alltoall", name)], process_set,
                   splits=splits)


def alltoall(tensor, splits=None, name=None,
             process_set: Optional[ProcessSet] = None):
    """Exchange: rank r sends slice j of its tensor to rank j.

    Returns the stacked received tensors; with ``splits`` also returns the
    received splits (reference AlltoallOp semantics).
    """
    out, recv_splits = alltoall_async(tensor, splits, name,
                                      process_set).wait()
    return out if splits is None else (out, recv_splits)


# -- reducescatter ---------------------------------------------------------

def reducescatter_async(tensor, op=SUM, name: Optional[str] = None,
                        process_set: Optional[ProcessSet] = None
                        ) -> CollectiveHandle:
    _check_reducescatter_op(op)
    return _submit("reducescatter", [tensor],
                   [_auto_name("reducescatter", name)], process_set,
                   red_op=op)


def reducescatter(tensor, op=SUM, name=None,
                  process_set: Optional[ProcessSet] = None):
    """Reduce then scatter dim-0 shards; row r of the result is rank r's.

    In-process mode with rows not divisible by the world size returns a
    list of per-rank chunks (earlier ranks get the larger shards, the
    native core's chunk layout) instead of one stacked array.
    """
    return reducescatter_async(tensor, op, name, process_set).wait()


# -- barrier / join --------------------------------------------------------

def barrier(process_set: Optional[ProcessSet] = None):
    """Block until all ranks (and all previously enqueued collectives on
    this process set) have arrived (reference BarrierOp)."""
    if _tcp_mode() or _mh_mode():
        # Control-plane sync: negotiation itself is the barrier, so the
        # host path serves both multi-process modes.  The name must be
        # the deterministic sequence name — a per-rank unique default
        # would never match across ranks.
        return basics._get_tcp_core().barrier(
            name=_auto_name("barrier", None),
            process_set_id=_ps_id(process_set))
    return _engine().enqueue_barrier(
        _auto_name("barrier", None), _ps_id(process_set)).wait()


def join(device=None, ranks=None) -> int:
    """Signal out-of-data ranks (reference JoinOp, ``hvd.join``).

    Multi-process modes: the calling rank joins; returns the last rank
    to join once everyone has (the core's zero-contribution protocol,
    ``operations.cc`` JoinOp path).

    In-process SPMD mode the single controller drives every rank, so
    ``ranks`` names which world ranks are out of data: their rows of
    every subsequent stacked Sum allreduce payload contribute zeros,
    Average divides by the live-contributor count, and other
    collectives are rejected while any rank is joined.  A final
    ``join()`` with no ``ranks`` ends the round: remaining ranks join
    in rank order, the joined set clears, and the last joiner's rank is
    returned.
    """
    if not basics._controller_is_spmd():
        if ranks is not None:
            raise ValueError(
                "ranks= is the in-process (single-controller) form; in "
                "multi-process modes each rank calls join() itself")
        return basics._get_tcp_core().join()
    eng = _engine()
    if ranks is not None:
        eng.mark_joined([ranks] if isinstance(ranks, int) else ranks)
        return -1
    barrier()
    return eng.finalize_join()


# -- handle helpers --------------------------------------------------------

def synchronize(handle: CollectiveHandle):
    """Wait on an async handle and return its output (reference
    ``hvd.synchronize``)."""
    return handle.wait()


def poll(handle: CollectiveHandle) -> bool:
    """True if the async op has completed (reference ``hvd.poll``)."""
    return handle.poll()
