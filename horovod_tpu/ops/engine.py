"""Asynchronous collective engine: enqueue -> fuse -> execute.

TPU-native re-design of the reference's C++ coordination core hot path
(``horovod/common/operations.cc`` ``BackgroundThreadLoop``/``RunLoopOnce``,
``tensor_queue.cc``, ``fusion_buffer_manager.cc``): callers enqueue named
tensors and get an async handle; a background cycle thread wakes every
``HOROVOD_CYCLE_TIME`` ms, drains the queue, *fuses* small same-typed
allreduces into one flat buffer (up to ``HOROVOD_FUSION_THRESHOLD`` bytes,
padded to power-of-two buckets so the compiled-executable cache hits), runs
one XLA collective per fused group, scatters results back, and resolves the
handles.

In the single-controller SPMD world "negotiation" is trivial (one process
knows all readiness), so the controller concern collapses into this engine;
the full rank-0 negotiation protocol lives in the C++ TCP core
(``horovod_tpu/core``) used by the multi-process mode.  The engine still
records NEGOTIATE/QUEUE/FUSE/EXEC phases in the timeline so traces read
like the reference's.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.profiler
import jax.numpy as jnp
import numpy as np

from ..common import faultline, metrics
from ..common.config import Config
from ..utils.stall_inspector import StallInspector
from ..utils.timeline import Timeline
from . import fastpath, xla_ops
from .executable_cache import ExecutableCache
from .xla_ops import MeshCollectives

LOG = logging.getLogger("horovod_tpu")

_OP_ALLREDUCE = "allreduce"
_OP_ALLGATHER = "allgather"
_OP_BROADCAST = "broadcast"
_OP_ALLTOALL = "alltoall"
_OP_REDUCESCATTER = "reducescatter"
_OP_BARRIER = "barrier"


class HorovodInternalError(RuntimeError):
    """A collective failed (reference parity: surfaces to elastic mode)."""


class CollectiveDeadlineExceeded(HorovodInternalError):
    """A negotiated group outlived its per-collective deadline
    (HOROVOD_COLLECTIVE_TIMEOUT_SECS) and was error-completed.

    A HorovodInternalError subclass on purpose: elastic's run() loop
    must treat deadline expiry as a recoverable fault and restore from
    the last committed spill.  Its message must never contain the
    stall inspector's abort text ("stall shutdown threshold") — that
    phrase routes elastic to the DRAIN exit instead of restore."""


class CollectiveHandle:
    """Async completion handle (reference: torch handle_manager.cc idea)."""

    __slots__ = ("_event", "_result", "_error", "name")

    def __init__(self, name: str):
        self.name = name
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def _set_result(self, value):
        self._result = value
        self._event.set()

    def _set_error(self, exc: BaseException):
        self._error = exc
        self._event.set()

    def poll(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                "collective %r did not complete in %s s" % (self.name, timeout))
        if self._error is not None:
            raise HorovodInternalError(str(self._error)) from self._error
        return self._result


class _Entry:
    __slots__ = ("name", "op_type", "payload", "red_op", "prescale",
                 "postscale", "root_rank", "splits", "process_set_id",
                 "handle", "enqueue_t", "nbytes", "joined_idx")

    def __init__(self, name, op_type, payload, red_op, prescale, postscale,
                 root_rank, splits, process_set_id, handle, nbytes,
                 joined_idx=()):
        self.name = name
        self.op_type = op_type
        self.payload = payload
        self.red_op = red_op
        self.prescale = prescale
        self.postscale = postscale
        self.root_rank = root_rank
        self.splits = splits
        self.process_set_id = process_set_id
        self.handle = handle
        self.enqueue_t = time.monotonic()
        self.nbytes = nbytes
        # Joined-rank snapshot taken at ENQUEUE time: a later join()
        # must not retroactively zero (or reject) ops submitted while
        # every rank was still in-data.
        self.joined_idx = tuple(joined_idx)


def _fp_slot_sig(e: "_Entry") -> tuple:
    """One entry's frozen-schedule slot signature.  Names are NOT part
    of it on purpose: steady-state training loops enqueue the same
    tensors in the same order every step but often with step-suffixed
    names, and the reference's response cache keys on shape/type for
    the same reason.  Position in the cycle is the identity."""
    return (e.op_type, e.process_set_id, str(e.payload.dtype), e.red_op,
            float(e.prescale), float(e.postscale), e.joined_idx,
            tuple(e.payload.shape), int(e.nbytes))


def _bucket(n: int) -> int:
    """Pad fused flat length to a power-of-two bucket (>=1024) so compiled
    executables are reused across steps with slightly different groupings —
    static shapes are what keep XLA/MXU happy."""
    b = 1024
    while b < n:
        b <<= 1
    return b


class CollectiveEngine:
    """Background-cycle fusion engine over one device list."""

    def __init__(self, devices, config: Config, timeline: Timeline,
                 process_set_resolver: Callable[[int], List[int]]):
        self.devices = list(devices)
        self.size = len(self.devices)
        self.config = config
        self.timeline = timeline
        self._resolve_process_set = process_set_resolver
        self.cache = ExecutableCache(config.cache_capacity)
        # Process-set mesh memo: populated lazily from BOTH the caller
        # plane (enqueue path) and the cycle thread.
        self._collectives: Dict[int, MeshCollectives] = {}  # graftlint: guarded-by=_lock
        self._queue: List[_Entry] = []  # graftlint: guarded-by=_lock
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # Poison/stop flag: set under the lock so the notify in
        # shutdown() can't race the cycle thread's wait predicate.
        self._shutdown = False  # graftlint: guarded-by=_lock
        self._cycle_count = 0  # graftlint: owned-by=hvd-tpu-cycle
        # Monotonic collective-group id: every dispatched execution
        # (fused chunk or single op) gets one; the same id tags the
        # group's timeline EXEC events (args.group) and the
        # engine_last_group_id gauge, correlating trace and metrics.
        # Guarded by its own leaf lock since r22: frozen fast-path
        # buckets dispatch on the CALLER thread, so the cycle thread
        # no longer owns the sequence.
        self._gid_lock = threading.Lock()
        self._group_seq = 0  # graftlint: guarded-by=_gid_lock
        # Fixed unlabeled series resolved ONCE: the enqueue/cycle hot
        # paths must pay only the .inc()/.set() lock round trip, not a
        # per-call name lookup + label-tuple build.
        self._m_cycles = metrics.counter("engine_cycles_total")
        self._m_cycle_seconds = metrics.histogram("engine_cycle_seconds")
        self._m_queue_depth = metrics.gauge("engine_queue_depth")
        self._m_bytes_submitted = metrics.counter(
            "engine_bytes_submitted_total")
        self._m_bytes_fused = metrics.counter("engine_bytes_fused_total")
        self._m_tensors_fused = metrics.counter(
            "engine_tensors_fused_total")
        self._m_cache_hits = metrics.gauge("exec_cache_hits")
        self._m_cache_misses = metrics.gauge("exec_cache_misses")
        self._m_last_group = metrics.gauge("engine_last_group_id")
        self.stall_inspector = StallInspector(
            warning_secs=config.stall_warning_secs,
            shutdown_secs=config.stall_shutdown_secs,
            enabled=not config.stall_check_disable)
        self.parameter_manager = None  # installed by basics when autotuning
        # One-shot latch: the converged GP point is staged into the
        # plan cache exactly once (cycle-thread only).
        self._pm_converged_noted = False  # graftlint: owned-by=hvd-tpu-cycle
        # Ranks marked out-of-data (reference JoinOp): they contribute
        # zeros to allreduces until every rank has joined.  Ordered so
        # finalize can report the LAST rank to join, like the core.
        self._joined: List[int] = []  # graftlint: guarded-by=_lock
        # -- steady-state fast path (frozen schedule, ISSUE 19) --
        # Staging state for the current frozen cycle: callers match
        # entries against the frozen slots positionally and dispatch
        # each overlap bucket inline the instant it fills — no cycle-
        # thread handoff, no cycle-time wait.  _fp_lock is reentrant
        # (a mismatch thaw flushes from under the staging section) and
        # is always taken BEFORE _lock/_wake (lock order, never after).
        self._fp_lock = threading.RLock()
        self._fp_pending: List[_Entry] = []  # graftlint: guarded-by=_fp_lock
        self._fp_idx = 0  # graftlint: guarded-by=_fp_lock
        self._fp_t = 0.0  # graftlint: guarded-by=_fp_lock
        self._fp = fastpath.ScheduleFreezer(
            warm_cycles=config.fast_path_warm_cycles,
            enabled=config.fast_path, spmd=False, plane_name="eager",
            on_thaw=self._fp_flush, stage_lock=self._fp_lock)
        fastpath.register(self._fp)
        self._m_fp_frozen = metrics.counter("fastpath_frozen_cycles_total")
        self._m_fp_bucket = metrics.histogram(
            "engine_overlap_bucket_seconds")
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-cycle", daemon=True)
        self._thread.start()

    # -- join (zero contribution, reference JoinOp) ------------------------

    def mark_joined(self, ranks):
        """Mark world ranks as out of data; their rows of every
        subsequent stacked allreduce payload are zeroed (the reference's
        joined ranks contribute zeros, ``operations.cc`` JoinOp path)."""
        # A join changes the payload the frozen schedule would
        # dispatch (zeroed rows): thaw before mutating membership.
        self._fp.thaw("membership", detail="rank(s) %s joined"
                      % list(ranks))
        with self._lock:
            for r in ranks:
                r = int(r)
                if not 0 <= r < self.size:
                    raise ValueError("join rank %d outside world [0, %d)"
                                     % (r, self.size))
                if r not in self._joined:
                    self._joined.append(r)

    def finalize_join(self) -> int:
        """All remaining ranks join now (in rank order); clears the
        joined set and returns the last rank to join, like the core's
        ``hvd_tcp_join``."""
        with self._lock:
            joined, self._joined = self._joined, []
        remaining = [r for r in range(self.size) if r not in joined]
        if remaining:
            return remaining[-1]
        return joined[-1] if joined else self.size - 1

    def _joined_member_indices(self, process_set_id) -> List[int]:
        with self._lock:
            joined = list(self._joined)
        if not joined:
            return []
        members = self._resolve_process_set(process_set_id)
        if members is None:
            members = list(range(self.size))
        return [i for i, g in enumerate(members) if g in joined]

    # -- process-set meshes ------------------------------------------------

    def collectives_for(self, process_set_id: int) -> MeshCollectives:
        # Reached from the caller plane (enqueue_alltoall sizing) AND
        # the cycle thread (_run_cycle): memoize under the lock so two
        # racing first-touches can't build two meshes for one set.
        with self._lock:
            mc = self._collectives.get(process_set_id)
            if mc is None:
                ranks = self._resolve_process_set(process_set_id)
                devs = (self.devices if ranks is None
                        else [self.devices[r] for r in ranks])
                mc = MeshCollectives(devs, cache=self.cache,
                                     name="ps%d" % process_set_id)
                self._collectives[process_set_id] = mc
            return mc

    def invalidate_process_set(self, process_set_id: int):
        self._fp.thaw("membership",
                      detail="process set %d invalidated"
                      % process_set_id)
        with self._lock:
            self._collectives.pop(process_set_id, None)

    # -- enqueue API -------------------------------------------------------

    def _enqueue(self, name, op_type, payload, red_op=xla_ops.SUM,
                 prescale=1.0, postscale=1.0, root_rank=0, splits=None,
                 process_set_id=0, nbytes=0) -> CollectiveHandle:
        if self._shutdown:
            raise HorovodInternalError("engine is shut down")
        joined_idx = self._joined_member_indices(process_set_id)
        if joined_idx and op_type == _OP_ALLREDUCE and \
                red_op not in (xla_ops.SUM, xla_ops.AVERAGE):
            # Zero is Sum's reduction identity; Average is handled by
            # dividing by the live-contributor count at execution.  For
            # Min/Max/Product a zero contribution from joined ranks would
            # silently corrupt the result (mirrors the Adasum guard in
            # op_manager.py).
            raise HorovodInternalError(
                "allreduce %r with op=%s submitted while ranks are joined; "
                "zero-contribution join is only supported for Sum/Average"
                % (name, red_op))
        handle = CollectiveHandle(name)
        e = _Entry(name, op_type, payload, red_op, prescale, postscale,
                   root_rank, splits, process_set_id, handle, nbytes,
                   joined_idx=joined_idx)
        self.timeline.negotiate_start(name, op_type)
        self.stall_inspector.record_enqueue(name)
        self._m_bytes_submitted.inc(nbytes)
        if self._fp_stage(e):
            return handle
        with self._wake:
            self._queue.append(e)
            self._wake.notify()
        return handle

    def enqueue_allreduce(self, name, stacked, red_op, prescale, postscale,
                          process_set_id) -> CollectiveHandle:
        arr = jnp.asarray(stacked)
        return self._enqueue(name, _OP_ALLREDUCE, arr, red_op=red_op,
                             prescale=prescale, postscale=postscale,
                             process_set_id=process_set_id,
                             nbytes=arr.nbytes // max(arr.shape[0], 1))

    def enqueue_allgather(self, name, per_rank, process_set_id):
        return self._enqueue(name, _OP_ALLGATHER, per_rank,
                             process_set_id=process_set_id)

    def enqueue_broadcast(self, name, stacked, root_rank, process_set_id):
        return self._enqueue(name, _OP_BROADCAST, jnp.asarray(stacked),
                             root_rank=root_rank,
                             process_set_id=process_set_id)

    def enqueue_alltoall(self, name, stacked, splits, process_set_id):
        return self._enqueue(name, _OP_ALLTOALL, stacked, splits=splits,
                             process_set_id=process_set_id)

    def enqueue_reducescatter(self, name, stacked, red_op, process_set_id):
        return self._enqueue(name, _OP_REDUCESCATTER, jnp.asarray(stacked),
                             red_op=red_op, process_set_id=process_set_id)

    def enqueue_barrier(self, name, process_set_id):
        return self._enqueue(name, _OP_BARRIER, None,
                             process_set_id=process_set_id)

    # -- steady-state fast path (frozen schedule, ISSUE 19) ----------------

    def _fp_profile(self, batch: List[_Entry]):
        """Freezable profile of one negotiated cycle, or None.  Only
        pure-allreduce cycles sharing ONE fuse key with no joined
        ranks freeze: an overlap bucket is a fused dispatch unit, and
        mixed keys (or a membership transition) cannot fuse."""
        keys = set()
        for e in batch:
            if e.op_type != _OP_ALLREDUCE or e.joined_idx:
                return None
            keys.add((e.process_set_id, str(e.payload.dtype), e.red_op,
                      float(e.prescale), float(e.postscale)))
            if len(keys) > 1:
                return None
        return tuple(_fp_slot_sig(e) for e in batch)

    def _fp_payload(self, batch: List[_Entry], prof) -> dict:
        """The schedule cached at freeze time: the positional slot
        signatures plus the overlap-bucket partition (contiguous,
        balanced by bytes, capped at the fusion threshold)."""
        ends = fastpath.bucket_ends(
            [e.nbytes for e in batch], self.config.overlap_buckets,
            self.config.fusion_threshold_bytes)
        return {"sig": fastpath.schedule_sig(prof),
                "slots": list(prof), "ends": ends}

    def _fp_stage(self, e: _Entry) -> bool:  # graftlint: schedule-entry=fastpath -- frozen-schedule bucket dispatch of the eager plane (negotiation skipped)
        """Frozen-schedule staging (caller thread).  Match ``e``
        against the next frozen slot; the instant an overlap bucket's
        last tensor lands, dispatch that bucket INLINE — the XLA
        dispatch is async, so the caller keeps producing gradients for
        later buckets while this one's collective runs, and the
        negotiation queue, cycle thread and cycle-time wait are all
        skipped.  A mismatch thaws loudly and falls back (returns
        False: the caller requeues ``e`` on the negotiation path)."""
        if self._fp.frozen() is None:
            return False
        with self._fp_lock:
            fs = self._fp.frozen()
            if fs is None:
                return False
            slots = fs["slots"]
            if (self._fp_idx >= len(slots)
                    or _fp_slot_sig(e) != slots[self._fp_idx]):
                self._fp.thaw(
                    "shape", detail="entry %r does not match frozen "
                    "slot %d" % (e.name, self._fp_idx))
                return False
            self.timeline.negotiate_end(e.name)
            self._fp_pending.append(e)
            self._fp_t = time.monotonic()
            self._fp_idx += 1
            if self._fp_idx not in fs["ends"]:
                return True
            if fastpath.stale_dispatch_seam():
                # Injected stale dispatch: the frozen schedule must
                # not be trusted — thaw loudly; the flush pushes this
                # bucket's tensors back through full negotiation
                # (correct values, no hang).
                self._fp.thaw(
                    "staleness", detail="injected stale dispatch "
                    "(engine.fastpath.stale_dispatch)")
                return True
            pending, self._fp_pending = self._fp_pending, []
            done = self._fp_idx == len(slots)
            if done:
                self._fp_idx = 0
            t0 = time.monotonic()
            self._execute_fused_allreduce(pending)
            self._m_fp_bucket.observe(time.monotonic() - t0)
            if done:
                # A frozen cycle is counted here, NOT in
                # engine_cycles_total: exactly one of the two moves
                # per cycle, and the exec-cache gauges refresh in the
                # same breath so levers.metrics never reads a cached
                # dispatch as both a cache hit and a negotiation
                # cycle.
                self._m_fp_frozen.inc()
                hits, misses = self.cache.stats()
                self._m_cache_hits.set(hits)
                self._m_cache_misses.set(misses)
            return True

    def _fp_flush(self, _payload: dict, _reason: str):
        """Thaw fallback (any thread; runs under _fp_lock via the
        freezer): staged-but-undispatched entries re-enter the
        negotiation queue in program order, so their handles resolve
        through the normal cycle path."""
        with self._fp_lock:
            pending, self._fp_pending = self._fp_pending, []
            self._fp_idx = 0
        if not pending:
            return
        with self._wake:
            self._queue.extend(pending)
            self._wake.notify()

    def _fp_idle_check(self):
        """Safety valve (cycle thread): a frozen cycle staged
        PARTIALLY and went quiet — the app's per-step entry list
        shrank without tripping a slot mismatch.  Waiting forever
        would hang a caller blocked on a staged handle; thaw and
        negotiate the stragglers instead."""
        if self._fp.frozen() is None:
            return
        with self._fp_lock:
            stale = bool(self._fp_pending) and (
                time.monotonic() - self._fp_t
                > max(0.05, 4 * self.config.cycle_time_ms / 1e3))
            if stale:
                self._fp.thaw(
                    "shape", detail="partial frozen cycle (%d of %d "
                    "slots) flushed back to negotiation"
                    % (self._fp_idx,
                       len((self._fp.frozen() or {}).get("slots", ()))))

    # -- background loop ---------------------------------------------------

    def _loop(self):
        while True:
            with self._wake:
                if not self._queue and not self._shutdown:
                    # Idle coarsening: with nothing queued AND nothing
                    # outstanding there is no work the cycle tick could
                    # start — sleep long (enqueue notifies instantly).
                    # An idle engine waking every few ms steals the GIL
                    # from the jit dispatch loop (measured ~1 ms/step
                    # on the ResNet bench with a 5 ms tick).
                    idle_t = (self.config.cycle_time_ms / 1e3
                              if self.stall_inspector.has_outstanding()
                              else 0.5)
                    self._wake.wait(timeout=idle_t)
                if self._shutdown and not self._queue:
                    return
                batch, self._queue = self._queue, []
            self._fp_idle_check()
            self._cycle_count += 1
            self.timeline.mark_cycle(self._cycle_count)
            if batch:
                self._m_cycles.inc()
                self._m_queue_depth.set(len(batch))
                t0 = time.monotonic()
                _, misses0 = self.cache.stats()
                nbytes = sum(e.nbytes for e in batch)
                self._run_cycle(batch)
                self._m_cycle_seconds.observe(time.monotonic() - t0)
                hits, misses = self.cache.stats()
                self._m_cache_hits.set(hits)
                self._m_cache_misses.set(misses)
                # A cycle that compiled a new XLA executable measures
                # the compiler, not communication; feeding it to the
                # tuner would bias the early GP samples (the reference
                # resets after HOROVOD_AUTOTUNE_WARMUP for the same
                # reason).
                compiled = misses != misses0
                if self.parameter_manager is not None and not compiled:
                    self.parameter_manager.observe(
                        nbytes, time.monotonic() - t0)
                    self.config.fusion_threshold_bytes = (
                        self.parameter_manager.fusion_threshold)
                    self.config.cycle_time_ms = (
                        self.parameter_manager.cycle_time_ms)
                    if (self.parameter_manager.frozen
                            and self.parameter_manager.samples_done > 0
                            and not self._pm_converged_noted):
                        # Stage the converged operating point for the
                        # plan cache the moment the GP pins it —
                        # convergence is only observable here, and
                        # shutdown persists whatever was staged.
                        # samples_done > 0 excludes a PM that was
                        # BORN frozen from a cache warm start: its
                        # point is cached provenance, not tuned.
                        self._pm_converged_noted = True
                        from ..utils import plancache
                        plancache.note_tuned(
                            self.parameter_manager.fusion_threshold,
                            self.parameter_manager.cycle_time_ms, True)
                # Warm counting for the steady-state fast path: one
                # identical-profile streak long enough freezes the
                # schedule (single-controller world: the freeze verdict
                # is trivially SPMD-uniform, no KV round needed).
                if self._fp.enabled and self._fp.frozen() is None:
                    prof = self._fp_profile(batch)
                    if self._fp.observe(prof):
                        with self._gid_lock:
                            gid = self._group_seq
                        self._fp.freeze(
                            self._fp_payload(batch, prof), gid)
            try:
                self.stall_inspector.check()
            except Exception as exc:  # StallError -> fail outstanding ops
                with self._wake:
                    pending, self._queue = self._queue, []
                for e in pending:
                    e.handle._set_error(exc)

    def _run_cycle(  # graftlint: schedule-entry=eager -- per-cycle collective order of the eager TCP-core plane
            self, batch: List[_Entry]):
        faultline.site("engine.cycle.pre")
        # Group allreduces for fusion: (process set, dtype, red_op, scales).
        fuse_groups: Dict[tuple, List[_Entry]] = {}
        singles: List[_Entry] = []
        for e in batch:
            self.timeline.negotiate_end(e.name)
            if e.op_type == _OP_ALLREDUCE:
                # joined_idx is part of the key: entries straddling a
                # join() must not fuse, or the Average live-contributor
                # divisor below would be wrong for part of the bucket.
                k = (e.process_set_id, str(e.payload.dtype), e.red_op,
                     float(e.prescale), float(e.postscale), e.joined_idx)
                fuse_groups.setdefault(k, []).append(e)
            else:
                singles.append(e)
        for key, group in fuse_groups.items():
            # Respect the fusion threshold: chunk greedy-first-fit in order.
            chunk: List[_Entry] = []
            chunk_bytes = 0
            for e in group:
                if chunk and chunk_bytes + e.nbytes > \
                        self.config.fusion_threshold_bytes:
                    self._execute_fused_allreduce(chunk)
                    chunk, chunk_bytes = [], 0
                chunk.append(e)
                chunk_bytes += e.nbytes
            if chunk:
                self._execute_fused_allreduce(chunk)
        for e in singles:
            self._execute_single(e)

    def _next_group(self) -> int:
        """Next collective-group id (cycle thread OR a caller thread
        dispatching a frozen bucket): tags the group's timeline EXEC
        span and the engine_last_group_id gauge so the trace and
        metrics planes correlate."""
        with self._gid_lock:
            self._group_seq += 1
            gid = self._group_seq
        self._m_last_group.set(gid)
        return gid

    def _execute_fused_allreduce(self, entries: List[_Entry]):
        names = [e.name for e in entries]
        # xprof span (the reference's NVTX op range, nvtx_op_range.cc):
        # collective executions show up named in jax.profiler traces
        with jax.profiler.TraceAnnotation(
                "hvd.allreduce[%d tensors]" % len(entries)):
            self._execute_fused_allreduce_inner(entries, names)

    def _execute_fused_allreduce_inner(self, entries: List[_Entry],
                                       names: List[str]):
        try:
            mc = self.collectives_for(entries[0].process_set_id)
            size = mc.size

            def zero_joined(stacked, joined_idx):
                # Joined ranks contribute zeros (reference JoinOp).
                # Uses the entry's enqueue-time snapshot, so join() is
                # never retroactive.
                if not joined_idx:
                    return stacked
                return stacked.at[jnp.asarray(joined_idx)].set(0)

            # Average over live contributors: zero is not Average's
            # identity, so dividing by the full member count would bias
            # the result toward zero.  Execute as Sum with 1/live folded
            # into postscale (mirrors the controller's join rewrite).
            e0 = entries[0]
            red_op, postscale = e0.red_op, float(e0.postscale)
            if e0.joined_idx and red_op == xla_ops.AVERAGE:
                live = size - len(e0.joined_idx)
                if live <= 0:
                    raise HorovodInternalError(
                        "Average allreduce with every member joined")
                red_op, postscale = xla_ops.SUM, postscale / live

            if len(entries) == 1 and entries[0].payload.ndim >= 1:
                e = entries[0]
                self.timeline.activity_start(
                    e.name, "EXEC_ALLREDUCE",
                    args={"group": self._next_group()})
                out = mc.allreduce(
                    zero_joined(e.payload, e.joined_idx), red_op,
                    float(e.prescale), postscale)
                self.timeline.activity_end(e.name)
                self.stall_inspector.record_done(e.name)
                e.handle._set_result(out)
                return
            # The whole fusion cycle is ONE compiled program (flatten +
            # zero joined rows + concat into the padded bucket + the
            # collective + per-entry slices): XLA manages the fusion
            # buffer as a compiler scratch instead of the engine
            # dispatching separate concat/collective/slice programs
            # (the reference's persistent fusion buffer, the XLA way).
            self._m_bytes_fused.inc(sum(e.nbytes for e in entries))
            self._m_tensors_fused.inc(len(entries))
            self.timeline.activity_start_all(
                names, "EXEC_FUSED_ALLREDUCE",
                args={"group": self._next_group()})
            total = sum(
                int(np.prod(e.payload.shape[1:], dtype=np.int64))
                for e in entries)
            outs = mc.fused_allreduce(
                [e.payload for e in entries], red_op,
                float(e0.prescale), postscale,
                [e.joined_idx for e in entries], _bucket(total))
            self.timeline.activity_end_all(names)
            for e, out in zip(entries, outs):
                self.stall_inspector.record_done(e.name)
                e.handle._set_result(out)
        except Exception as exc:  # noqa: BLE001 - propagate to handles
            LOG.error("fused allreduce failed: %s", exc)
            for e in entries:
                self.stall_inspector.record_done(e.name)
                e.handle._set_error(exc)

    def _execute_single(self, e: _Entry):
        try:
            mc = self.collectives_for(e.process_set_id)
            if e.op_type != _OP_BARRIER and e.joined_idx:
                # Mirror the controller: only allreduce can proceed with
                # a zero contribution from joined ranks; anything else
                # would deadlock or silently mis-shape.
                raise HorovodInternalError(
                    "%s %r submitted while ranks are joined; only "
                    "allreduce supports zero-contribution join"
                    % (e.op_type, e.name))
            self.timeline.activity_start(
                e.name, "EXEC_" + e.op_type.upper(),
                args={"group": self._next_group()})
            # xprof span (reference NVTX op range, nvtx_op_range.cc)
            with jax.profiler.TraceAnnotation("hvd.%s" % e.op_type):
                if e.op_type == _OP_ALLGATHER:
                    out = mc.allgather(e.payload)
                elif e.op_type == _OP_BROADCAST:
                    out = mc.broadcast(e.payload, e.root_rank)
                elif e.op_type == _OP_ALLTOALL:
                    out = mc.alltoall(e.payload, e.splits)
                elif e.op_type == _OP_REDUCESCATTER:
                    d0 = e.payload.shape[1]
                    if d0 % mc.size:
                        # Uneven rows: full reduce on the mesh, then
                        # slice the core's chunk layout — rank j gets
                        # d0//n + (1 if j < d0%n) rows, earlier ranks
                        # larger (operations.cc REDUCESCATTER chunking).
                        red = mc.allreduce(e.payload, e.red_op)
                        rows, offs = xla_ops.uneven_chunks(d0, mc.size)
                        out = [red[o:o + c] for c, o in zip(rows, offs)]
                    else:
                        out = mc.reducescatter(e.payload, e.red_op)
                elif e.op_type == _OP_BARRIER:
                    out = mc.barrier()
                else:
                    raise NotImplementedError(e.op_type)
            self.timeline.activity_end(e.name)
            self.stall_inspector.record_done(e.name)
            e.handle._set_result(out)
        except Exception as exc:  # noqa: BLE001
            LOG.error("%s %r failed: %s", e.op_type, e.name, exc)
            self.stall_inspector.record_done(e.name)
            e.handle._set_error(exc)

    # -- shutdown ----------------------------------------------------------

    def shutdown(self):
        # Flush any staged frozen work back into the queue FIRST so
        # the cycle thread drains it before exiting (the world is
        # ending: membership is the honest reason).
        self._fp.thaw("membership", detail="engine shutdown")
        fastpath.unregister(self._fp)
        with self._wake:
            self._shutdown = True
            self._wake.notify()
        self._thread.join(timeout=10.0)
