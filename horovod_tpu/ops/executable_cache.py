"""Compiled-collective executable cache.

The TPU-native analog of the reference's response cache
(``horovod/common/response_cache.cc``): on TPU there is no user-space
collective library call — a collective is an XLA program executed via PJRT.
The steady-state fast path is therefore *skipping compilation*: executables
are cached keyed by (op, process set, dtype, bucketed size), so after
warm-up every cycle dispatches a pre-compiled program, the same way the
reference's bit-vector cache path skips full negotiation.

Capacity is ``HOROVOD_CACHE_CAPACITY`` (default 1024); eviction is LRU,
matching the reference's clock-ish eviction behavior closely enough for
parity.  Hit/miss counters feed the autotuner's throughput score.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Hashable, Optional


class ExecutableCache:
    def __init__(self, capacity: int = 1024):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Hashable, Any]" = (
            collections.OrderedDict())
        self.hits = 0
        self.misses = 0

    def lookup(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                return self._entries[key]
            self.misses += 1
            return None

    def put(self, key: Hashable, value: Any):
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def get_or_build(self, key: Hashable, builder: Callable[[], Any]) -> Any:
        found = self.lookup(key)
        if found is not None:
            return found
        built = builder()
        self.put(key, built)
        return built

    def stats(self):
        """Atomic ``(hits, misses)`` snapshot.  The engines' exec-cache
        gauges and the fast path's frozen-cycle refresh both read
        through here: a frozen (negotiation-skipping) dispatch still
        hits this cache, and the gauges must move together with the
        ``fastpath_frozen_cycles_total`` counter so a cached-schedule
        cycle is attributed exactly once — as a fast-path cycle with a
        cache hit, never additionally as a negotiation cycle."""
        with self._lock:
            return self.hits, self.misses

    def keys(self):
        """Snapshot of cached keys (observability: tests assert the
        packed-bucket paths keep the executable count flat across
        varying group compositions)."""
        with self._lock:
            return list(self._entries.keys())

    def clear(self):
        with self._lock:
            self._entries.clear()

    def __len__(self):
        with self._lock:
            return len(self._entries)
