"""Steady-state fast path: frozen negotiated schedules (the upstream
``response_cache.cc`` idea taken one step further).

Upstream Horovod observes that once tensor shapes stabilize, per-step
negotiation dominates, and coordinates steady state through a bit
vector over cached responses instead of re-gathering full requests
(Sergeev & Del Balso, arXiv:1802.05799).  This module is our version
of that cache with the remaining coordination removed too: after
``HOROVOD_FAST_PATH_WARM_CYCLES`` *identical* negotiated cycles (same
tensor multiset, shapes, dtypes, reduction parameters, membership) the
engine FREEZES the response schedule.  A frozen engine dispatches
collectives straight off the cached schedule — request gather, fusion
planning and response broadcast are all skipped — and carves the fused
payload into ``HOROVOD_OVERLAP_BUCKETS`` staging buckets, each
dispatched the instant its last tensor lands so early buckets'
collectives overlap later gradient production (the bucketed
comm/compute overlap lever of Li et al., arXiv:2006.15704).

A frozen schedule must never mask a change: every loud-invalidation
source THAWS it back to full negotiation —

- ``shape``      a staged tensor no longer matches its frozen slot
                 (also the partial-cycle safety valve);
- ``membership`` process-set invalidation, join, elastic resize,
                 engine shutdown, or an unexpected negotiated record;
- ``staleness``  a :meth:`PlanController.invalidate` trip (and the
                 injected ``engine.fastpath.stale_dispatch`` fault);
- ``route``      a degraded-route demote/promote verdict
                 (``resilience._apply_route``);
- ``deadline``   a per-collective deadline expiry.

Thaws are loud: a warning log, ``fastpath_thaws_total{reason}`` and a
``fastpath_thaw`` journal event carrying the frozen schedule's group
id for timeline correlation.

The freeze decision is SPMD-uniform.  Multi-process engines route it
through the rendezvous-KV record protocol (rank-0 verdict, the plan-
staleness/degraded-route pattern): every member's warm streak trips at
the same negotiated-record index because records are coordinator-
broadcast, rank 0 publishes ``{seq, sig, freeze}`` under the topology
fingerprint and members block for a record covering their own proposal
seq — a frozen rank and a negotiating rank can never coexist (the
frozen rank stops feeding the coordinator and would wedge the world).
KV-less multi-member worlds never freeze (warned once); the
single-controller in-process engine freezes locally.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..common import metrics

LOG = logging.getLogger("horovod_tpu")

# The thaw-reason label enum (docs/observability.md); thaw() rejects
# anything else so the metric's cardinality stays closed.
THAW_REASONS = ("shape", "membership", "staleness", "route", "deadline")

# Rendezvous-KV key carrying rank 0's freeze verdicts, per topology
# fingerprint (the plan-staleness record protocol).
_FREEZE_KEY = "fastpath/freeze/v%d/%s"


def stale_dispatch_seam() -> bool:
    """The frozen-schedule bucket-dispatch injection seam: a completed
    overlap bucket is about to dispatch off the frozen schedule, and a
    ``drop`` here means the schedule must be treated as stale.  Fired
    by BOTH engines' ``_fp_stage`` through this one helper so the site
    names one seam (the ``serving.replica.die`` pattern)."""
    from ..common import faultline
    return bool(faultline.site("engine.fastpath.stale_dispatch"))


def schedule_sig(profile) -> str:
    """Stable signature of one cycle profile (hashed so the KV record
    stays small; members compare signatures, never full profiles)."""
    return hashlib.sha1(repr(profile).encode()).hexdigest()[:16]


class _Frozen:
    __slots__ = ("payload", "group_id")

    def __init__(self, payload: Dict[str, Any], group_id: int):
        self.payload = payload
        self.group_id = group_id


class ScheduleFreezer:
    """Warm-streak counter + freeze/thaw state machine for one engine.

    The engine feeds :meth:`observe` one profile per negotiated cycle
    and calls :meth:`freeze` when the streak trips; callers on the
    enqueue path read :meth:`frozen` (racy fast check) and re-check it
    under ``stage_lock`` — the same lock :meth:`thaw` mutates the
    frozen latch under, so a thaw and an in-flight staging operation
    fully serialize and a thaw's ``on_thaw`` flush always sees a
    consistent staged set.
    """

    def __init__(self, warm_cycles: int, enabled: bool = True,
                 spmd: bool = False, plane_name: str = "eager",
                 on_thaw: Optional[Callable[[Dict[str, Any], str], None]]
                 = None,
                 stage_lock=None):
        self.warm_cycles = max(1, int(warm_cycles))
        self.enabled = bool(enabled)
        self.plane_name = plane_name
        self._spmd = bool(spmd)
        self._on_thaw = on_thaw
        # Streak/seq state lock (leaf: nothing is called while held).
        self._lock = threading.Lock()
        # The frozen latch is guarded by the engine's staging lock so
        # thaw-vs-stage races cannot dispatch off a dead schedule.
        self._stage_lock = (stage_lock if stage_lock is not None
                            else threading.RLock())
        self._last_profile = None  # graftlint: guarded-by=_lock
        self._streak = 0  # graftlint: guarded-by=_lock
        self._seq = 0  # freeze proposals made  # graftlint: guarded-by=_lock
        self._warned_no_kv = False  # graftlint: guarded-by=_lock
        self._frozen: Optional[_Frozen] = None  # graftlint: guarded-by=_stage_lock

    # -- read side ---------------------------------------------------------

    def frozen(self) -> Optional[Dict[str, Any]]:
        """Current frozen schedule payload (None = negotiating).  The
        bare read is the hot-path fast check; stage paths re-check
        under ``stage_lock`` before trusting it."""
        fz = self._frozen
        return fz.payload if fz is not None else None

    def frozen_group_id(self) -> Optional[int]:
        fz = self._frozen
        return fz.group_id if fz is not None else None

    @property
    def streak(self) -> int:
        with self._lock:
            return self._streak

    # -- warm counting -----------------------------------------------------

    def observe(self, profile) -> bool:
        """Feed one negotiated cycle's schedule profile (None = not
        freezable); returns True when the warm streak just tripped and
        the engine should attempt :meth:`freeze`."""
        if not self.enabled:
            return False
        with self._lock:
            if self._frozen is not None:
                return False
            if profile is None or profile != self._last_profile:
                self._last_profile = profile
                self._streak = 1 if profile is not None else 0
                return False
            self._streak += 1
            return self._streak >= self.warm_cycles

    def reset_streak(self):
        with self._lock:
            self._streak = 0
            self._last_profile = None

    # -- freeze ------------------------------------------------------------

    def freeze(self, payload: Dict[str, Any], group_id: int,
               ok: bool = True) -> bool:
        """Freeze ``payload`` (the engine's cached schedule) as of
        collective group ``group_id``.  ``ok`` is the engine's local
        eligibility gate (e.g. no in-flight negotiated work); on SPMD
        planes only rank 0's gate decides and members adopt the
        verdict.  Returns True when the schedule is now frozen."""
        if not self.enabled:
            return False
        verdict = self._agree_freeze(payload, ok) if self._spmd else ok
        if not verdict:
            # A refused proposal restarts warm counting everywhere at
            # the same cycle index (locally trivial; SPMD because the
            # verdict itself is uniform).
            self.reset_streak()
            return False
        with self._stage_lock:
            if self._frozen is None:
                self._frozen = _Frozen(dict(payload), int(group_id))
        LOG.info(
            "fast path FROZEN (%s plane): %d-slot schedule cached as of "
            "group %d after %d identical cycles — dispatch now skips "
            "negotiation until a thaw",
            self.plane_name, len(payload.get("slots", ())), group_id,
            self.warm_cycles)
        metrics.event("fastpath_freeze", plane=self.plane_name,
                      group=int(group_id), sig=payload.get("sig"),
                      slots=len(payload.get("slots", ())))
        return True

    def _agree_freeze(self, payload, ok: bool) -> bool:  # graftlint: spmd-uniform -- rank-0-decide -> KV-adopt: every member's warm streak trips at the same negotiated-record index (records are coordinator-broadcast, so the observed schedule stream is identical on every member); rank 0 publishes {seq, sig, freeze} under the fingerprint key and members block for a record covering THEIR OWN proposal seq, adopting rank 0's verdict on a signature match — freeze state can never diverge (a frozen rank stops feeding the coordinator, so a half-frozen world is the r14 hang class).  KV-less multi-member worlds never freeze (warned once) and a member that cannot reach rank 0's record raises rather than guess.
        from ..utils import plancache
        plane = plancache.world_plane()
        size = plane.size or 1
        if size <= 1:
            return ok
        if plane.kv is None:
            with self._lock:
                if not self._warned_no_kv:
                    self._warned_no_kv = True
                    LOG.warning(
                        "fast path: multi-member world with no "
                        "rendezvous KV to agree through (set "
                        "HOROVOD_RENDEZVOUS_ADDR) — schedules stay "
                        "unfrozen (a rank-local freeze would desync "
                        "the negotiation loop)")
            return False
        with self._lock:
            self._seq += 1
            seq = self._seq
        sig = payload.get("sig")
        key = _FREEZE_KEY % (plancache.SCHEMA_VERSION,
                             plane.fingerprint or "world")
        if plane.rank in (None, 0):
            plane.kv.put_json(
                key, {"seq": seq, "sig": sig, "freeze": bool(ok)})
            return bool(ok)
        deadline = time.monotonic() + 60.0
        while True:
            rec = plane.kv.get_json(key)
            if isinstance(rec, dict) and rec.get("seq", 0) >= seq:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "fast-path freeze: rank 0 never published verdict "
                    "#%d — members must adopt rank 0's freeze or not "
                    "at all (a half-frozen world wedges negotiation)"
                    % seq)
            time.sleep(0.05)
        if rec.get("seq") != seq or rec.get("sig") != sig:
            # Proposal streams diverged (this member tripped on a
            # different schedule or index than rank 0): refuse and
            # re-warm rather than freeze on a schedule rank 0 didn't
            # certify.
            LOG.warning(
                "fast-path freeze verdict mismatch (rank 0 published "
                "seq=%s sig=%s, local seq=%d sig=%s); staying thawed",
                rec.get("seq"), rec.get("sig"), seq, sig)
            return False
        if rec.get("freeze") and not ok:
            LOG.warning(
                "fast path: adopting rank 0's freeze verdict with "
                "local in-flight negotiated work still pending — an "
                "async enqueue pattern straddling the freeze point "
                "can only resolve through the collective deadline")
        return bool(rec.get("freeze"))

    # -- thaw --------------------------------------------------------------

    def thaw(self, reason: str, detail: str = "") -> bool:
        """Invalidate the frozen schedule back to full negotiation.
        Loud on purpose: warning log + ``fastpath_thaws_total{reason}``
        + a ``fastpath_thaw`` event carrying the frozen group id.
        No-op (False) when nothing is frozen."""
        if reason not in THAW_REASONS:
            raise ValueError("unknown thaw reason %r (one of %s)"
                             % (reason, ", ".join(THAW_REASONS)))
        with self._stage_lock:
            fz, self._frozen = self._frozen, None
            if fz is None:
                return False
            self.reset_streak()
            metrics.counter("fastpath_thaws_total", reason=reason).inc()
            metrics.event("fastpath_thaw", plane=self.plane_name,
                          reason=reason, group=fz.group_id,
                          sig=fz.payload.get("sig"), detail=detail)
            LOG.warning(
                "fast path THAWED (%s plane, reason=%s%s): frozen "
                "schedule of group %d (%d slot(s)) falls back to full "
                "negotiation",
                self.plane_name, reason,
                ", " + detail if detail else "", fz.group_id,
                len(fz.payload.get("slots", ())))
            if self._on_thaw is not None:
                # Still under stage_lock (reentrant): the flush sees
                # the exact staged set the thaw interrupted.
                try:
                    self._on_thaw(fz.payload, reason)
                except Exception:  # noqa: BLE001 - flush must not mask the thaw
                    LOG.exception("fast-path thaw flush failed")
        return True


# -- module registry (external invalidation planes reach engines here) -----

_REG_LOCK = threading.Lock()
_FREEZERS: List[ScheduleFreezer] = []  # graftlint: guarded-by=_REG_LOCK
# Optional provider of the native core's avoided-negotiation-round
# counter (installed by the multihost engine when the .so exports it).
_CORE_ROUNDS: Optional[Callable[[], int]] = None


def register(freezer: ScheduleFreezer):
    with _REG_LOCK:
        if freezer not in _FREEZERS:
            _FREEZERS.append(freezer)


def unregister(freezer: ScheduleFreezer):
    with _REG_LOCK:
        if freezer in _FREEZERS:
            _FREEZERS.remove(freezer)


def set_core_rounds_provider(fn: Optional[Callable[[], int]]):
    global _CORE_ROUNDS
    _CORE_ROUNDS = fn


def thaw_all(reason: str, detail: str = "") -> int:
    """Thaw every registered engine's frozen schedule (no-op on
    engines that aren't frozen).  The hook every loud-invalidation
    plane calls: plan-staleness trips, degraded-route verdicts,
    collective-deadline expiry, membership changes."""
    with _REG_LOCK:
        freezers = list(_FREEZERS)
    return sum(1 for fz in freezers if fz.thaw(reason, detail))


def reset():
    """Test hook: drop registered freezers and the core provider."""
    global _CORE_ROUNDS
    with _REG_LOCK:
        del _FREEZERS[:]
    _CORE_ROUNDS = None


def describe() -> Dict[str, Any]:
    """The ``levers.fastpath`` self-attribution block (bench.py and
    the allreduce_bw A/B leg): frozen/thaw counters from the live
    metrics plus per-plane freezer state.  Degrades to counters-only
    before/without ``hvd.init``."""
    snap = metrics.snapshot()
    thaws: Dict[str, float] = {}
    for row in (snap.get("fastpath_thaws_total") or {}).get("series", []):
        r = row.get("labels", {}).get("reason", "?")
        thaws[r] = thaws.get(r, 0.0) + float(row.get("value", 0.0))
    with _REG_LOCK:
        freezers = list(_FREEZERS)
    planes = {}
    for fz in freezers:
        planes[fz.plane_name] = {
            "enabled": fz.enabled,
            "frozen": fz.frozen() is not None,
            "warm_streak": fz.streak,
            "warm_cycles": fz.warm_cycles,
        }
    out: Dict[str, Any] = {
        "frozen_cycles_total": metrics.series_sum(
            "fastpath_frozen_cycles_total"),
        "thaws_total": sum(thaws.values()),
        "thaws_by_reason": thaws,
        "planes": planes,
    }
    if _CORE_ROUNDS is not None:
        try:
            out["core_idle_rounds_skipped"] = int(_CORE_ROUNDS())
        except Exception:  # noqa: BLE001 - stale .so, degraded attribution
            out["core_idle_rounds_skipped"] = None
    return out


def bucket_ends(sizes: List[int], buckets: int, cap_bytes: int
                ) -> List[int]:
    """Partition a frozen cycle's per-slot byte sizes into up to
    ``buckets`` contiguous overlap buckets (balanced by bytes, each
    additionally capped at the fusion threshold); returns the
    exclusive end index of every bucket — the staging path dispatches
    a bucket the instant the slot at ``end - 1`` lands."""
    n = len(sizes)
    if n == 0:
        return []
    buckets = max(1, min(int(buckets), n))
    total = sum(sizes) or 1
    target = total / float(buckets)
    ends: List[int] = []
    acc = 0
    for i, s in enumerate(sizes):
        acc += int(s)
        if i == n - 1 or acc >= target or acc > cap_bytes:
            ends.append(i + 1)
            acc = 0
    return ends
