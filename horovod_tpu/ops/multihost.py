"""Multihost (multi-controller SPMD) collective execution.

The TPU-native realisation of the reference's MPI-control/NCCL-payload
split (``horovod/common/ops/nccl_operations.cc`` executing payloads while
the MPI/Gloo controller negotiates, SURVEY.md §2.6): one process per
host, every process a member of one global ``jax`` runtime
(``jax.distributed.initialize``).  The native TCP core negotiates
readiness and a single cross-rank execution order; this module's
executor drains the negotiated group records and runs each collective as
a compiled XLA program over the GLOBAL device mesh — ICI/DCN on TPU
pods, gloo on the CPU test world.

Rank semantics: one Horovod rank per process (host), exactly the
reference's model.  A process's collective input is ITS tensor; the
global mesh carries one leading "proc" axis (one row per member process)
and a "local" axis over each process's addressable devices, on which
contributions are replicated.

Ordering contract: all member processes must issue the same global
collective programs in the same order or the runtime deadlocks — that is
precisely what the control plane guarantees, and why eager collectives
may ONLY be executed by this engine's single executor thread (the role
the reference's background thread plays for NCCL kernels).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..common.config import Config
from ..utils.timeline import Timeline
from . import xla_ops
from .engine import CollectiveHandle, HorovodInternalError
from .xla_ops import ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM

LOG = logging.getLogger("horovod_tpu")


from .xla_ops import uneven_chunks as _uneven_chunks


class GlobalMeshCollectives:
    """Compiled XLA collectives over the global (all-process) mesh.

    Every method is a *collective program*: all member processes must
    call it with consistent arguments (guaranteed by negotiation).
    Executables are cached per (op, dtype, shape, params) so steady
    state dispatches without retracing.
    """

    def __init__(self, member_procs: Optional[Sequence[int]] = None,
                 name: str = "global"):
        import jax
        from jax.sharding import Mesh

        all_procs = sorted({d.process_index for d in jax.devices()})
        self.procs = (list(member_procs) if member_procs is not None
                      else all_procs)
        self.size = len(self.procs)
        self.name = name
        self.my_idx = (self.procs.index(jax.process_index())
                       if jax.process_index() in self.procs else -1)
        devs = sorted((d for d in jax.devices()
                       if d.process_index in set(self.procs)),
                      key=lambda d: (self.procs.index(d.process_index),
                                     d.id))
        n_local = len(devs) // self.size
        self.mesh = Mesh(
            np.asarray(devs).reshape(self.size, n_local),
            ("proc", "local"))
        self._fns: Dict[tuple, object] = {}

    # -- plumbing ----------------------------------------------------------

    def _sharding(self, spec):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, spec)

    def _global(self, local: np.ndarray):
        """Stage this process's block [1, ...] into a global array
        [size, ...] sharded over the proc axis (replicated over local
        devices within each process)."""
        import jax
        from jax.sharding import PartitionSpec as P
        global_shape = (self.size,) + tuple(local.shape[1:])
        return jax.make_array_from_process_local_data(
            self._sharding(P("proc")), local, global_shape)

    def _fetch(self, arr) -> np.ndarray:
        """Host value of a replicated global array."""
        import jax
        shard = arr.addressable_shards[0].data
        return np.asarray(jax.device_get(shard))

    def _compiled(self, key, build):
        fn = self._fns.get(key)
        if fn is None:
            fn = build()
            self._fns[key] = fn
        return fn

    # -- collectives -------------------------------------------------------

    def allreduce(self, local_flat: np.ndarray, red_op: str = SUM,
                  prescale: float = 1.0, postscale: float = 1.0
                  ) -> np.ndarray:
        """Reduce one flat [n] contribution per process -> [n]."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        x = np.asarray(local_flat)[None]  # [1, n]
        size = self.size
        key = ("allreduce", str(x.dtype), x.shape, red_op,
               float(prescale), float(postscale))

        def build():
            def fn(g):
                v = g * np.asarray(prescale, g.dtype) \
                    if prescale != 1.0 else g
                if red_op in (SUM, AVERAGE, ADASUM):
                    r = jnp.sum(v, axis=0)
                    if red_op == AVERAGE:
                        r = (r / size).astype(v.dtype) if \
                            jnp.issubdtype(v.dtype, jnp.floating) \
                            else r // size
                elif red_op == MIN:
                    r = jnp.min(v, axis=0)
                elif red_op == MAX:
                    r = jnp.max(v, axis=0)
                elif red_op == PRODUCT:
                    r = jnp.prod(v, axis=0)
                else:
                    raise NotImplementedError(red_op)
                if postscale != 1.0:
                    r = r * np.asarray(postscale, r.dtype)
                return r

            return jax.jit(fn, out_shardings=self._sharding(P()))

        return self._fetch(self._compiled(key, build)(self._global(x)))

    def broadcast(self, local: np.ndarray, root_idx: int) -> np.ndarray:
        """Member ``root_idx``'s tensor to every process."""
        import jax
        from jax.sharding import PartitionSpec as P

        x = np.asarray(local)[None]
        key = ("broadcast", str(x.dtype), x.shape, int(root_idx))

        def build():
            return jax.jit(lambda g: g[root_idx],
                           out_shardings=self._sharding(P()))

        return self._fetch(self._compiled(key, build)(self._global(x)))

    def allgather(self, local: np.ndarray,
                  rows_per_member: Sequence[int]) -> np.ndarray:
        """Concat dim-0-ragged per-process tensors (reference
        AllgatherOp): pad to the max row count, one XLA all-gather,
        slice the valid segments back out."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        rows = [int(r) for r in rows_per_member]
        max_rows = max(rows) if rows else 0
        x = np.asarray(local)
        pad = max_rows - x.shape[0]
        if pad:
            x = np.concatenate(
                [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        x = x[None]
        key = ("allgather", str(x.dtype), x.shape, tuple(rows))

        def build():
            return jax.jit(lambda g: g,
                           out_shardings=self._sharding(P()))

        full = self._fetch(self._compiled(key, build)(self._global(x)))
        return np.concatenate(
            [full[j, :rows[j]] for j in range(self.size)])

    def alltoall(self, local: np.ndarray, splits_matrix: np.ndarray):
        """Member-major splits matrix routing (reference AlltoallOp).

        v1 moves the exchange as one padded all-gather then local
        slicing — correct on any mesh; a `lax.all_to_all` fast path for
        the uniform case is a recorded follow-up.
        Returns (my_received_rows, recv_splits).
        """
        sm = np.asarray(splits_matrix).reshape(self.size, self.size)
        send_rows = [int(sm[j].sum()) for j in range(self.size)]
        gathered = self.allgather(local, send_rows)
        # Segment offsets inside each sender's block.
        out = []
        base = 0
        recv_splits = []
        for j in range(self.size):  # sender
            off = int(sm[j, :self.my_idx].sum())
            cnt = int(sm[j, self.my_idx])
            out.append(gathered[base + off: base + off + cnt])
            recv_splits.append(cnt)
            base += send_rows[j]
        return np.concatenate(out) if out else gathered[:0], recv_splits

    def reducescatter(self, local: np.ndarray, red_op: str = SUM
                      ) -> np.ndarray:
        """Reduce then take this member's dim-0 shard (uneven chunks
        follow the reference's earlier-ranks-larger split)."""
        reduced = self.allreduce(
            np.asarray(local).reshape(-1), red_op).reshape(local.shape)
        rows, offs = _uneven_chunks(local.shape[0], self.size)
        i = self.my_idx
        return reduced[offs[i]: offs[i] + rows[i]]


class MultihostEngine:
    """Single executor thread draining the core's negotiated groups.

    Enqueue side: ops are registered with the control plane
    (``TcpCore.enqueue_external``) and the local payload parked here.
    Executor side: for each negotiated group (one fused Response), run
    the XLA collective over the global mesh in negotiation order, then
    complete both the Python handles and the core entries.
    """

    def __init__(self, core, config: Config, timeline: Timeline,
                 process_set_resolver):
        self.core = core
        self.config = config
        self.timeline = timeline
        self._resolve_process_set = process_set_resolver
        self._collectives: Dict[int, GlobalMeshCollectives] = {}
        self._lock = threading.Lock()
        # core handle -> (py handle, local payload ndarray, orig shape)
        self._pending: Dict[int, tuple] = {}
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-multihost-exec", daemon=True)
        self._thread.start()

    # -- process-set meshes ------------------------------------------------

    def collectives_for(self, process_set_id: int) -> GlobalMeshCollectives:
        mc = self._collectives.get(process_set_id)
        if mc is None:
            ranks = self._resolve_process_set(process_set_id)
            mc = GlobalMeshCollectives(ranks, name="ps%d" % process_set_id)
            self._collectives[process_set_id] = mc
        return mc

    def invalidate_process_set(self, process_set_id: int):
        self._collectives.pop(process_set_id, None)

    # -- enqueue API (per-rank tensor semantics) ---------------------------

    def _enqueue(self, name, op_type, arr, **kw) -> CollectiveHandle:
        py = CollectiveHandle(name)
        # Enqueue and park ATOMICALLY w.r.t. the executor's _take: the
        # instant enqueue_external returns, the background thread can
        # negotiate the op and the executor can pop its record — if the
        # payload weren't parked yet, this rank would contribute zeros
        # and the handle would never resolve.
        with self._lock:
            ch = self.core.enqueue_external(
                name, op_type, arr.shape, arr.dtype, **kw)
            self._pending[ch._h] = (py, arr)
        return py

    def enqueue_allreduce(self, name, tensor, red_op=SUM, prescale=1.0,
                          postscale=1.0, process_set_id=0
                          ) -> CollectiveHandle:
        arr = np.ascontiguousarray(np.asarray(tensor))
        return self._enqueue(
            name, "allreduce", arr, red_op=red_op,
            process_set_id=process_set_id, prescale=prescale,
            postscale=postscale)

    def enqueue_allgather(self, name, tensor, process_set_id=0
                          ) -> CollectiveHandle:
        arr = np.ascontiguousarray(np.asarray(tensor))
        return self._enqueue(name, "allgather", arr,
                             process_set_id=process_set_id)

    def enqueue_broadcast(self, name, tensor, root_rank=0,
                          process_set_id=0) -> CollectiveHandle:
        arr = np.ascontiguousarray(np.asarray(tensor))
        return self._enqueue(name, "broadcast", arr,
                             root_rank=root_rank,
                             process_set_id=process_set_id)

    def enqueue_alltoall(self, name, tensor, splits=None,
                         process_set_id=0) -> CollectiveHandle:
        arr = np.ascontiguousarray(np.asarray(tensor))
        if splits is None:
            n = self.collectives_for(process_set_id).size
            if arr.shape[0] % n:
                raise ValueError(
                    "uniform alltoall needs dim0 %% set size (%d) == 0"
                    % n)
            splits = [arr.shape[0] // n] * n
        return self._enqueue(name, "alltoall", arr, splits=list(splits),
                             process_set_id=process_set_id)

    def enqueue_reducescatter(self, name, tensor, red_op=SUM,
                              process_set_id=0) -> CollectiveHandle:
        arr = np.ascontiguousarray(np.asarray(tensor))
        return self._enqueue(name, "reducescatter", arr, red_op=red_op,
                             process_set_id=process_set_id)

    # -- executor ----------------------------------------------------------

    def _loop(self):
        from ..core.client import parse_negotiated_record
        while not self._shutdown:
            rec = self.core.next_negotiated()
            if rec is None:
                time.sleep(self.config.cycle_time_ms / 2e3)
                continue
            try:
                self._execute(parse_negotiated_record(rec))
            except Exception as exc:  # noqa: BLE001 - keep draining
                LOG.error("multihost executor: %s", exc)

    def _take(self, handle: int):
        with self._lock:
            return self._pending.pop(handle, (None, None))

    def _execute(self, g: dict):
        mc = self.collectives_for(g["process_set_id"])
        entries = g["entries"]
        taken = [self._take(e["handle"]) if e["handle"] >= 0
                 else (None, None) for e in entries]
        try:
            results = self._run_group(g, mc, taken)
            for (py, _), res, e in zip(taken, results, entries):
                if e["handle"] >= 0:
                    self.core.external_done(e["handle"], ok=True)
                    self.core._lib.hvd_tcp_release(e["handle"])
                if py is not None:
                    py._set_result(res)
        except Exception as exc:  # noqa: BLE001
            LOG.error("multihost %s failed: %s", g["op_type"], exc)
            for (py, _), e in zip(taken, entries):
                if e["handle"] >= 0:
                    self.core.external_done(e["handle"], ok=False,
                                            error=str(exc))
                    self.core._lib.hvd_tcp_release(e["handle"])
                if py is not None:
                    py._set_error(exc)

    def _run_group(self, g: dict, mc: GlobalMeshCollectives,
                   taken: List[tuple]) -> List:
        op = g["op_type"]
        dtype = g["dtype"]
        if op == "allreduce":
            # Fused group: concat flats in negotiated order (missing =
            # joined rank -> zero contribution), one collective, split.
            # The controller rejects joined + Min/Max/Product/Adasum at
            # negotiation and rewrites Average to Sum with a live-count
            # divisor; by the time a zero-fill reaches this executor the
            # reduction must be Sum (the only op whose identity is zero).
            if (any(arr is None for _, arr in taken)
                    and g["red_op"] != SUM):
                raise HorovodInternalError(
                    "zero-contribution join reached the executor with "
                    "op=%s; only Sum may be zero-filled" % g["red_op"])
            lengths = [int(n) for n in g["aux_sizes"]]
            flats, shapes = [], []
            for (py, arr), ln in zip(taken, lengths):
                if arr is None:
                    flats.append(np.zeros((ln,), dtype))
                    shapes.append((ln,))
                else:
                    flats.append(arr.reshape(-1))
                    shapes.append(arr.shape)
            fused = np.concatenate(flats) if len(flats) > 1 else flats[0]
            out = mc.allreduce(fused, g["red_op"], g["prescale"],
                               g["postscale"])
            results, off = [], 0
            for ln, shape in zip(lengths, shapes):
                results.append(out[off:off + ln].reshape(shape))
                off += ln
            return results
        (py, arr) = taken[0]
        if op == "allgather":
            rows = g["aux_sizes"]
            return [mc.allgather(arr, rows)]
        if op == "broadcast":
            # root_rank is a GLOBAL rank; map to member index.
            ranks = self._resolve_process_set(g["process_set_id"])
            members = ranks if ranks is not None else list(
                range(mc.size))
            root_idx = members.index(g["root_rank"])
            return [mc.broadcast(arr, root_idx)]
        if op == "alltoall":
            out, recv = mc.alltoall(arr, np.asarray(g["aux_sizes"]))
            return [(out, recv)]
        if op == "reducescatter":
            return [mc.reducescatter(arr, g["red_op"])]
        raise NotImplementedError("multihost op %r" % op)

    # -- shutdown ----------------------------------------------------------

    def shutdown(self):
        self._shutdown = True
        self._thread.join(timeout=10.0)
        with self._lock:
            pending, self._pending = self._pending, {}
        for py, _ in pending.values():
            if not py.poll():
                py._set_error(
                    HorovodInternalError("engine shut down"))
