"""Multihost (multi-controller SPMD) collective execution.

The TPU-native realisation of the reference's MPI-control/NCCL-payload
split (``horovod/common/ops/nccl_operations.cc`` executing payloads while
the MPI/Gloo controller negotiates, SURVEY.md §2.6): one process per
host, every process a member of one global ``jax`` runtime
(``jax.distributed.initialize``).  The native TCP core negotiates
readiness and a single cross-rank execution order; this module's
executor drains the negotiated group records and runs each collective as
a compiled XLA program over the GLOBAL device mesh — ICI/DCN on TPU
pods, gloo on the CPU test world.

Rank semantics: one Horovod rank per process (host), exactly the
reference's model.  A process's collective input is ITS tensor.  The
eager payload plane has two gears: small payloads ride a
one-device-per-process mesh (axis "proc", device 0 of every member),
and payloads at or above ``HOROVOD_HIERARCHICAL_ALLREDUCE_THRESHOLD``
ride a proc x local mesh spanning EVERY local chip — chunk j of the
payload lives on local device j, cross-host reduction moves 1/k of the
bytes per chip, and a local ``all_gather`` reassembles the result over
intra-host ICI (the reference's NCCL hierarchical allreduce,
``HOROVOD_HIERARCHICAL_ALLREDUCE``).  jit-path data parallelism
(``jax/data_parallel.py``) keeps using every addressable device.

Ordering contract: all member processes must issue the same global
collective programs in the same order or the runtime deadlocks — that is
precisely what the control plane guarantees, and why eager collectives
may ONLY be executed by this engine's single executor thread (the role
the reference's background thread plays for NCCL kernels).
"""

from __future__ import annotations

import collections
import logging
import queue as queue_mod
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..common import faultline, metrics, resilience
from ..common.config import Config
from ..utils.timeline import Timeline
from . import fastpath, xla_ops
from .engine import (CollectiveDeadlineExceeded, CollectiveHandle,
                     HorovodInternalError)
from .xla_ops import (ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM,
                      alltoall_chunk_reduce, product_allreduce)

LOG = logging.getLogger("horovod_tpu")


from .xla_ops import uneven_chunks as _uneven_chunks

# Above this many bytes, exact pow2 bucketing would waste up to 2x wire
# bytes on padding; large payloads round up to the next multiple of it
# instead (pad waste bounded by the threshold, still a small number of
# size classes for the executable cache).
_POW2_BUCKET_MAX_BYTES = 4 << 20


def _size_class(n_elems: int, itemsize: int) -> int:
    """Padded element count keying a packed collective executable:
    power-of-two below ``_POW2_BUCKET_MAX_BYTES`` (the recompile-cliff
    protection for shape-varying bursts), coarse linear steps above it
    (bounded pad waste for big tensors)."""
    from .engine import _bucket
    step = max(_POW2_BUCKET_MAX_BYTES // max(int(itemsize), 1), 1)
    n = max(int(n_elems), 1)
    if n <= step:
        return _bucket(n)
    return -(-n // step) * step


def _is_device_array(x) -> bool:
    import jax
    return isinstance(x, jax.Array)


def _pow2_class(nbytes: int) -> str:
    """Pow2-ceil byte class labeling per-collective metric series: ~40
    distinct values per op across any realistic payload range, so the
    full 5-op (op, size_class) space (~200 combos worst case) stays
    inside the default HOROVOD_METRICS_MAX_SERIES cap of 256."""
    n = max(int(nbytes), 1)
    return str(1 << (n - 1).bit_length())


def _count_path(op: str, nbytes: int, hier: bool, codec=None,
                wire_bytes=None):
    """Path attribution for one executed collective: which plane moved
    the bytes (hier = proc x local mesh, flat = one-device-per-process)
    and what actually hit the WIRE.  ``mh_bus_bytes_total`` is a
    wire-bytes counter: with a cross-host codec active it records the
    compressed ``wire_bytes`` (payload elements at the wire itemsize
    plus scale overhead), otherwise the pre-padding payload bytes —
    the self-attribution the BENCH compression A/B reads."""
    path = "hier" if hier else "flat"
    metrics.counter("mh_collective_path_total", op=op, path=path).inc()
    wire = (int(wire_bytes) if codec is not None and wire_bytes
            else max(int(nbytes), 0))
    metrics.counter("mh_bus_bytes_total", op=op, path=path).inc(
        max(wire, 0))
    if codec is not None and wire_bytes:
        metrics.counter("mh_compressed_collectives_total", op=op,
                        codec=codec.name).inc()
        metrics.gauge("mh_compression_ratio", op=op,
                      codec=codec.name).set(
            round(max(int(nbytes), 0) / float(wire_bytes), 4))


class _WireCodec:
    """Resolved cross-host wire codec (HOROVOD_CROSS_HOST_COMPRESSION):
    ``kind`` 'cast' rides the existing cross-host legs natively in the
    narrower ``wire`` dtype (fp16/bf16 arithmetic is well-defined on
    every backend); ``kind`` 'quant' (int8/fp8) never does arithmetic
    in the wire dtype — wire payloads move via exchange legs (two-phase
    reduce-scatter/all-gather for allreduce, masked byte-psum for
    broadcast, all_to_all/all_gather with per-sender scales elsewhere)
    and dequantize to f32 on the far side."""

    __slots__ = ("name", "kind", "wire")

    def __init__(self, name: str, kind: str, wire):
        self.name = name
        self.kind = kind
        self.wire = np.dtype(wire)


def _resolve_codec(name: str) -> Optional[_WireCodec]:
    """Config codec string -> _WireCodec (None for 'none').  fp8 on a
    jax without float8 dtypes downgrades LOUDLY to a bf16 wire (2x,
    not 4x) instead of silently shipping full precision."""
    import jax.numpy as jnp
    if name in (None, "", "none"):
        return None
    if name == "fp16":
        return _WireCodec("fp16", "cast", np.float16)
    if name == "bf16":
        return _WireCodec("bf16", "cast", jnp.bfloat16)
    if name == "int8":
        return _WireCodec("int8", "quant", np.int8)
    if name == "fp8":
        from ..jax.compression import FP8_WIRE_DTYPE
        if FP8_WIRE_DTYPE is None:
            LOG.error(
                "HOROVOD_CROSS_HOST_COMPRESSION=fp8: this jax version "
                "has no float8_e4m3fn dtype; falling back to a bf16 "
                "wire (2x reduction instead of 4x)")
            return _WireCodec("fp8-as-bf16", "cast", jnp.bfloat16)
        return _WireCodec("fp8", "quant", FP8_WIRE_DTYPE)
    raise ValueError("unknown cross-host compression codec %r" % name)


def _axis0_reduce(deq, red_op, size: int):
    """Reduce f32 dequantized contributions [members, n] -> [n] per
    the negotiated op (AVERAGE divides by the full member count, like
    the uncompressed planes; join cannot reach the compressed leg)."""
    import jax.numpy as jnp
    if red_op in (SUM, AVERAGE):
        r = jnp.sum(deq, axis=0)
        if red_op == AVERAGE:
            r = r / size
    elif red_op == MIN:
        r = jnp.min(deq, axis=0)
    elif red_op == MAX:
        r = jnp.max(deq, axis=0)
    elif red_op == PRODUCT:
        r = jnp.prod(deq, axis=0)
    else:
        raise NotImplementedError(red_op)
    return r


def _chunked_segments(p, n_items, item_start, item_valid, bc, k):
    """Segment list staging k j-major local chunks of ``n_items``
    padded items: local chunk j carries, for every item m, elements
    [j*bc, (j+1)*bc) of item m's (bc*k)-padded span.  Items are slices
    of ``p`` at ``item_start[m]`` with ``item_valid[m]`` live elements;
    the remainder pads with zeros.  Shared by the hierarchical alltoall
    (items = destination blocks) and reducescatter (items = member
    segments) staging paths."""
    segs = []
    for j in range(k):
        for m in range(n_items):
            lo = j * bc
            take = min(max(int(item_valid[m]) - lo, 0), bc)
            if take:
                segs.append((p, int(item_start[m]) + lo, take))
            if take < bc:
                segs.append((None, 0, bc - take))
    return segs


def adasum_combine(v, axis_name: str, size: int):
    """Device-resident Adasum over a mesh axis (per-shard code).

    The reference's GPU-resident Adasum (SURVEY §2.2,
    ``adasum_gpu_operations.cc``) keeps payloads on the accelerator;
    here the recursive-halving tree of ``utils/adasum.py`` runs as
    log2(size) ``ppermute`` exchange rounds over the axis: partners at
    XOR-stride distance swap full vectors, both compute the SAME
    symmetric merge, and every shard converges to the tree result —
    bytes = n·log2(N) over ICI, no host bounce.  Merge order matches
    ``utils/adasum.adasum_reduce_stacked`` (strides n/2, n/4, …, 1 =
    the stacked halving tree), including the per-round cast back to
    the payload dtype.
    """
    import jax
    import jax.numpy as jnp
    if size & (size - 1):
        raise HorovodInternalError(
            "Adasum requires a power-of-two member count (got %d), as "
            "in the reference's recursive-halving implementation" % size)
    from ..utils.adasum import adasum_pair
    stride = size // 2
    while stride >= 1:
        perm = [(i, i ^ stride) for i in range(size)]
        w = jax.lax.ppermute(v, axis_name, perm)
        # adasum_pair is the single source of truth for the merge rule
        # (f32 dots, epsilon guard, payload-dtype round-trip) — both
        # partners compute the SAME symmetric merge, so every shard
        # converges to the host tree's result.
        v = adasum_pair(v, w)
        stride //= 2
    return v


class GlobalMeshCollectives:
    """Compiled XLA collectives over the member processes' devices.

    The base plane is the reference's one-accelerator-per-rank NCCL
    model (``ops/nccl_operations.cc``): each member process owns one
    mesh device (its first addressable device), payloads stay
    device-resident end to end — ``jax.Array`` inputs are staged with
    a device-to-device put (no host bounce), numpy inputs with a
    single host-to-device transfer — and every collective is explicit
    HLO (``psum`` / ``all_gather`` / ``all_to_all`` / ``psum_scatter``
    under ``shard_map``), not a host-staged emulation.  Large
    allreduces additionally shard across every LOCAL chip
    (``_hier_allreduce``), so all local ICI/DCN links carry payload
    instead of chip 0's alone.

    Every method is a *collective program*: all member processes must
    call it with consistent negotiated arguments.  Executables are
    cached per (op, dtype, shape, params) so steady state dispatches
    without retracing; staged inputs are donated, so XLA may reuse the
    payload buffer for the result (the reference's persistent fusion
    buffer, expressed as buffer donation).
    """

    def __init__(self, member_procs: Optional[Sequence[int]] = None,
                 name: str = "global"):
        import jax
        from jax.sharding import Mesh

        all_procs = sorted({d.process_index for d in jax.devices()})
        self.procs = (list(member_procs) if member_procs is not None
                      else all_procs)
        self.size = len(self.procs)
        self.name = name
        self.my_idx = (self.procs.index(jax.process_index())
                       if jax.process_index() in self.procs else -1)
        by_proc: Dict[int, list] = {}
        for d in sorted(jax.devices(), key=lambda d: d.id):
            by_proc.setdefault(d.process_index, []).append(d)
        missing = [p for p in self.procs if p not in by_proc]
        if missing:
            raise HorovodInternalError(
                "process set %r members %s have no addressable JAX "
                "devices; every member process must expose at least "
                "one device" % (name, missing))
        devs = [by_proc[p][0] for p in self.procs]
        self.mesh = Mesh(np.asarray(devs), ("proc",))
        self.device = devs[self.my_idx] if self.my_idx >= 0 else None  # graftlint: spmd-uniform -- device HANDLE: names where this process STAGES payload bytes (per-rank placement is the SPMD model); no routing decision ever reads it
        from ..common.config import Config as _Cfg
        cfg = _Cfg.from_env()
        # Multi-chip payload plane (reference hierarchical allreduce,
        # SURVEY §2.2 NCCL row): a 2-D proc x local mesh over every
        # member's local chips.  k is the least local device count
        # across members (the mesh must be rectangular); k == 1
        # degenerates to the one-device plane.
        k = min(len(by_proc[p]) for p in self.procs)
        self._hier_mode = cfg.hierarchical_allreduce
        self._hier_threshold = int(cfg.hierarchical_allreduce_threshold)
        self.local_size = k if self._hier_mode != "off" else 1
        self.mesh2 = None
        self.local_devices: list = []
        if self.local_size > 1:
            devs2 = np.asarray(
                [[by_proc[p][j] for j in range(k)] for p in self.procs])
            self.mesh2 = Mesh(devs2, ("proc", "local"))
            self.local_devices = (list(devs2[self.my_idx])
                                  if self.my_idx >= 0 else [])
        # Cross-host wire codec (r12): consulted at the SAME gate as
        # _hier_eligible — only the hier plane has a distinct DCN leg
        # to compress; in-host reassembly stays in the payload dtype.
        # Reduce ops (Sum/Average) go through error-feedback residuals
        # keyed per bucket so quantization error is delayed, not lost.
        self._codec = (_resolve_codec(cfg.cross_host_compression)
                       if self.local_size > 1 else None)
        if (self._codec is None
                and cfg.cross_host_compression != "none"):
            LOG.warning(
                "HOROVOD_CROSS_HOST_COMPRESSION=%s is set but the "
                "hierarchical plane is unavailable (one local device, "
                "or mode 'off'): payloads stay full precision",
                cfg.cross_host_compression)
        self._quantizer = None
        self._ef = None
        if self._codec is not None and self._codec.kind == "quant":
            # fp8 uses the absmax-SCALED e4m3 quantizer here, not the
            # framework-surface plain cast: an unscaled cast NaNs past
            # +-448, and the engine must be range-safe for any payload.
            from ..jax.compression import (ErrorFeedback, Int8Quantizer,
                                           ScaledFP8Quantizer)
            self._quantizer = (Int8Quantizer if self._codec.name == "int8"
                               else ScaledFP8Quantizer)
            self._ef = ErrorFeedback(self._quantizer,
                                     cfg.compression_residual_buckets)
        # Leg-2 (post-reduce) error-feedback residuals of the two-phase
        # quantized allreduce: mesh-sharded device arrays carried across
        # steps as donated program inputs/outputs, LRU-capped like the
        # eager residual buckets.  Executor-thread only.
        self._res2: "collections.OrderedDict" = collections.OrderedDict()
        self._res2_cap = max(int(getattr(
            cfg, "compression_residual_buckets", 64)), 1)
        # Collective-plan plane (persistent autotuned plans): per-(op,
        # size_class) routing decisions — hier/flat leg + codec
        # engagement — from the plan loaded/adopted at init().  None
        # when the plane is disabled or this mesh's topology differs
        # from the tuned fingerprint (process-set sub-meshes); routing
        # then falls back to the global byte-threshold gate unchanged.
        self._plan_ctl = None
        try:
            from ..utils import plancache
            self._plan_ctl = plancache.controller_for(
                self.size, self.local_size,
                getattr(devs[0], "device_kind", devs[0].platform))
        except Exception:  # noqa: BLE001 - plans must never block a mesh
            self._plan_ctl = None
        # Capacity-bounded LRU like the in-process engine (the
        # reference's HOROVOD_CACHE_CAPACITY): long jobs with varying
        # shapes must not grow compiled programs without bound.
        from .executable_cache import ExecutableCache
        self._fns = ExecutableCache(cfg.cache_capacity)
        # key -> lowered HLO text, populated when HVD_TPU_DUMP_HLO=1
        # (lets tests assert the real collective ops are emitted).
        self.hlo: Dict[tuple, str] = {}
        # Count of host (numpy) stagings — device payloads must never
        # bump this (the device-residency contract, testable).
        self.host_stages = 0

    # -- plumbing ----------------------------------------------------------

    def _sharding(self, spec):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, spec)

    def _stage(self, arr, row_shape, dtype):
        """Stage this process's contribution as its row of a global
        [size, *row_shape] array sharded over ``proc``.

        ``jax.Array`` payloads stay on device (at most a local reshape
        + device-to-device put); numpy payloads cross the host boundary
        exactly once; ``None`` (a joined rank's missing entry)
        synthesizes zeros directly on the mesh device.  The staged row
        is always a fresh buffer, so compiled programs may donate it.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        shape = (1,) + tuple(int(d) for d in row_shape)
        if arr is None:
            with jax.default_device(self.device):
                row = jnp.zeros(shape, dtype)
        elif _is_device_array(arr):
            row = jax.device_put(jnp.reshape(arr, shape), self.device)
        else:
            self.host_stages += 1
            row = jax.device_put(
                np.ascontiguousarray(np.asarray(arr)).reshape(shape),
                self.device)
        return jax.make_array_from_single_device_arrays(
            (self.size,) + shape[1:], self._sharding(P("proc")), [row])

    def _replicated(self, garr):
        """This process's view of a replicated (P()) program output, as
        a single-device jax.Array — no host transfer."""
        return garr.addressable_shards[0].data

    def _pack_flat(self, segments, total: int, bucket: int, np_dtype):
        """One padded flat [bucket] buffer on this process's mesh
        device.

        ``segments`` is a list of (payload, start_elem, n_elems) flat
        slices laid out back to back (payload None -> zeros); the
        bucket padding keys the compiled program by SIZE CLASS instead
        of exact composition — the reference's persistent fusion
        buffer, shared by the packed allreduce and the per-op packed
        paths.  Each DISTINCT payload flattens exactly once (device
        payloads: one local reshape/device_put, no host transit; numpy
        payloads: one host crossing, one ``host_stages`` bump), however
        many segments slice it."""
        import jax
        import jax.numpy as jnp

        flats: Dict[int, object] = {}

        def flat_of(payload):
            fid = id(payload)
            f = flats.get(fid)
            if f is None:
                if _is_device_array(payload):
                    f = jax.device_put(jnp.reshape(payload, (-1,)),
                                       self.device)
                else:
                    self.host_stages += 1
                    f = jnp.asarray(np.ascontiguousarray(
                        np.asarray(payload)).reshape(-1))
                flats[fid] = f
            return f

        parts = []
        with jax.default_device(self.device):
            for payload, start, n in segments:
                if n == 0:
                    continue
                if payload is None:
                    parts.append(jnp.zeros((n,), np_dtype))
                else:
                    f = flat_of(payload)
                    parts.append(
                        f if start == 0 and n == f.shape[0]
                        else jax.lax.slice_in_dim(f, start, start + n))
            if bucket > total:
                parts.append(jnp.zeros((bucket - total,), np_dtype))
            row = (jnp.concatenate(parts) if len(parts) > 1
                   else parts[0] if parts
                   else jnp.zeros((bucket,), np_dtype))
            if row.dtype != np_dtype:
                row = row.astype(np_dtype)
        return row

    def _stage_flat_padded(self, segments, total: int, bucket: int,
                           np_dtype):
        """``_pack_flat`` staged as one row of the proc-sharded global
        array."""
        return self._stage(
            self._pack_flat(segments, total, bucket, np_dtype),
            (bucket,), np_dtype)

    def _my_row(self, garr):
        """This process's row of a P('proc') program output."""
        return garr.addressable_shards[0].data[0]

    def _hier_eligible(self, nbytes: int) -> bool:
        """Route this payload over the proc x local mesh?  One shared
        gate for all five eager ops (the reference's NCCL ops drive
        every local accelerator's links for every collective, SURVEY
        §2.2): more than one local chip, and either mode 'on' or the
        payload at/above the hierarchical threshold."""
        return (self.local_size > 1
                and (self._hier_mode == "on"
                     or int(nbytes) >= self._hier_threshold))

    def _route(self, op: str, nbytes: int):  # graftlint: hot-path
        """(use_hier, engage_codec) for one dispatch: the per-(op,
        size_class) plan wins when the plan plane is active (explicit
        gate envs win over it and suppress pinning, resolved at
        controller construction), otherwise the global byte-threshold
        gate with the codec left to ``_wire_codec``.  Every member
        resolves identically — the plan is shared via the cache blob /
        KV adoption — so negotiated programs never diverge."""
        cls = _pow2_class(nbytes)
        hier = self._hier_eligible(nbytes)
        if self._plan_ctl is not None:
            hier, codec_on = self._plan_ctl.route(op, cls, hier)
        else:
            codec_on = True
        # The resilience demotion map is authoritative over every
        # other gate: a demoted class is flat on EVERY member (the
        # map only ever changes through the rank-0 KV verdict), even
        # if a stale plan entry or env pin still says hier.
        if hier and resilience.demoted(op, cls):
            return False, codec_on
        return hier, codec_on

    def _guarded(self, op: str, nbytes: int, run_hier, run_flat,
                 payloads=(), codec=None):  # graftlint: hot-path
        """Run a hier leg under the data-plane guard
        (:func:`resilience.run_hier_leg`: injection sites, wire
        integrity, transient retry under the group deadline), falling
        back to the flat program for THIS group on retry exhaustion.

        The fallback is rank-local by design: the fault shapes the
        guard absorbs exhaust identically on every member (shared DCN
        link, config-driven codec faults, symmetric injection), and a
        genuinely asymmetric exhaustion diverges the programs only
        until the group deadline poisons the engine and elastic
        restores.  Persistent routing never changes here — only the
        rank-0 KV verdict in ``check_degraded_routes`` demotes a
        class."""
        cls = _pow2_class(nbytes)
        try:
            return resilience.run_hier_leg(
                op, cls, run_hier, payloads=payloads,
                quantized=codec is not None and codec.kind == "quant")
        except resilience.LegDegraded as exc:
            LOG.warning(
                "multihost %s[%s]: hier leg degraded (%s); this group "
                "falls back to the flat plane", op, cls, exc.cause)
            return run_flat()

    def _stage_hier(self, segments, total: int, chunk: int, np_dtype):
        """Stage ``segments`` as this process's (1, k, chunk) slab of a
        [size, k, chunk] array over the proc x local mesh: the packed
        flat [k*chunk] buffer splits j-major, chunk j committed to
        local device j via ``_stage_hier_rows`` (one device-to-device
        put per chip; numpy payloads cross the host once inside
        ``_pack_flat``)."""
        k = self.local_size
        flat = self._pack_flat(segments, total, chunk * k, np_dtype)
        return self._stage_hier_rows(flat.reshape(k, chunk))

    def _wire_codec(self, np_dtype, red_op=None) -> Optional[_WireCodec]:
        """The active cross-host codec for a hier-path payload of
        ``np_dtype``: the configured codec when the payload is floating
        and the wire dtype is actually narrower; None otherwise (a
        discrete payload would be corrupted, a same-width cast wins
        nothing).  Product reductions are excluded from the QUANT
        codecs: an element below its chunk's absmax/254 quantizes to
        exactly 0 and zeroes the whole product — unbounded relative
        error, unlike the scale/2-bounded Sum/Average/Min/Max cases."""
        c = self._codec
        if c is None:
            return None
        if red_op == PRODUCT and c.kind == "quant":
            return None
        import jax.numpy as jnp
        dt = np.dtype(np_dtype)
        if not jnp.issubdtype(dt, jnp.floating):
            return None
        if c.wire.itemsize >= dt.itemsize:
            return None
        return c

    def _wire_nbytes(self, codec: _WireCodec, n_elems: int) -> int:
        """Bytes this payload puts on the cross-host wire under
        ``codec``: payload elements at the wire itemsize, plus the
        per-chunk f32 absmax scales of the quantizing codecs (bounded
        by two scale sets per local chunk — the two-phase allreduce
        carries one per leg)."""
        w = int(n_elems) * codec.wire.itemsize
        if codec.kind == "quant":
            w += self.local_size * 8
        return w

    def _stage_hier_rows(self, rows2d):  # graftlint: hot-path
        """Stage an eagerly-encoded per-chunk [k, m] device array (row
        j -> local device j) as this process's (1, k, m) slab of a
        [size, k, m] proc x local array — the wire-staging seam: what
        lands here is exactly what crosses DCN."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        k = self.local_size
        m = int(rows2d.shape[1])
        rows = [jax.device_put(
            jax.lax.slice_in_dim(rows2d, j, j + 1).reshape(1, 1, m),
            dev) for j, dev in enumerate(self.local_devices)]
        return jax.make_array_from_single_device_arrays(
            (self.size, k, m),
            NamedSharding(self.mesh2, P("proc", "local")), rows)

    def _quant_encode(self, flat, ef_key=None):  # graftlint: hot-path
        """Eagerly encode a packed flat [k*m] buffer for the wire:
        one row per local chip, quantized per row (absmax int8 /
        absmax-scaled e4m3) — through the error-feedback residual
        keyed by ``ef_key`` for the linear reduce ops, plain for data
        movement.  Returns (wire [k, m], scales [k, 1] f32); the ones
        fallback covers any scale-free ctx shape."""
        import jax.numpy as jnp
        rows = flat.reshape(self.local_size, -1)
        if ef_key is not None and self._ef is not None:
            wire, ctx = self._ef.compress(rows, bucket=ef_key)
        else:
            wire, ctx = self._quantizer.compress(rows)
        if isinstance(ctx, tuple):
            scales = ctx[0].astype(jnp.float32).reshape(
                self.local_size, 1)
        else:
            scales = jnp.ones((self.local_size, 1), jnp.float32)
        return wire, scales

    def _wire_residual2(self, key, slice_n: int):
        """Leg-2 residual carrier for the two-phase quantized
        allreduce: the [size, k, slice_n] f32 array the previous step's
        program emitted (donated back in this step), or zeros on first
        touch / geometry change."""
        import jax.numpy as jnp
        arr = self._res2.get(key)
        if arr is not None and arr.shape[2] == int(slice_n):
            self._res2.move_to_end(key)
            return arr
        return self._stage_hier_rows(
            jnp.zeros((self.local_size, int(slice_n)), jnp.float32))

    def _store_residual2(self, key, arr):
        self._res2[key] = arr
        self._res2.move_to_end(key)
        while len(self._res2) > self._res2_cap:
            self._res2.popitem(last=False)

    def _compiled(self, key, build, example_args=None, notify=None):
        """``notify`` is the per-dispatch cold-compile callback,
        threaded through the call chain from the engine's dispatch (it
        brackets AOT compiles so the execution watchdog never charges
        compile time to the watched window).  It is an explicit
        argument, NOT instance state: two executors dispatching through
        one mesh object must not cross their callbacks."""
        fn = self._fns.lookup(key)
        if fn is None:
            fn = build()
            import os
            if notify is not None:
                notify("begin")
            try:
                if example_args is not None:
                    # AOT lower+compile HERE (not lazily at the first
                    # call): compilation is local and can be long;
                    # doing it inside this helper lets the engine's
                    # watchdog distinguish compiling (healthy) from a
                    # wedged execution (member died after negotiation).
                    lowered = fn.lower(*example_args)
                    if os.environ.get("HVD_TPU_DUMP_HLO"):
                        self.hlo[key] = lowered.as_text()
                    fn = lowered.compile()
            finally:
                if notify is not None:
                    notify("end")
            self._fns.put(key, fn)
        return fn

    def _collective_jit(self, fn, n_args, out_spec, mesh=None,
                        in_spec=None):
        """shard_map + jit with every staged input donated."""
        import jax
        from jax.sharding import PartitionSpec as P
        # The static replication/vma checker cannot see through the
        # axis_index masking / per-process static slicing these
        # programs use; the negotiation contract guarantees consistent
        # collectives, so disable it.  jax.shard_map is always the
        # vma-era API here: xla_ops (imported above) installs a
        # translating shim on older jax.
        mapped = jax.shard_map(
            fn, mesh=mesh if mesh is not None else self.mesh,
            in_specs=(in_spec if in_spec is not None
                      else P("proc"),) * n_args,
            out_specs=out_spec, check_vma=False)
        return jax.jit(mapped, donate_argnums=tuple(range(n_args)))

    @staticmethod
    def _scaled(v, factor):
        return v if factor == 1.0 else v * np.asarray(factor, v.dtype)

    # -- collectives -------------------------------------------------------

    def _reduce_block(self, v, red_op, prescale, postscale, divisor):
        """Per-shard reduction body shared by allreduce flavors."""
        import jax
        import jax.numpy as jnp
        v = self._scaled(v, prescale)
        if red_op == ADASUM:
            r = adasum_combine(v, "proc", self.size)
        elif red_op in (SUM, AVERAGE):
            r = jax.lax.psum(v, "proc")
            if red_op == AVERAGE:
                r = (r / divisor).astype(v.dtype) if \
                    jnp.issubdtype(v.dtype, jnp.floating) \
                    else r // divisor
        elif red_op == MIN:
            r = jax.lax.pmin(v, "proc")
        elif red_op == MAX:
            r = jax.lax.pmax(v, "proc")
        elif red_op == PRODUCT:
            # Exact bytes-proportional product (reduce-scatter +
            # tiled all_gather, ~2x like Sum — not N x all_gather).
            r = product_allreduce(
                v.reshape(-1), "proc", self.size).reshape(v.shape)
        else:
            raise NotImplementedError(red_op)
        return self._scaled(r, postscale)

    def fused_allreduce(self, payloads: Sequence, lengths: Sequence[int],
                        dtype, red_op: str = SUM, prescale: float = 1.0,
                        postscale: float = 1.0, notify=None,
                        names: Optional[Sequence[str]] = None
                        ) -> List:  # graftlint: hot-path
        """One compiled program reducing a negotiated fusion group.

        ``payloads[i]`` is this process's flat contribution for entry i
        (jax.Array, numpy, or None for a joined rank's missing entry);
        ``lengths`` are the negotiated element counts.  The program
        takes one [size, n_i] input per entry and emits one psum per
        entry — XLA's all-reduce combiner packs them into a single
        fused collective (the compiler-managed fusion buffer).  Returns
        per-entry flat device arrays, replicated on the mesh device.
        """
        lengths = [int(n) for n in lengths]
        if red_op != SUM and any(p is None for p in payloads):
            # Zero fill is only the identity for Sum: a joined rank's
            # zeros clamp Min to <=0 and annihilate Product.  The
            # controller rewrites Average->Sum with a live-count divisor
            # and rejects the rest at negotiation; a direct caller that
            # reaches here with None + non-Sum must fail loudly, not
            # corrupt the reduction (reference join semantics).
            raise HorovodInternalError(
                "joined-rank (None) payload with op=%s: zero fill is "
                "only neutral for Sum" % red_op)
        if len(lengths) > 1 and red_op != ADASUM:
            # Adasum must stay per-entry: its dot-product combine over
            # a packed bucket would merge ACROSS tensors (wrong math),
            # so fused Adasum groups compile the direct multi-input
            # program with one combine per entry.
            return self._fused_allreduce_packed(
                payloads, lengths, dtype, red_op, prescale, postscale,
                notify)
        hier = codec_on = False
        if len(lengths) == 1 and red_op != ADASUM:
            hier, codec_on = self._route(
                "allreduce", lengths[0] * np.dtype(dtype).itemsize)
        def run_flat() -> List:
            key = ("fused_allreduce", tuple(lengths),
                   str(np.dtype(dtype)), red_op, float(prescale),
                   float(postscale))
            size = self.size

            def build():
                def fn(*xs):
                    return tuple(
                        self._reduce_block(x.reshape(-1), red_op,
                                           prescale, postscale, size)
                        for x in xs)
                from jax.sharding import PartitionSpec as P
                return self._collective_jit(fn, len(lengths), P())

            _count_path("allreduce",
                        sum(lengths) * np.dtype(dtype).itemsize, False)
            staged = [self._stage(p, (n,), dtype)
                      for p, n in zip(payloads, lengths)]
            outs = self._compiled(key, build, staged, notify)(*staged)
            return [self._replicated(o) for o in outs]

        if hier:
            # Multi-chip hierarchical path: every local chip moves 1/k
            # of the bytes cross-host instead of chip 0 moving all of
            # them.  Adasum is excluded — its combine is dot-product
            # based over the WHOLE vector, so per-chunk combines would
            # change the math (it stays on the one-device plane).
            codec = (self._wire_codec(dtype, red_op) if codec_on
                     else None)
            _count_path("allreduce",
                        lengths[0] * np.dtype(dtype).itemsize, True,
                        codec,
                        self._wire_nbytes(codec, lengths[0])
                        if codec else None)
            return self._guarded(
                "allreduce", lengths[0] * np.dtype(dtype).itemsize,
                lambda: [self._hier_allreduce(
                    payloads[0], lengths[0], dtype, red_op, prescale,
                    postscale, notify, codec,
                    names[0] if names else None)],
                run_flat, payloads=(payloads[0],), codec=codec)
        return run_flat()

    def _hier_allreduce(self, p, n: int, dtype, red_op, prescale,
                        postscale, notify=None, codec=None,
                        ef_name=None):  # graftlint: hot-path
        """Hierarchical allreduce over the proc x local mesh — the
        reference's ``HOROVOD_HIERARCHICAL_ALLREDUCE`` (NCCL
        reduce-scatter intra-node + cross-node allreduce + allgather,
        SURVEY §2.2) with one-contribution-per-HOST rank semantics:

        1. scatter (staging): the flat payload splits into k chunks,
           chunk j committed to local device j — the intra-host
           reduce-scatter degenerates to a split because each host has
           exactly ONE contribution;
        2. cross-host reduce: chunk j psums over the ``proc`` axis —
           k parallel collectives, each moving n/k bytes over that
           chip's own ICI/DCN links (the bandwidth win: all local
           chips' links drive traffic instead of chip 0's alone);
        3. ``all_gather`` over the ``local`` axis reassembles the full
           reduced vector on every local chip — intra-host ICI.

        Returns the reduced flat [n] device array (replica on this
        process's first local device, like the one-device plane).
        """
        import jax
        from jax.sharding import PartitionSpec as P

        import jax.numpy as jnp

        k = self.local_size
        chunk = -(-int(n) // k)
        padded = chunk * k
        np_dtype = np.dtype(dtype)
        size = self.size
        if codec is not None and codec.kind == "quant":
            # Two-phase compressed exchange (the 1-bit-Adam scheme):
            # leg 1 all_to_all's each chip's quantized chunk slices
            # and dequant-reduces MY slice in f32 (a compressed
            # reduce-scatter); leg 2 requantizes the reduced slice —
            # through a SECOND error-feedback residual for the linear
            # ops, carried across steps as a donated program
            # input/output — and all_gathers it back (a compressed
            # all-gather).  Per-chip DCN traffic is ~2*(p-1)/p wire
            # bytes at ANY world size: the uncompressed psum's
            # movement shape at 1/4 the byte width, never the O(p)
            # blow-up of all-gathering the full wire payload.
            chunk = -(-chunk // size) * size  # leg-1 slices split evenly
            padded = chunk * k
            slice_n = chunk // size
            linear = red_op in (SUM, AVERAGE)
            flat = self._pack_flat([(p, 0, int(n))], int(n), padded,
                                   np_dtype)
            # Residuals key by the tensor NAME when the caller has one
            # (each named gradient keeps its OWN delayed error — EF
            # theory wants per-tensor residuals); the packed fusion
            # bucket has no stable name and falls back to its size
            # class, the reference fusion-buffer granularity.
            ef_key = (("allreduce", padded, str(np_dtype), ef_name)
                      if linear else None)
            wireq, scales = self._quant_encode(flat, ef_key)
            qarr = self._stage_hier_rows(wireq)
            sarr = self._stage_hier_rows(scales)
            key = ("hier_allreduce", int(chunk), str(np_dtype), red_op,
                   float(prescale), float(postscale), k, codec.name)

            def _leg1(q, s):
                # Compressed reduce-scatter: exchange wire slices,
                # dequantize with per-sender scales, reduce in f32.
                y = q[0, 0].reshape(size, slice_n)
                w = jax.lax.all_to_all(y, "proc", split_axis=0,
                                       concat_axis=0)  # [size, slice_n]
                sg = jax.lax.all_gather(s[0, 0], "proc")   # [size, 1]
                deq = self._scaled(w.astype(jnp.float32) * sg, prescale)
                return self._scaled(
                    _axis0_reduce(deq, red_op, size), postscale)

            def _requant(rc):
                # ONE quantization definition for both legs: the same
                # jit-compatible quantizer that encoded leg 1 (1-D
                # input = one chunk), so the two legs can never drift
                # — and the fp8 path absmax-scales, never NaN-casting
                # a reduced value past e4m3's +-448 range.
                q2, ctx2 = self._quantizer.compress(rc)
                return q2, ctx2[0].astype(jnp.float32)

            def _leg2(q2, s2):
                # Compressed all-gather of the reduced slices, then
                # payload-dtype reassembly over in-host ICI.
                g = jax.lax.all_gather(q2, "proc")  # [size, slice_n]
                s2g = jax.lax.all_gather(s2.reshape(1), "proc")
                out = (g.astype(jnp.float32) * s2g).reshape(
                    chunk).astype(np_dtype)
                return jax.lax.all_gather(out, "local", tiled=True)

            if linear:
                def build():
                    def fn(q, s, res2):
                        rc = _leg1(q, s) + res2[0, 0]
                        q2, s2 = _requant(rc)
                        nres = rc - q2.astype(jnp.float32) * s2
                        return _leg2(q2, s2), nres[None, None]
                    return self._collective_jit(
                        fn, 3, (P(), P("proc", "local")),
                        mesh=self.mesh2, in_spec=P("proc", "local"))

                res2 = self._wire_residual2(ef_key, slice_n)
                out_g, nres = self._compiled(
                    key, build, (qarr, sarr, res2),
                    notify)(qarr, sarr, res2)
                self._store_residual2(ef_key, nres)
                out = self._replicated(out_g)
            else:
                def build():
                    def fn(q, s):
                        q2, s2 = _requant(_leg1(q, s))
                        return _leg2(q2, s2)
                    return self._collective_jit(
                        fn, 2, P(), mesh=self.mesh2,
                        in_spec=P("proc", "local"))

                out = self._replicated(
                    self._compiled(key, build, (qarr, sarr),
                                   notify)(qarr, sarr))
            return out[:int(n)] if padded > n else out
        # Cast codec (fp16/bf16): the staging pack casts to the wire
        # dtype, the cross-host reduce runs natively in it, and the
        # result returns to the payload dtype before the in-host
        # reassembly leg.
        stage_dtype = codec.wire if codec is not None else np_dtype
        garr = self._stage_hier([(p, 0, int(n))], int(n), chunk,
                                stage_dtype)

        key = ("hier_allreduce", int(chunk), str(np_dtype), red_op,
               float(prescale), float(postscale), k,
               codec.name if codec is not None else "none")

        def build():
            def fn(x):
                r = self._reduce_block(x[0, 0], red_op, prescale,
                                       postscale, self.size)
                if r.dtype != np_dtype:
                    r = r.astype(np_dtype)
                return jax.lax.all_gather(r, "local", tiled=True)
            return self._collective_jit(
                fn, 1, P(), mesh=self.mesh2, in_spec=P("proc", "local"))

        out = self._replicated(
            self._compiled(key, build, (garr,), notify)(garr))
        return out[:int(n)] if padded > n else out

    def _fused_allreduce_packed(self, payloads, lengths, dtype, red_op,
                                prescale, postscale,
                                notify=None):  # graftlint: hot-path
        """Multi-entry fusion via a bucket-padded flat buffer — the
        reference's fusion buffer (MemcpyInFusionBuffer / 64 MB
        persistent buffer, SURVEY §2.1 row 8) in XLA form.

        Group COMPOSITION depends on arrival timing: a DistributedOptimizer
        burst negotiates different (n_1..n_k) tuples cycle to cycle, and
        a compiled program per composition recompiles endlessly (measured
        16-60x slowdowns on async bursts).  Packing the entries into one
        size-class bucket keys the collective executable by bucket size
        alone; the pack/unpack copies are cheap eager device ops, exactly
        the memcpy in/out the reference pays."""
        np_dtype = np.dtype(dtype)
        total = int(sum(lengths))
        bucket = _size_class(total, np_dtype.itemsize)
        flat = self._pack_flat(
            [(p, 0, int(n)) for p, n in zip(payloads, lengths)],
            total, bucket, np_dtype)
        out = self.fused_allreduce([flat], [bucket], np_dtype, red_op,
                                   prescale, postscale, notify)[0]
        offs = np.concatenate([[0], np.cumsum(lengths)]).astype(int)  # graftlint: disable=host-bounce issue=ISSUE-1 -- offsets over negotiated lengths, never payload bytes
        return [out[offs[i]:offs[i] + lengths[i]]
                for i in range(len(lengths))]

    def allreduce(self, local_flat, red_op: str = SUM,
                  prescale: float = 1.0, postscale: float = 1.0):
        """Reduce one flat [n] contribution per process -> [n] device
        array (replicated on the mesh device)."""
        n = int(np.prod(np.shape(local_flat), dtype=np.int64))
        dtype = (local_flat.dtype if hasattr(local_flat, "dtype")
                 else np.asarray(local_flat).dtype)
        return self.fused_allreduce([local_flat], [n], dtype, red_op,
                                    prescale, postscale)[0]

    def broadcast(self, local, root_idx: int,
                  notify=None):  # graftlint: hot-path
        """Member ``root_idx``'s tensor to every process (masked psum:
        cheaper than an all-gather for size > 2, and explicit HLO).

        The program takes a power-of-two flat bucket, so a burst of
        varying shapes (``broadcast_parameters``: one op per layer)
        reuses one executable per size class instead of compiling per
        shape."""
        import jax
        import jax.numpy as jnp

        shape = tuple(np.shape(local))
        dtype = np.dtype(local.dtype if hasattr(local, "dtype")
                         else np.asarray(local).dtype)  # graftlint: disable=host-bounce issue=ISSUE-1 -- dtype probe; asarray branch reached only for host-typed inputs
        n = int(np.prod(shape, dtype=np.int64))
        # psum silently promotes bool to int32; ride the wire as uint8
        # and cast back so broadcast preserves every dtype.
        is_bool = dtype == np.bool_
        wire = np.dtype(np.uint8) if is_bool else dtype
        if is_bool:
            local = (local.astype(jnp.uint8) if _is_device_array(local)
                     else np.asarray(local).astype(np.uint8))  # graftlint: disable=host-bounce issue=ISSUE-1 -- bool wire-cast; np branch reached only for host-typed inputs
        bucket = _size_class(n, wire.itemsize)
        hier, codec_on = self._route("broadcast", n * wire.itemsize)
        codec = self._wire_codec(wire) if hier and codec_on else None

        def run_flat():
            key = ("broadcast", str(wire), int(bucket), int(root_idx))

            def build():
                def fn(x):
                    idx = jax.lax.axis_index("proc")
                    v = jnp.where(idx == root_idx, x[0],
                                  jnp.zeros_like(x[0]))
                    return jax.lax.psum(v, "proc")
                from jax.sharding import PartitionSpec as P
                return self._collective_jit(fn, 1, P())

            staged = self._stage_flat_padded([(local, 0, n)], n, bucket,
                                             wire)
            return self._replicated(
                self._compiled(key, build, (staged,), notify)(staged))

        if hier:
            _count_path("broadcast", n * wire.itemsize, True, codec,
                        self._wire_nbytes(codec, n) if codec else None)
            out = self._guarded(
                "broadcast", n * wire.itemsize,
                lambda: self._hier_broadcast(local, n, bucket, wire,
                                             root_idx, notify, codec),
                run_flat,
                payloads=((local,) if self.my_idx == root_idx else ()),
                codec=codec)
        else:
            _count_path("broadcast", n * wire.itemsize, False)
            out = run_flat()
        out = (out[:n].reshape(shape) if out.shape[0] > n
               else out.reshape(shape))
        return out.astype(jnp.bool_) if is_bool else out

    def _hier_broadcast(self, p, n: int, bucket: int, wire, root_idx,
                        notify=None, codec=None):  # graftlint: hot-path
        """Broadcast over the proc x local mesh: the root's payload
        scatters into k chunks across its local chips (staging), each
        chunk rides a masked cross-host psum over that chip's own
        ICI/DCN links (1/k of the bytes per chip), and a local
        ``all_gather`` reassembles the full tensor on every chip —
        the ``_hier_allreduce`` treatment for the one-sender case
        (``broadcast_parameters`` sweeps are burst of exactly these).
        Non-root members stage zeros (nothing of theirs is sent), and
        the in-program root mask stays as defense in depth."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        k = self.local_size
        chunk = -(-int(bucket) // k)
        segments = ([(p, 0, int(n))] if self.my_idx == root_idx else [])
        if codec is not None and codec.kind == "quant":
            # Data-movement op: plain quantize/dequantize (no error
            # feedback — nothing is reduced, the error never
            # compounds).  The root's quantized payload rides a masked
            # psum over the wire BYTES (bitcast to u8: non-roots
            # contribute exact zeros, so the byte sum IS the root's
            # wire — no quantized arithmetic, any 1-byte wire dtype,
            # and the uncompressed broadcast's ~2*(p-1)/p movement
            # shape at 1/4 the byte width — never an O(p) full-wire
            # all_gather).  The root's scale rides the same masked
            # psum.
            np_pay = np.dtype(wire)
            wire_jnp = codec.wire
            if self.my_idx == root_idx:
                flat = self._pack_flat(segments, int(n), chunk * k,
                                       np_pay)
                wireq, scales = self._quant_encode(flat)
            else:
                # Non-roots contribute nothing: stage zero wire rows
                # and unit scales directly instead of paying a full
                # quantization pass over a zero buffer the in-program
                # root mask discards anyway.
                with jax.default_device(self.device):
                    wireq = jnp.zeros((k, chunk), wire_jnp)
                    scales = jnp.ones((k, 1), jnp.float32)
            qarr = self._stage_hier_rows(wireq)
            sarr = self._stage_hier_rows(scales)
            key = ("hier_broadcast", str(wire), int(chunk),
                   int(root_idx), k, codec.name)

            def build():
                def fn(q, s):
                    idx = jax.lax.axis_index("proc")
                    qv = jnp.where(idx == root_idx, q[0, 0],
                                   jnp.zeros_like(q[0, 0]))
                    sv = jnp.where(idx == root_idx, s[0, 0],
                                   jnp.zeros_like(s[0, 0]))
                    qb = jax.lax.psum(jax.lax.bitcast_convert_type(
                        qv, jnp.uint8), "proc")
                    qr = jax.lax.bitcast_convert_type(qb, wire_jnp)
                    sr = jax.lax.psum(sv, "proc")      # [1] f32
                    deq = (qr.astype(jnp.float32) * sr).astype(np_pay)
                    return jax.lax.all_gather(deq, "local", tiled=True)
                return self._collective_jit(fn, 2, P(), mesh=self.mesh2,
                                            in_spec=P("proc", "local"))

            return self._replicated(
                self._compiled(key, build, (qarr, sarr),
                               notify)(qarr, sarr))
        stage_dtype = codec.wire if codec is not None else wire
        key = ("hier_broadcast", str(wire), int(chunk), int(root_idx), k,
               codec.name if codec is not None else "none")

        def build():
            def fn(x):
                idx = jax.lax.axis_index("proc")
                v = jnp.where(idx == root_idx, x[0, 0],
                              jnp.zeros_like(x[0, 0]))
                r = jax.lax.psum(v, "proc")
                if r.dtype != np.dtype(wire):
                    r = r.astype(wire)
                return jax.lax.all_gather(r, "local", tiled=True)
            return self._collective_jit(fn, 1, P(), mesh=self.mesh2,
                                        in_spec=P("proc", "local"))

        garr = self._stage_hier(
            segments, int(n) if segments else 0, chunk, stage_dtype)
        return self._replicated(
            self._compiled(key, build, (garr,), notify)(garr))

    def allgather(self, local, rows_per_member: Sequence[int],
                  notify=None):  # graftlint: hot-path
        """Concat dim-0-ragged per-process tensors (reference
        AllgatherOp): each member's contribution flattens into a
        power-of-two bucket, one ``lax.all_gather`` moves the buckets,
        and the valid segments are sliced back out eagerly.  The
        executable is keyed by (dtype, bucket) ALONE, so ragged bursts
        whose row counts vary call to call (variable-length batches,
        ``allgather_object``) reuse one program per size class —
        the ``_fused_allreduce_packed`` recompile-cliff treatment."""
        import jax
        import jax.numpy as jnp

        rows = [int(r) for r in rows_per_member]
        trailing = tuple(np.shape(local))[1:]
        telems = int(np.prod(trailing, dtype=np.int64)) if trailing else 1
        dtype = np.dtype(local.dtype if hasattr(local, "dtype")
                         else np.asarray(local).dtype)  # graftlint: disable=host-bounce issue=ISSUE-1 -- dtype probe; asarray branch reached only for host-typed inputs
        lens = [r * telems for r in rows]
        if not lens or max(lens) == 0:
            with jax.default_device(self.device):
                return jnp.zeros((0,) + trailing, dtype)
        bucket = _size_class(max(lens), dtype.itemsize)
        size = self.size
        my_len = lens[self.my_idx]
        hier, codec_on = self._route("allgather", bucket * dtype.itemsize)
        codec = self._wire_codec(dtype) if hier and codec_on else None

        def run_flat():
            key = ("allgather", str(dtype), int(bucket))

            def build():
                def fn(x):
                    return jax.lax.all_gather(x[0], "proc")  # [size, bucket]
                from jax.sharding import PartitionSpec as P
                return self._collective_jit(fn, 1, P())

            staged = self._stage_flat_padded([(local, 0, my_len)],
                                             my_len, bucket, dtype)
            return self._replicated(
                self._compiled(key, build, (staged,), notify)(staged))

        if hier:
            _count_path("allgather", my_len * dtype.itemsize, True,
                        codec,
                        self._wire_nbytes(codec, my_len)
                        if codec else None)
            # Both planes' outputs slice identically: flat g is
            # [size, bucket], hier g is [size, k*chunk >= bucket], and
            # the valid-segment slice below reads lens[m] <= bucket
            # rows either way — so a degraded fallback is transparent.
            g = self._guarded(
                "allgather", bucket * dtype.itemsize,
                lambda: self._hier_allgather(local, my_len, bucket,
                                             dtype, notify, codec),
                run_flat, payloads=(local,), codec=codec)
        else:
            _count_path("allgather", my_len * dtype.itemsize, False)
            g = run_flat()
        parts = [g[m, :lens[m]].reshape((rows[m],) + trailing)
                 for m in range(size) if rows[m]]
        return (jnp.concatenate(parts, axis=0) if len(parts) > 1
                else parts[0])

    def _hier_allgather(self, p, my_len: int, bucket: int, np_dtype,
                        notify=None, codec=None):  # graftlint: hot-path
        """Allgather over the proc x local mesh: each member's padded
        bucket splits into k chunks across its local chips; chunk j
        all_gathers over the ``proc`` axis from local device j (every
        chip moves (size-1)/k buckets cross-host instead of chip 0
        moving them all), and a local ``all_gather`` reassembles the
        member-major [size, bucket] result over intra-host ICI.
        Returns the gathered [size, k*ceil(bucket/k)] device array
        (k*chunk >= bucket; callers slice valid rows)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        k = self.local_size
        chunk = -(-int(bucket) // k)
        size = self.size
        if codec is not None and codec.kind == "quant":
            # Data-movement op: plain quantize/dequantize.  The
            # cross-host all_gather moves the WIRE payload (+ one f32
            # scale per chunk); each member's rows dequantize with its
            # own scale before the in-host reassembly leg.
            np_d = np.dtype(np_dtype)
            flat = self._pack_flat([(p, 0, int(my_len))], int(my_len),
                                   chunk * k, np_d)
            wireq, scales = self._quant_encode(flat)
            qarr = self._stage_hier_rows(wireq)
            sarr = self._stage_hier_rows(scales)
            key = ("hier_allgather", str(np_dtype), int(chunk), k,
                   codec.name)

            def build():
                def fn(q, s):
                    g = jax.lax.all_gather(q[0, 0], "proc")   # [p,chunk]
                    sg = jax.lax.all_gather(s[0, 0], "proc")  # [p,1]
                    deq = (g.astype(jnp.float32) * sg).astype(np_d)
                    gg = jax.lax.all_gather(deq, "local")  # [k,p,chunk]
                    return jnp.swapaxes(gg, 0, 1).reshape(
                        size, k * chunk)
                return self._collective_jit(fn, 2, P(), mesh=self.mesh2,
                                            in_spec=P("proc", "local"))

            return self._replicated(
                self._compiled(key, build, (qarr, sarr),
                               notify)(qarr, sarr))
        stage_dtype = codec.wire if codec is not None else np_dtype
        key = ("hier_allgather", str(np_dtype), int(chunk), k,
               codec.name if codec is not None else "none")

        def build():
            def fn(x):
                g = jax.lax.all_gather(x[0, 0], "proc")  # [size, chunk]
                if g.dtype != np.dtype(np_dtype):
                    g = g.astype(np_dtype)
                gg = jax.lax.all_gather(g, "local")      # [k, size, chunk]
                return jnp.swapaxes(gg, 0, 1).reshape(size, k * chunk)
            return self._collective_jit(fn, 1, P(), mesh=self.mesh2,
                                        in_spec=P("proc", "local"))

        garr = self._stage_hier([(p, 0, int(my_len))], int(my_len),
                                chunk, stage_dtype)
        return self._replicated(
            self._compiled(key, build, (garr,), notify)(garr))

    def alltoall(self, local, splits_matrix: np.ndarray,
                 notify=None):  # graftlint: hot-path
        """Member-major splits matrix routing (reference AlltoallOp) as
        real ``lax.all_to_all`` HLO: each send segment is padded to the
        matrix max so every exchange block is uniform, one all-to-all
        moves them, and the receiver slices its valid rows back out.
        Returns (my_received_rows, recv_splits).
        """
        import jax
        import jax.numpy as jnp

        sm = np.asarray(splits_matrix).reshape(self.size, self.size)  # graftlint: disable=host-bounce issue=ISSUE-1 -- negotiated splits matrix (control metadata), never payload bytes
        trailing = tuple(np.shape(local))[1:]
        telems = int(np.prod(trailing, dtype=np.int64)) if trailing else 1
        dtype = np.dtype(local.dtype if hasattr(local, "dtype")
                         else np.asarray(local).dtype)  # graftlint: disable=host-bounce issue=ISSUE-1 -- dtype probe; asarray branch reached only for host-typed inputs
        size = self.size
        c = int(sm.max()) if sm.size else 0
        recv_splits = [int(sm[j, self.my_idx]) for j in range(size)]
        if c == 0:
            with jax.default_device(self.device):
                return jnp.zeros((0,) + trailing, dtype), recv_splits
        # Every exchange block pads to one power-of-two bucket derived
        # from the NEGOTIATED matrix max (identical on all members), so
        # the executable is keyed by (dtype, block) alone — varying
        # splits matrices (MoE routing shifts every step) reuse one
        # program per size class instead of compiling per matrix.
        block = _size_class(c * telems, dtype.itemsize)
        my_idx = self.my_idx
        offs = np.concatenate([[0], np.cumsum(sm[my_idx])]).astype(int)  # graftlint: disable=host-bounce issue=ISSUE-1 -- offsets over the negotiated splits row, never payload bytes

        hier, codec_on = self._route("alltoall",
                                     size * block * dtype.itemsize)
        codec = self._wire_codec(dtype) if hier and codec_on else None

        def run_flat():
            key = ("alltoall", str(dtype), int(block))

            def build():
                def fn(x):
                    y = x[0].reshape(size, block)
                    w = jax.lax.all_to_all(y, "proc", split_axis=0,
                                           concat_axis=0)  # [size, block]
                    return w.reshape(1, size * block)
                from jax.sharding import PartitionSpec as P
                return self._collective_jit(fn, 1, P("proc"))

            # Segment layout: dest j's rows (slice from my payload),
            # padded to the uniform block.
            segments = []
            for j in range(size):
                seg_elems = int(sm[my_idx, j]) * telems
                segments.append((local, int(offs[j]) * telems,
                                 seg_elems))
                if seg_elems < block:
                    segments.append((None, 0, block - seg_elems))
            staged = self._stage_flat_padded(segments, size * block,
                                             size * block, dtype)
            return self._my_row(
                self._compiled(key, build, (staged,), notify)(staged)), block

        if hier:
            _count_path("alltoall",
                        int(offs[-1]) * telems * dtype.itemsize, True,
                        codec,
                        self._wire_nbytes(codec, int(offs[-1]) * telems)
                        if codec else None)
            # stride differs per plane (flat = block, hier = k*ceil),
            # so each closure returns its own (row, stride) pair and
            # the valid-rows slice below works either way.
            w, stride = self._guarded(
                "alltoall", size * block * dtype.itemsize,
                lambda: self._hier_alltoall(local, sm, offs, telems,
                                            block, dtype, notify,
                                            codec),
                run_flat, payloads=(local,), codec=codec)
        else:
            _count_path("alltoall",
                        int(offs[-1]) * telems * dtype.itemsize, False)
            w, stride = run_flat()
        parts = [w[j * stride:j * stride + recv_splits[j] * telems]
                 .reshape((recv_splits[j],) + trailing)
                 for j in range(size) if recv_splits[j]]
        if not parts:
            with jax.default_device(self.device):
                return jnp.zeros((0,) + trailing, dtype), recv_splits
        out = (jnp.concatenate(parts, axis=0) if len(parts) > 1
               else parts[0])
        return out, recv_splits

    def _hier_alltoall(self, p, sm, offs, telems: int, block: int,
                       np_dtype, notify=None,
                       codec=None):  # graftlint: hot-path
        """Alltoall over the proc x local mesh: every destination block
        splits into k chunks across the local chips; local device j
        runs the cross-host ``all_to_all`` for chunk j of every block
        (each chip exchanges 1/k of the bytes over its own links), and
        a local ``all_gather`` reassembles the received blocks.
        Returns (my received flat [size * k*ceil(block/k)] row, the
        per-source stride k*ceil(block/k))."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        k = self.local_size
        bc = -(-int(block) // k)    # block chunk per local chip
        blockk = bc * k
        size = self.size
        my_idx = self.my_idx
        segments = _chunked_segments(
            p, size, [int(offs[m]) * telems for m in range(size)],
            [int(sm[my_idx, m]) * telems for m in range(size)], bc, k)
        if codec is not None and codec.kind == "quant":
            # Data-movement op: plain quantize/dequantize.  The
            # cross-host all_to_all exchanges the WIRE payload; each
            # received row m dequantizes with sender m's this-chunk
            # scale (one scalar all_gather rides along) before the
            # in-host reassembly leg.
            np_d = np.dtype(np_dtype)
            flat = self._pack_flat(segments, size * blockk,
                                   size * bc * k, np_d)
            wireq, scales = self._quant_encode(flat)
            qarr = self._stage_hier_rows(wireq)
            sarr = self._stage_hier_rows(scales)
            key = ("hier_alltoall", str(np_dtype), int(bc), k,
                   codec.name)

            def build():
                def fn(q, s):
                    y = q[0, 0].reshape(size, bc)
                    w = jax.lax.all_to_all(y, "proc", split_axis=0,
                                           concat_axis=0)  # [size, bc]
                    sg = jax.lax.all_gather(s[0, 0], "proc")  # [size,1]
                    deq = (w.astype(jnp.float32) * sg).astype(np_d)
                    ww = jax.lax.all_gather(deq, "local")  # [k,size,bc]
                    return jnp.swapaxes(ww, 0, 1).reshape(
                        1, size * blockk)
                return self._collective_jit(fn, 2, P("proc"),
                                            mesh=self.mesh2,
                                            in_spec=P("proc", "local"))

            w = self._my_row(
                self._compiled(key, build, (qarr, sarr),
                               notify)(qarr, sarr))
            return w, blockk
        stage_dtype = codec.wire if codec is not None else np_dtype
        key = ("hier_alltoall", str(np_dtype), int(bc), k,
               codec.name if codec is not None else "none")

        def build():
            def fn(x):
                y = x[0, 0].reshape(size, bc)
                w = jax.lax.all_to_all(y, "proc", split_axis=0,
                                       concat_axis=0)   # [size, bc]
                if w.dtype != np.dtype(np_dtype):
                    w = w.astype(np_dtype)
                ww = jax.lax.all_gather(w, "local")     # [k, size, bc]
                return jnp.swapaxes(ww, 0, 1).reshape(
                    1, size * blockk)
            return self._collective_jit(fn, 1, P("proc"),
                                        mesh=self.mesh2,
                                        in_spec=P("proc", "local"))

        garr = self._stage_hier(segments, size * blockk, size * bc,
                                stage_dtype)
        w = self._my_row(
            self._compiled(key, build, (garr,), notify)(garr))
        return w, blockk

    def reducescatter(self, local, red_op: str = SUM, notify=None,
                      name=None):  # graftlint: hot-path
        """Reduce then scatter dim-0 shards as real ``psum_scatter``
        HLO (uneven chunks follow the reference's earlier-ranks-larger
        split: each chunk is padded to the largest inside the program,
        scattered tiled, and sliced back out)."""
        import jax
        import jax.numpy as jnp

        shape = tuple(np.shape(local))
        dtype = np.dtype(local.dtype if hasattr(local, "dtype")
                         else np.asarray(local).dtype)  # graftlint: disable=host-bounce issue=ISSUE-1 -- dtype probe; asarray branch reached only for host-typed inputs
        d0 = shape[0]
        trailing = shape[1:]
        telems = int(np.prod(trailing, dtype=np.int64)) if trailing else 1
        size = self.size
        rows, offs = _uneven_chunks(d0, size)
        c = rows[0] if rows else 0  # largest chunk (earlier ranks larger)
        # Member-major packed buffer: member m's chunk flattens and
        # pads to one power-of-two segment, so the executable is keyed
        # by (dtype, segment, op) — shape-varying bursts reuse one
        # program per size class (the packed-fusion-bucket treatment).
        seg = _size_class(max(c * telems, 1), dtype.itemsize)
        my_idx = self.my_idx
        hier = codec_on = False
        if red_op in (SUM, AVERAGE, MIN, MAX, PRODUCT):
            hier, codec_on = self._route("reducescatter",
                                         size * seg * dtype.itemsize)
        codec = (self._wire_codec(dtype, red_op) if hier and codec_on
                 else None)
        my_n = rows[my_idx] * telems

        def run_flat():
            key = ("reducescatter", str(dtype), int(seg), red_op)

            def build():
                def fn(x):
                    y = x[0]  # [size*seg]
                    if red_op in (SUM, AVERAGE):
                        w = jax.lax.psum_scatter(
                            y, "proc", scatter_dimension=0, tiled=True)
                        if red_op == AVERAGE:
                            # Divides by the full member count (core
                            # reducescatter semantics; join cannot reach
                            # this op).
                            w = (w / size).astype(w.dtype) if \
                                jnp.issubdtype(w.dtype, jnp.floating) \
                                else w // size
                    elif red_op in (MIN, MAX, PRODUCT):
                        # One all_to_all + local reduce: 1x payload bytes
                        # (the full-reduce-then-slice fallback moved N x).
                        w = alltoall_chunk_reduce(y, "proc", size, red_op)
                    else:
                        r = self._reduce_block(y, red_op, 1.0, 1.0, size)
                        w = jax.lax.slice_in_dim(
                            r, my_idx * seg, (my_idx + 1) * seg)
                    return w[None]  # [1, seg]
                from jax.sharding import PartitionSpec as P
                return self._collective_jit(fn, 1, P("proc"))

            segments = []
            for m in range(size):
                n_m = rows[m] * telems
                segments.append((local, int(offs[m]) * telems, n_m))
                if n_m < seg:
                    segments.append((None, 0, seg - n_m))
            staged = self._stage_flat_padded(segments, size * seg,
                                             size * seg, dtype)
            out = self._my_row(
                self._compiled(key, build, (staged,), notify)(staged))
            return out[:my_n].reshape((rows[my_idx],) + trailing)

        if hier:
            # Adasum (and any other whole-vector combine) stays on the
            # one-device plane: per-chunk combines would change the
            # math — the ``_hier_allreduce`` exclusion.
            _count_path("reducescatter", d0 * telems * dtype.itemsize,
                        True, codec,
                        self._wire_nbytes(codec, d0 * telems)
                        if codec else None)

            def run_hier():
                out = self._hier_reducescatter(local, rows, offs,
                                               telems, seg, dtype,
                                               red_op, notify, codec,
                                               name)
                return out[:my_n].reshape((rows[my_idx],) + trailing)

            return self._guarded("reducescatter",
                                 size * seg * dtype.itemsize, run_hier,
                                 run_flat, payloads=(local,),
                                 codec=codec)
        _count_path("reducescatter", d0 * telems * dtype.itemsize,
                    False)
        return run_flat()

    def _hier_reducescatter(self, p, rows, offs, telems: int, seg: int,
                            np_dtype, red_op, notify=None, codec=None,
                            ef_name=None):  # graftlint: hot-path
        """Reducescatter over the proc x local mesh: every member
        segment splits into k chunks across the local chips; local
        device j reduces+scatters chunk j of every segment over the
        ``proc`` axis (``psum_scatter`` for Sum/Average, the
        bytes-proportional ``alltoall_chunk_reduce`` for
        Min/Max/Product — each chip moving 1/k of the bytes), and a
        local ``all_gather`` reassembles this member's full reduced
        segment.  Returns the flat padded [k*ceil(seg/k)] row."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        k = self.local_size
        sc = -(-int(seg) // k)      # segment chunk per local chip
        size = self.size
        segments = _chunked_segments(
            p, size, [int(offs[m]) * telems for m in range(size)],
            [int(rows[m]) * telems for m in range(size)], sc, k)
        if codec is not None and codec.kind == "quant":
            # Only the cross-host REDUCE leg is compressed: the
            # quantized member segments exchange via all_to_all (the
            # reduce-scatter's wire movement), dequantize to f32 with
            # per-sender scales, and reduce locally; the in-host
            # reassembly all_gather stays in the payload dtype.
            # Error feedback for the linear ops, plain otherwise.
            np_d = np.dtype(np_dtype)
            flat = self._pack_flat(segments, size * sc * k,
                                   size * sc * k, np_d)
            ef_key = (("reducescatter", size * sc * k, str(np_d),
                       ef_name)
                      if red_op in (SUM, AVERAGE) else None)
            wireq, scales = self._quant_encode(flat, ef_key)
            qarr = self._stage_hier_rows(wireq)
            sarr = self._stage_hier_rows(scales)
            key = ("hier_reducescatter", str(np_dtype), int(sc), red_op,
                   k, codec.name)

            def build():
                def fn(q, s):
                    y = q[0, 0].reshape(size, sc)
                    w = jax.lax.all_to_all(y, "proc", split_axis=0,
                                           concat_axis=0)  # [size, sc]
                    sg = jax.lax.all_gather(s[0, 0], "proc")  # [size,1]
                    deq = w.astype(jnp.float32) * sg
                    r = _axis0_reduce(deq, red_op, size).astype(np_d)
                    return jax.lax.all_gather(
                        r, "local", tiled=True)[None]
                return self._collective_jit(fn, 2, P("proc"),
                                            mesh=self.mesh2,
                                            in_spec=P("proc", "local"))

            return self._my_row(
                self._compiled(key, build, (qarr, sarr),
                               notify)(qarr, sarr))
        stage_dtype = codec.wire if codec is not None else np_dtype
        key = ("hier_reducescatter", str(np_dtype), int(sc), red_op, k,
               codec.name if codec is not None else "none")

        def build():
            def fn(x):
                y = x[0, 0]          # [size * sc]
                if red_op in (SUM, AVERAGE):
                    w = jax.lax.psum_scatter(
                        y, "proc", scatter_dimension=0, tiled=True)
                    if red_op == AVERAGE:
                        w = (w / size).astype(w.dtype) if \
                            jnp.issubdtype(w.dtype, jnp.floating) \
                            else w // size
                else:
                    w = alltoall_chunk_reduce(y, "proc", size, red_op)
                if w.dtype != np.dtype(np_dtype):
                    w = w.astype(np_dtype)
                return jax.lax.all_gather(w, "local", tiled=True)[None]
            return self._collective_jit(fn, 1, P("proc"),
                                        mesh=self.mesh2,
                                        in_spec=P("proc", "local"))

        garr = self._stage_hier(segments, size * sc * k, size * sc,
                                stage_dtype)
        return self._my_row(
            self._compiled(key, build, (garr,), notify)(garr))


class MultihostEngine:
    """Single executor thread draining the core's negotiated groups.

    Enqueue side: ops are registered with the control plane
    (``TcpCore.enqueue_external``) and the local payload parked here.
    Executor side: for each negotiated group (one fused Response), run
    the XLA collective over the global mesh in negotiation order, then
    complete both the Python handles and the core entries.
    """

    def __init__(self, core, config: Config, timeline: Timeline,
                 process_set_resolver):
        self.core = core
        self.config = config
        self.timeline = timeline
        self._resolve_process_set = process_set_resolver
        # Process-set mesh memo: reached from the caller plane
        # (enqueue_alltoall sizing) and the executor thread.
        self._collectives: Dict[int, GlobalMeshCollectives] = {}  # graftlint: guarded-by=_lock
        self._lock = threading.Lock()
        # core handle -> (py handle, local payload ndarray, orig shape)
        self._pending: Dict[int, tuple] = {}  # graftlint: guarded-by=_lock
        # Monotonic False->True poison flag, read racily by the drain /
        # watchdog loops as their while-predicate (GIL-atomic; a late
        # read costs one extra bounded wait, never a hang).
        self._shutdown = False  # graftlint: owned-by=any
        # Two-stage pipeline (the reference's background loop negotiates
        # cycle N+1 while N's NCCL kernels run async, SURVEY §3.2): the
        # drain thread only stages + dispatches compiled programs (XLA
        # dispatch is async), the completion thread performs the
        # blocking device_get / handle resolution.  Bounded so a slow
        # host fetch backpressures dispatch instead of piling device
        # programs without limit.
        # Pipeline depth: device programs dispatched but not yet
        # complete.  The drain thread parks one representative output
        # per group and blocks on the OLDEST once the window fills —
        # bounding live staging/output buffers (the reference's finite
        # NCCL stream queue) while keeping up to `depth` collectives
        # overlapped on device.  Only the drain thread touches it.
        self._depth = max(1, int(getattr(config, "max_inflight_groups",
                                         4)))
        self._inflight_outs: List = []  # graftlint: owned-by=hvd-tpu-multihost-exec
        self._done_q: "queue_mod.Queue" = queue_mod.Queue(
            maxsize=self._depth)
        # Groups routed through the completion thread and not yet
        # finished (guarded by _lock): the drain thread completes a
        # device-only group inline ONLY when this is zero, so handle
        # resolution order always follows negotiation order.
        self._host_inflight = 0  # graftlint: guarded-by=_lock
        # Execution-phase watchdog (the device-plane analog of the
        # stall inspector): dispatched groups register here; a group
        # that outlives stall_warning_secs logs a warning, and — when
        # device_exec_timeout_secs > 0 — one that outlives the timeout
        # fails every outstanding handle with a diagnostic naming the
        # group, then poisons the engine (a member that died after
        # negotiation leaves the runtime wedged; callers must not hang
        # with it).
        # Monotonic collective-group id (mirrors the in-process
        # engine's): tags each negotiated group's timeline EXEC span
        # and the engine_last_group_id gauge for trace<->metrics
        # correlation.
        self._group_seq = 0  # graftlint: owned-by=hvd-tpu-multihost-exec
        # -- steady-state fast path (frozen negotiated schedules) ----------
        # Caller threads stage payloads against the frozen schedule and
        # hand full buckets to the drain thread via _fp_q, so every
        # dispatch still flows through _execute (one schedule entry, one
        # watchdog/deadline registration path).  _fp_lock is the
        # freezer's stage lock and is ALWAYS taken before self._lock
        # (the thaw flush re-enqueues through the core under both).
        self._fp_lock = threading.RLock()
        # Staged-but-undispatched payloads of the CURRENT bucket only:
        # (py handle, ndarray, name) in frozen slot order.  A thaw
        # flush renegotiates exactly these — already-dispatched buckets
        # are in flight and complete through _finish.
        self._fp_pending: List[tuple] = []  # graftlint: guarded-by=_fp_lock
        self._fp_idx = 0  # graftlint: guarded-by=_fp_lock
        self._fp_t = 0.0  # graftlint: guarded-by=_fp_lock
        # Synthetic frozen-bucket groups, drained by the exec thread
        # ahead of negotiated records (queue is thread-safe; unbounded
        # is fine — depth is capped by the frozen schedule's bucket
        # count times the caller's own blocking cadence).
        self._fp_q: "queue_mod.Queue" = queue_mod.Queue()
        self._fp = fastpath.ScheduleFreezer(
            warm_cycles=config.fast_path_warm_cycles,
            enabled=getattr(config, "fast_path", True), spmd=True,
            plane_name="multihost", on_thaw=self._fp_flush,
            stage_lock=self._fp_lock)
        fastpath.register(self._fp)
        rounds = getattr(core, "fastpath_idle_rounds", None)
        if rounds is not None:
            fastpath.set_core_rounds_provider(rounds)
        self._m_fp_frozen = metrics.counter("fastpath_frozen_cycles_total")
        self._m_fp_bucket = metrics.histogram(
            "engine_overlap_bucket_seconds")
        # Fixed unlabeled series resolved once (hot-path discipline);
        # the exec-cache gauges additionally refresh at most 1/s —
        # they only change on a compile, and _finish runs per group.
        self._m_cycles = metrics.counter("engine_cycles_total")
        self._m_queue_depth = metrics.gauge("engine_queue_depth")
        self._m_bytes_submitted = metrics.counter(
            "engine_bytes_submitted_total")
        self._m_bytes_fused = metrics.counter("engine_bytes_fused_total")
        self._m_tensors_fused = metrics.counter(
            "engine_tensors_fused_total")
        self._m_cache_hits = metrics.gauge("exec_cache_hits")
        self._m_cache_misses = metrics.gauge("exec_cache_misses")
        self._m_last_group = metrics.gauge("engine_last_group_id")
        # Read/written racily from the drain AND completion threads as
        # a refresh throttle; a lost update costs one extra gauge
        # refresh, never a wrong value.
        self._cache_gauge_t = 0.0  # graftlint: owned-by=any
        self._watch_lock = threading.Lock()
        self._watched: Dict[int, dict] = {}  # graftlint: guarded-by=_watch_lock
        self._killed_wids: set = set()  # graftlint: guarded-by=_watch_lock
        self._watch_seq = 0  # graftlint: guarded-by=_watch_lock
        self._last_progress = time.monotonic()  # graftlint: guarded-by=_watch_lock
        # Set under _lock so the poison is atomic with the pending-map
        # sweep; read racily as a fast-path check (reads unchecked).
        self._failed: Optional[Exception] = None  # graftlint: guarded-by=_lock
        # HOROVOD_STALL_CHECK_DISABLE silences the warning path here
        # exactly like the negotiation-phase inspector; the explicit
        # timeout knob remains a separate opt-in.
        self._exec_warn = (0.0 if getattr(config, "stall_check_disable",
                                          False)
                           else max(float(config.stall_warning_secs),
                                    0.0))
        self._exec_timeout = max(float(getattr(
            config, "device_exec_timeout_secs", 0.0)), 0.0)
        # Per-collective deadlines ride the same watchdog thread: when
        # the deadline plane is on, the thread must run even with the
        # warning/timeout knobs off.
        self._deadline_enabled = resilience.collective_timeout_secs() > 0
        if (self._exec_warn > 0 or self._exec_timeout > 0
                or self._deadline_enabled):
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="hvd-tpu-multihost-watchdog", daemon=True)
            self._watchdog.start()
        self._done_thread = threading.Thread(
            target=self._completion_loop,
            name="hvd-tpu-multihost-done", daemon=True)
        self._done_thread.start()
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-multihost-exec", daemon=True)
        self._thread.start()

    # -- process-set meshes ------------------------------------------------

    def collectives_for(self, process_set_id: int) -> GlobalMeshCollectives:
        # Reached from the caller plane (enqueue_alltoall sizing) AND
        # the executor thread (_execute): memoize under the lock so two
        # racing first-touches can't build two global meshes (and two
        # compiled-program caches) for one set.
        with self._lock:
            mc = self._collectives.get(process_set_id)
            if mc is None:
                ranks = self._resolve_process_set(process_set_id)
                mc = GlobalMeshCollectives(
                    ranks, name="ps%d" % process_set_id)
                self._collectives[process_set_id] = mc
            return mc

    def invalidate_process_set(self, process_set_id: int):
        # Membership changed: a frozen schedule negotiated against the
        # old mesh must never dispatch again (loud thaw, before _lock —
        # the flush path takes _fp_lock then _lock).
        self._fp.thaw("membership",
                      detail="process set %d invalidated" % process_set_id)
        with self._lock:
            self._collectives.pop(process_set_id, None)

    # -- enqueue API (per-rank tensor semantics) ---------------------------

    @staticmethod
    def _payload(tensor):
        """Keep device arrays device-resident; host data becomes one
        contiguous numpy array (crossing the host boundary is then the
        caller's choice, never this engine's)."""
        if _is_device_array(tensor):
            return tensor
        return np.ascontiguousarray(np.asarray(tensor))

    def _enqueue(self, name, op_type, arr, **kw) -> CollectiveHandle:
        fp = self._fp_stage(name, op_type, arr, kw)
        if fp is not None:
            return fp
        py = CollectiveHandle(name)
        # Enqueue and park ATOMICALLY w.r.t. the executor's _take: the
        # instant enqueue_external returns, the background thread can
        # negotiate the op and the executor can pop its record — if the
        # payload weren't parked yet, this rank would contribute zeros
        # and the handle would never resolve.  The _failed check lives
        # under the same lock the watchdog uses for its pending sweep,
        # so a handle either raises here or is guaranteed to be swept.
        with self._lock:
            if self._failed is not None:
                raise HorovodInternalError(
                    "multihost engine disabled after watchdog "
                    "failure: %s" % self._failed)
            faultline.site("mh.enqueue.pre_register")
            ch = self.core.enqueue_external(
                name, op_type, tuple(arr.shape), np.dtype(arr.dtype),
                **kw)
            self._pending[ch._h] = (py, arr)
            self._m_bytes_submitted.inc(int(arr.nbytes))
            self._m_queue_depth.set(len(self._pending))
        return py

    def enqueue_allreduce(self, name, tensor, red_op=SUM, prescale=1.0,
                          postscale=1.0, process_set_id=0
                          ) -> CollectiveHandle:
        return self._enqueue(
            name, "allreduce", self._payload(tensor), red_op=red_op,
            process_set_id=process_set_id, prescale=prescale,
            postscale=postscale)

    def enqueue_allgather(self, name, tensor, process_set_id=0
                          ) -> CollectiveHandle:
        return self._enqueue(name, "allgather", self._payload(tensor),
                             process_set_id=process_set_id)

    def enqueue_broadcast(self, name, tensor, root_rank=0,
                          process_set_id=0) -> CollectiveHandle:
        return self._enqueue(name, "broadcast", self._payload(tensor),
                             root_rank=root_rank,
                             process_set_id=process_set_id)

    def enqueue_alltoall(self, name, tensor, splits=None,
                         process_set_id=0) -> CollectiveHandle:
        arr = self._payload(tensor)
        if splits is None:
            n = self.collectives_for(process_set_id).size
            if arr.shape[0] % n:
                raise ValueError(
                    "uniform alltoall needs dim0 %% set size (%d) == 0"
                    % n)
            splits = [arr.shape[0] // n] * n
        return self._enqueue(name, "alltoall", arr, splits=list(splits),
                             process_set_id=process_set_id)

    def enqueue_reducescatter(self, name, tensor, red_op=SUM,
                              process_set_id=0) -> CollectiveHandle:
        return self._enqueue(name, "reducescatter", self._payload(tensor),
                             red_op=red_op,
                             process_set_id=process_set_id)

    # -- steady-state fast path (frozen negotiated schedules) --------------

    @staticmethod
    def _fp_slot_sig(op_type, arr, kw) -> tuple:
        """Positional slot identity on the enqueue side.  Names carry
        step suffixes in real training loops, so frozen slots match on
        what negotiation actually keys on — op, set, dtype, reduction
        parameters and flat size at position i (the upstream
        ``response_cache.cc`` keys on shape/type for the same reason)."""
        return (op_type, int(kw.get("process_set_id", 0)),
                np.dtype(arr.dtype).name, kw.get("red_op"),
                float(kw.get("prescale", 1.0)),
                float(kw.get("postscale", 1.0)), int(arr.size))

    def _fp_profile(self, g: dict):
        """One negotiated record's schedule profile, or None when the
        record is not freezable (non-allreduce, error record, or a
        zero-filled joined entry — membership is mid-change)."""
        if (g["op_type"] != "allreduce" or g.get("error")
                or any(e["handle"] < 0 for e in g["entries"])):
            return None
        dtype = np.dtype(g["dtype"]).name
        return tuple(
            ("allreduce", int(g["process_set_id"]), dtype, g["red_op"],
             float(g["prescale"]), float(g["postscale"]), int(n))
            for n in g["aux_sizes"])

    def _fp_payload(self, g: dict, prof) -> dict:
        lengths = [int(n) for n in g["aux_sizes"]]
        item = np.dtype(g["dtype"]).itemsize
        return {
            "sig": fastpath.schedule_sig(prof),
            "slots": [tuple(s) for s in prof],
            "lengths": lengths,
            "ends": fastpath.bucket_ends(
                [n * item for n in lengths],
                getattr(self.config, "overlap_buckets", 4),
                self.config.fusion_threshold_bytes),
            "process_set_id": int(g["process_set_id"]),
            "dtype": g["dtype"],
            "red_op": g["red_op"],
            "prescale": g["prescale"],
            "postscale": g["postscale"],
        }

    def _fp_cycle(self, g: dict):
        """Per-negotiated-record fast-path bookkeeping (exec thread,
        BEFORE the record executes).  A record arriving while frozen
        means some member kept negotiating — membership/world change;
        otherwise feed the warm streak and, when it trips, propose the
        freeze.  The flip happens before record K executes so a caller
        unblocked by K's handles stages K+1 against the frozen schedule
        on EVERY rank — rank 0's eligibility gate (every parked payload
        belongs to this record, i.e. no async caller is straddling the
        freeze point) is checked at the same record index on all
        members because records are coordinator-broadcast."""
        if self._fp.frozen() is not None:
            self._fp.thaw(
                "membership",
                detail="negotiated %s record arrived while frozen"
                % g["op_type"])
            return
        prof = self._fp_profile(g)
        if not self._fp.observe(prof):
            return
        with self._lock:
            quiesced = (self._failed is None
                        and len(self._pending) == len(g["entries"]))
        if self._fp.freeze(self._fp_payload(g, prof),
                           self._group_seq + 1, ok=quiesced):
            self._fp_core_set(True)

    def _fp_stage(self, name, op_type, arr, kw):
        """Caller-thread staging against the frozen schedule.  Returns
        a handle when the payload was staged (negotiation skipped), or
        None to fall through to full negotiation — including right
        after a loud shape thaw, whose flush has already renegotiated
        the staged prefix in program order."""
        if self._fp.frozen() is None:
            return None
        with self._fp_lock:
            fs = self._fp.frozen()
            if fs is None:
                return None
            i = self._fp_idx
            sig = self._fp_slot_sig(op_type, arr, kw)
            if i >= len(fs["slots"]) or tuple(fs["slots"][i]) != sig:
                self._fp.thaw(
                    "shape",
                    detail="staged %s %r does not match frozen slot %d"
                    % (op_type, name, i))
                return None
            py = CollectiveHandle(name)
            self._fp_pending.append((py, arr, name))
            self._fp_t = time.monotonic()
            self._fp_idx = i + 1
            self._m_bytes_submitted.inc(int(arr.nbytes))
            if self._fp_idx in fs["ends"]:
                if fastpath.stale_dispatch_seam():
                    # Injected stale frozen dispatch: thaw loudly and
                    # push the staged bucket back through full
                    # negotiation (the flush) — values stay correct,
                    # nothing hangs.
                    self._fp.thaw(
                        "staleness",
                        detail="injected stale dispatch "
                        "(engine.fastpath.stale_dispatch)")
                    return py
                start = self._fp_idx - len(self._fp_pending)
                bucket, self._fp_pending = self._fp_pending, []
                done = self._fp_idx >= len(fs["slots"])
                if done:
                    self._fp_idx = 0
                self._fp_q.put(self._fp_group(fs, bucket, start, done))
            return py

    def _fp_group(self, fs: dict, bucket, start: int, done: bool) -> dict:
        """Synthesize one frozen overlap bucket as a negotiated-group
        dict so dispatch reuses _execute verbatim (same watchdog,
        deadline, pipeline window and completion paths).  handle=-2
        marks entries with no core-side record to complete."""
        end = start + len(bucket)
        return {
            "op_type": "allreduce",
            "process_set_id": fs["process_set_id"],
            "dtype": fs["dtype"],
            "red_op": fs["red_op"],
            "prescale": fs["prescale"],
            "postscale": fs["postscale"],
            "aux_sizes": list(fs["lengths"][start:end]),
            "entries": [{"name": n, "handle": -2} for _, _, n in bucket],
            "_fp": True,
            "_fp_taken": [(py, arr) for py, arr, _ in bucket],
            "_fp_done": done,
            "_fp_t0": time.monotonic(),
        }

    def _fp_flush(self, fs: dict, reason: str):
        """Thaw flush (called under _fp_lock, inside the thaw — the
        re-entrant acquire below keeps the guard explicit): push the
        staged-but-undispatched bucket back through full negotiation in
        program order so every staged handle still resolves with
        correct values.  On a poisoned engine the handles error out
        instead — never silently dropped."""
        with self._fp_lock:
            bucket, self._fp_pending = self._fp_pending, []
            self._fp_idx = 0
        self._fp_core_set(False)
        if not bucket:
            return
        LOG.warning(
            "fast path: renegotiating %d staged tensor(s) after %s thaw",
            len(bucket), reason)
        for py, arr, name in bucket:
            with self._lock:
                if self._failed is not None:
                    if not py.poll():
                        py._set_error(HorovodInternalError(
                            "multihost engine disabled after watchdog "
                            "failure: %s" % self._failed))
                    continue
                ch = self.core.enqueue_external(
                    name, "allreduce", tuple(arr.shape),
                    np.dtype(arr.dtype), red_op=fs["red_op"],
                    process_set_id=fs["process_set_id"],
                    prescale=fs["prescale"], postscale=fs["postscale"])
                self._pending[ch._h] = (py, arr)
                self._m_queue_depth.set(len(self._pending))

    def _fp_core_set(self, on: bool):
        """Tell the native core to stretch its idle negotiation cadence
        while frozen (no requests will arrive); tolerate a stale .so
        without the export — the fast path works without it, the core
        just keeps polling at the normal cycle time."""
        set_fp = getattr(self.core, "set_fastpath", None)
        if set_fp is None:
            return
        try:
            set_fp(bool(on))
        except Exception:  # noqa: BLE001 - optional, stale .so
            pass

    def _fp_idle_check(self):
        """Partial-cycle safety valve (exec thread, every drain tick):
        an app that stops enqueuing mid-bucket would otherwise park
        staged handles forever — after ~4 cycle times of staging
        silence, thaw loudly and renegotiate the staged prefix."""
        with self._fp_lock:
            if not self._fp_pending:
                return
            age = time.monotonic() - self._fp_t
            limit = max(0.05, 4.0 * self.config.cycle_time_ms / 1000.0)
            if age > limit:
                self._fp.thaw(
                    "shape",
                    detail="partial frozen cycle: %d staged tensor(s) "
                    "idle for %.2fs" % (len(self._fp_pending), age))

    # -- executor ----------------------------------------------------------

    def _loop(self):
        from ..core.client import parse_negotiated_record
        # Blocking wait in the core (condition variable): the executor
        # runs a record the instant negotiation finishes instead of
        # poll-sleeping half a cycle; the timeout only bounds shutdown
        # latency.
        wait_ms = max(int(self.config.cycle_time_ms), 1)
        while not self._shutdown:
            # Frozen overlap buckets dispatch ahead of negotiated
            # records: a staged bucket is already schedule-certain and
            # every record behind it (if any) postdates the freeze.
            try:
                while True:
                    g = self._fp_q.get_nowait()
                    try:
                        self._execute(g)
                    except Exception as exc:  # noqa: BLE001 - keep draining
                        LOG.error("multihost executor (frozen): %s", exc)
            except queue_mod.Empty:
                pass
            self._fp_idle_check()
            rec = self.core.wait_negotiated(wait_ms)
            if rec is None:
                # A stopped control plane (negotiation failure / peer
                # disconnect) will never negotiate the parked payloads:
                # fail them loudly instead of letting callers hang —
                # this is what lets elastic recovery proceed on worlds
                # where no execution watchdog is configured.
                if (self._failed is None and not self._shutdown
                        and self.core.stopped()):
                    self._poison(HorovodInternalError(
                        "control plane stopped (negotiation failed — "
                        "a member disconnected); failing pending "
                        "collectives"))
                continue
            if faultline.site("mh.drain.record"):
                # Injected negotiated-but-never-dispatched member: the
                # record is consumed and dropped, peers wedge inside
                # their compiled program — the execution watchdog's
                # scenario, on demand.
                LOG.error("faultline: dropping negotiated record")
                continue
            try:
                g = parse_negotiated_record(rec)
                try:
                    # Freeze coordination failing (KV timeout) must not
                    # strand the record: execute it regardless so its
                    # handles resolve; the world simply stays thawed.
                    self._fp_cycle(g)
                except Exception as exc:  # noqa: BLE001
                    LOG.error(
                        "fast-path freeze coordination failed: %s", exc)
                self._execute(g)
            except Exception as exc:  # noqa: BLE001 - keep draining
                LOG.error("multihost executor: %s", exc)

    def _take(self, handle: int):
        with self._lock:
            taken = self._pending.pop(handle, (None, None))
            self._m_queue_depth.set(len(self._pending))
            return taken

    # -- execution-phase watchdog ------------------------------------------

    def _watch_register(self, g, names, taken, entries,
                        deadline_secs: float = 0.0) -> int:
        with self._watch_lock:
            wid = self._watch_seq
            self._watch_seq += 1
            self._watched[wid] = {
                "g": g, "names": names, "taken": taken,
                "entries": entries, "start": time.monotonic(),
                "warned": False,
                # Per-collective deadline (0 = none): absolute bound on
                # this record's watched age.  The clock restarts at
                # compile end (_watch_compile), so a legitimate cold
                # compile is never charged against the deadline.
                "deadline_secs": max(float(deadline_secs), 0.0),
            }
        return wid

    def _watch_compile(self, wid: int, phase: str):
        """Cold-compile bracketing: while a compile runs, the record is
        marked so the watchdog holds fire (the executor thread is alive
        doing local work — charging compile time to the watched window
        would poison a healthy engine); at compile end the clock
        restarts so the window times execution only."""
        with self._watch_lock:
            rec = self._watched.get(wid)
            if rec is not None:
                rec["compiling"] = phase == "begin"
                if phase == "end":
                    rec["start"] = time.monotonic()
            # _last_progress is NOT advanced here: completions are the
            # only liveness signal.  Registering or compiling must not
            # push out detection of an already-wedged earlier group —
            # an app that keeps enqueuing (or keeps cold-compiling)
            # would otherwise starve the watchdog forever.

    def _watch_clear(self, wid: int) -> bool:
        """Remove the record; returns True if the watchdog already
        failed this group's handles (completion must not repeat it)."""
        with self._watch_lock:
            self._watched.pop(wid, None)
            killed = wid in self._killed_wids
            self._killed_wids.discard(wid)
            self._last_progress = time.monotonic()
        return killed

    def _watchdog_loop(self):
        strikes = 0
        while not self._shutdown:
            time.sleep(1.0)
            now = time.monotonic()
            with self._watch_lock:
                # Already-fired records stay in _watched until their
                # (wedged) program clears them, but must not re-fire
                # and re-log every tick.
                items = [(w, r) for w, r in self._watched.items()
                         if w not in self._killed_wids]
                idle = now - self._last_progress
            fired = False
            expired = []
            for wid, rec in items:
                if rec.get("compiling"):
                    # THIS record's own dispatch is mid-compile (local
                    # work, always terminates; its clock restarts at
                    # compile end) — don't charge compile time to its
                    # watched window.  Only the compiling record is
                    # skipped: a workload that keeps cold-compiling new
                    # shapes must not defer detection of an UNRELATED
                    # group that wedged after its own dispatch.
                    continue
                age = now - rec["start"]
                if (self._exec_warn and age > self._exec_warn
                        and not rec["warned"]):
                    rec["warned"] = True
                    LOG.warning(
                        "multihost %s group %s executing for %.0fs — a "
                        "member process may have died after negotiation "
                        "(device-plane stall)", rec["g"]["op_type"],
                        rec["names"], age)
                # Fire only when the whole pipeline is starved too: a
                # busy-but-healthy executor (deep queue, long compile)
                # keeps completing OTHER groups and must not be killed
                # for being slow.
                if (self._exec_timeout and age > self._exec_timeout
                        and idle > self._exec_timeout):
                    fired = True
                # Per-collective deadline: an ABSOLUTE bound on this
                # record alone — no idle gate, no strikes.  Unlike the
                # starvation watchdog, the deadline is a per-group
                # contract: other groups completing does not make THIS
                # group less wedged, and the operator sized the bound
                # for the size class (per-GiB scaling) on purpose.
                dl = rec.get("deadline_secs") or 0.0
                if dl > 0 and age > dl:
                    expired.append(rec)
            if expired:
                strikes = 0
                self._deadline_fire(expired)
                continue
            # Poisoning the engine is irreversible, so demand the
            # starved condition on consecutive ticks: a single tick can
            # straddle the instant a slow-but-healthy program completes
            # (progress lands right after the snapshot above).
            strikes = strikes + 1 if fired else 0
            if strikes >= 2:
                strikes = 0
                self._watchdog_fire()

    def _deadline_fire(self, expired):
        """Per-collective deadline expiry: count + journal each
        expired group, then error-complete everything outstanding and
        poison the engine through the fail-fast path.  The worker's
        pending handles raise :class:`CollectiveDeadlineExceeded` (a
        ``HorovodInternalError``), which the elastic recovery loop
        treats as restorable — its message must never contain the
        stall inspector's abort text, which would route elastic to the
        drain exit instead of restore-from-spill."""
        # Thaw BEFORE poisoning: the flush re-enqueues any staged
        # frozen bucket through the core while _failed is still unset,
        # so those handles land in the pending map and are swept into
        # the same loud deadline error as everything else — no hang,
        # and the next (recovered) engine starts from full negotiation.
        fastpath.thaw_all(
            "deadline", detail="per-collective deadline expired")
        for rec in expired:
            g = rec["g"]
            metrics.counter("collective_deadline_expired_total",
                            op=g["op_type"]).inc()
            metrics.event("collective_deadline_expired",
                          op=g["op_type"], names=list(rec["names"]),
                          deadline_secs=rec.get("deadline_secs"),
                          size_class=g.get("_metrics_class"))
        self._poison(lambda records: CollectiveDeadlineExceeded(
            "collective deadline exceeded: negotiated group(s) %s "
            "outlived their per-collective deadline "
            "(HOROVOD_COLLECTIVE_TIMEOUT_SECS, size-class scaled); "
            "error-completing outstanding handles and poisoning the "
            "engine so the elastic recovery loop restores from the "
            "last committed spill" % sorted(
                {rec["g"]["op_type"] + str(rec["names"])
                 for rec in records.values()})))

    def _watchdog_fire(self):
        """Fail every outstanding handle and poison the engine: the
        device program a dead member never joined will wedge the
        runtime thread forever, but callers get a loud diagnostic
        instead of hanging with it."""
        self._poison(lambda records: HorovodInternalError(
            "device execution watchdog: negotiated group(s) %s did not "
            "complete within %.1fs (HOROVOD_DEVICE_EXEC_TIMEOUT_SECONDS)"
            "; a member process likely died between negotiation and "
            "dispatch — failing outstanding handles" % (
                sorted({rec["g"]["op_type"] + str(rec["names"])
                        for rec in records.values()}),
                self._exec_timeout)))

    def _poison(self, exc_or_factory):
        """Fail every watched group and parked payload and reject new
        work — shared by the execution watchdog and the control-plane-
        stopped sweep.  A callable argument receives the ONE records
        snapshot that is actually failed, so the diagnostic can never
        name a group this sweep did not kill."""
        with self._watch_lock:
            records = {w: r for w, r in self._watched.items()
                       if w not in self._killed_wids}
            # Keep the records (cleared by _finish) but remember they
            # were killed, so a program that later unwedges does not
            # repeat completion on already-failed handles — and the
            # fire loop never re-fires them.
            self._killed_wids.update(records)
        exc = (exc_or_factory(records) if callable(exc_or_factory)
               else exc_or_factory)
        LOG.error("%s", exc)
        # _failed is set under the SAME lock that guards _enqueue's
        # check + park, so a racing enqueue either raises or lands in
        # the pending map swept here.
        with self._lock:
            self._failed = exc
            pending, self._pending = self._pending, {}
        for rec in records.values():
            self._complete_error(rec["g"], rec["names"], rec["taken"],
                                 rec["entries"], exc)
        # Payloads never dispatched (parked behind the wedged program)
        # fail too — and _enqueue rejects new work from here on.
        for py, _ in pending.values():
            if py is not None and not py.poll():
                py._set_error(exc)

    def _execute(self, g: dict):  # graftlint: schedule-entry=hier -- per-group dispatch order of the hierarchical DCN plane
        """Stage and dispatch one negotiated group, then hand the
        blocking tail (device_get for numpy-typed entries, handle
        resolution) to the completion thread — the drain loop is free
        to pop and dispatch group N+1 while N's program runs on
        device."""
        entries = g["entries"]
        if g.get("_fp"):
            # Frozen overlap bucket: payloads were staged caller-side,
            # nothing is parked in the core or the pending map
            # (handle=-2 entries skip core completion in _finish too).
            taken = g.pop("_fp_taken")
        else:
            taken = [self._take(e["handle"]) if e["handle"] >= 0
                     else (None, None) for e in entries]
        names = [e["name"] for e in entries]
        if g.get("error"):
            # Fail-fast record: the core refused to zero-fill a
            # negotiated entry missing on this non-joined rank.  Every
            # rank of the group must fail loudly, never complete with
            # a corrupted reduction: error-complete this group's
            # handles, then poison the engine (peers wedge inside the
            # program this rank never joins; their watchdog/stopped
            # sweep turns that into the same loud error).
            exc = HorovodInternalError(g["error"])
            self._complete_error(g, names, taken, entries, exc)
            self._poison(exc)
            return
        mc = self.collectives_for(g["process_set_id"])
        if self._failed is not None:
            self._complete_error(g, names, taken, entries, self._failed)
            return
        # Register BEFORE dispatch — on worlds where the compiled call
        # itself blocks until peers join (CPU gloo), a wedged dispatch
        # must already be watched.  Cold compiles run AOT inside
        # _compiled and report back via the per-dispatch ``notify``
        # callback, which restarts THIS group's clock: compile time
        # (local, legitimately long) is never charged to the watched
        # execution window.  The callback is threaded through the
        # dispatch call — never parked on the shared mesh object, where
        # a second executor would cross callbacks (graftlint
        # dispatch-scoped).
        group_bytes = sum(
            int(arr.nbytes) for _, arr in taken if arr is not None)
        deadline_secs = resilience.collective_deadline(group_bytes)
        wid = self._watch_register(g, names, taken, entries,
                                   deadline_secs)
        notify = lambda phase: self._watch_compile(wid, phase)  # noqa: E731
        # One negotiated group = one engine cycle in this mode; the
        # group id correlates the timeline span, the metrics gauge and
        # (below, via g) the completion-latency histogram.
        self._group_seq += 1
        gid = self._group_seq
        if g.get("_fp"):
            # A frozen schedule's buckets are one logical cycle: count
            # it ONCE (on the final bucket) and as a fast-path cycle,
            # never additionally as a negotiation cycle — levers.metrics
            # must attribute each cycle to exactly one path.
            if g.get("_fp_done"):
                self._m_fp_frozen.inc()
        else:
            self._m_cycles.inc()
        self._m_last_group.set(gid)
        if g["op_type"] == "allreduce" and len(entries) > 1:
            self._m_bytes_fused.inc(group_bytes)
            self._m_tensors_fused.inc(len(entries))
        g["_metrics_t0"] = time.monotonic()
        g["_metrics_class"] = _pow2_class(group_bytes)
        if faultline.site("mh.deadline.wedge"):
            # The group is registered and deadline-stamped but its
            # dispatch is withheld: the exact shape of a member whose
            # program never starts.  The watchdog's deadline check must
            # expire it -> error-complete -> poison -> elastic restore.
            LOG.error(
                "faultline: withholding dispatch of negotiated %s "
                "group %s (mh.deadline.wedge); the group stays watched "
                "until its per-collective deadline expires",
                g["op_type"], names)
            return
        # The leg guard bounds its retries by this group's absolute
        # deadline (thread-local: two executors may share one mesh).
        resilience.set_group_deadline(
            time.monotonic() + deadline_secs if deadline_secs > 0
            else None)
        try:
            # Per-tensor timeline span (reference: the EXEC_* phases the
            # native executors record) + an xprof TraceAnnotation so the
            # device program shows up named in jax profiler traces.
            import jax.profiler
            self.timeline.activity_start_all(
                names, "EXEC_DEVICE_" + g["op_type"].upper(),
                args={"group": gid})
            with jax.profiler.TraceAnnotation(
                    "hvd.mh.%s[%d]" % (g["op_type"], len(entries))):
                finalize, needs_host, rep = self._dispatch_group(
                    g, mc, taken, notify)
        except Exception as exc:  # noqa: BLE001
            if not self._watch_clear(wid):
                self._complete_error(g, names, taken, entries, exc)
            return
        finally:
            resilience.set_group_deadline(None)
        with self._lock:
            route_q = needs_host or self._host_inflight > 0
            if route_q:
                self._host_inflight += 1
        nbytes = 0
        if self.config.autotune and g["op_type"] == "allreduce":
            nbytes = int(sum(int(n) for n in g["aux_sizes"])
                         * np.dtype(g["dtype"]).itemsize)
        t0 = time.monotonic()
        if rep is not None:
            self._inflight_outs.append(rep)
            while len(self._inflight_outs) > self._depth:
                try:
                    self._inflight_outs.pop(0).block_until_ready()
                except Exception as exc:  # noqa: BLE001
                    # Handles were resolved at dispatch for
                    # device-resident groups; the failure would
                    # otherwise only surface when a consumer touches
                    # the array — leave a diagnostic trail here.
                    LOG.error(
                        "multihost device program failed after "
                        "dispatch-time completion: %s", exc)
        if route_q:
            # Blocking host fetch — or completions still in flight
            # whose relative order we keep — go through the completion
            # thread.  (_host_inflight is decremented only after
            # _finish fully resolves a queued group, so "zero" really
            # means every earlier group's handles are set.)
            self._done_q.put((g, names, taken, entries, finalize, wid,
                              nbytes, t0))
        else:
            # Device-resident group: finalize never blocks, so complete
            # inline and spare the cross-thread handoff (a scheduler
            # quantum per op on busy hosts).
            self._finish(g, names, taken, entries, finalize, wid)
            if nbytes and rep is not None:
                # Autotune signal: the completion thread blocks on the
                # output and reports true dispatch-to-completion time
                # (measuring at a later pipeline-window pop would add
                # arbitrary idle; the negotiation cycle says nothing
                # about async XLA payloads).
                self._done_q.put(("observe", rep, nbytes, t0))

    def _completion_loop(self):
        while True:
            item = self._done_q.get()
            if item is None:
                return
            if item[0] == "observe":
                # Device-resident group: block on its output, report
                # true completion time to the autotuner.
                _, rep, nbytes, t0 = item
                try:
                    rep.block_until_ready()
                except Exception as exc:  # noqa: BLE001
                    # The group's handles resolved ok=True at dispatch
                    # (device-resident inline completion); a runtime
                    # failure here must not be a throughput sample, and
                    # must not vanish — the consumer will hit it when
                    # touching the array, so leave the diagnostic now.
                    LOG.error(
                        "multihost device program failed after "
                        "dispatch-time completion (autotune observe): "
                        "%s", exc)
                    continue
                self._observe_exec(nbytes, t0)
                continue
            g, names, taken, entries, finalize, wid, nbytes, t0 = item
            ok = self._finish(g, names, taken, entries, finalize, wid)
            # Host-fetch completion IS the group's true completion —
            # but a failed/watchdog-killed group is not a throughput
            # sample.
            if ok:
                self._observe_exec(nbytes, t0)
            with self._lock:
                self._host_inflight -= 1

    def _observe_exec(self, nbytes, t0):
        if not nbytes:
            return
        try:
            self.core.autotune_observe(nbytes, time.monotonic() - t0)
        except Exception:  # noqa: BLE001 - optional feedback path
            pass

    def _finish(self, g, names, taken, entries, finalize, wid=None
                ) -> bool:
        """Resolve the group's handles; returns True only on a clean
        completion (False for errors or watchdog-killed groups, which
        must not become autotune throughput samples)."""
        try:
            results = finalize()
        except Exception as exc:  # noqa: BLE001 - keep draining
            if not (wid is not None and self._watch_clear(wid)):
                self._complete_error(g, names, taken, entries, exc)
            return False
        if wid is not None and self._watch_clear(wid):
            # The watchdog already failed this group's handles while
            # the program was wedged; a late completion must not
            # repeat external_done/release or overwrite the error.
            return False
        try:
            self.timeline.activity_end_all(names)
            for (py, _), res, e in zip(taken, results, entries):
                if e["handle"] >= 0:
                    self.core.external_done(e["handle"], ok=True)
                    self.core._lib.hvd_tcp_release(e["handle"])
                if py is not None and not py.poll():
                    py._set_result(res)
        except Exception as exc:  # noqa: BLE001 - keep draining
            self._complete_error(g, names, taken, entries, exc)
            return False
        # Dispatch-to-resolution latency per (op, pow2 size class);
        # only clean completions are samples — an error or watchdog
        # kill is not a latency observation.
        t0 = g.get("_metrics_t0")
        if t0 is not None:
            metrics.histogram(
                "mh_collective_seconds", op=g["op_type"],
                size_class=g.get("_metrics_class", "0")).observe(
                    time.monotonic() - t0)
            fp_t0 = g.get("_fp_t0")
            if fp_t0 is not None:
                # Per-bucket staging-to-completion latency of the
                # frozen fast path (the eager plane reports dispatch
                # time; here completion is the meaningful bound).
                self._m_fp_bucket.observe(time.monotonic() - fp_t0)
            now = time.monotonic()
            if now - self._cache_gauge_t >= 1.0:
                # Benign race on the throttle stamp (worst case one
                # extra refresh); the totals only move on a compile,
                # so per-completion recomputation would be waste.
                self._cache_gauge_t = now
                with self._lock:
                    caches = [mc._fns
                              for mc in self._collectives.values()]
                self._m_cache_hits.set(sum(c.hits for c in caches))
                self._m_cache_misses.set(sum(c.misses for c in caches))
        return True

    def _complete_error(self, g, names, taken, entries, exc):
        self.timeline.activity_end_all(names)
        LOG.error("multihost %s failed: %s", g["op_type"], exc)
        # The failure-side complement of mh_collective_seconds (which
        # only records clean completions): every error-completed group
        # is visible in the fleet merge, bucketed by why it died.
        metrics.counter("mh_collective_failures_total", op=g["op_type"],
                        reason=resilience.failure_reason(exc)).inc()
        for (py, _), e in zip(taken, entries):
            if e["handle"] >= 0:
                self.core.external_done(e["handle"], ok=False,
                                        error=str(exc))
                self.core._lib.hvd_tcp_release(e["handle"])
            if py is not None and not py.poll():
                py._set_error(exc)

    @staticmethod
    def _match(out, arr, shape=None):
        """Shape a program output like the caller's input: device
        arrays stay device-resident (eager reshape only), numpy inputs
        get numpy back.  This is the single conversion point — the
        GlobalMeshCollectives methods always return device arrays."""
        import jax
        import jax.numpy as jnp
        if arr is not None and _is_device_array(arr):
            return jnp.reshape(out, shape) if shape is not None else out
        host = np.asarray(jax.device_get(out))
        return host.reshape(shape) if shape is not None else host

    def _dispatch_group(self, g: dict, mc: GlobalMeshCollectives,
                        taken: List[tuple],
                        notify=None):  # graftlint: hot-path
        """Issue the group's compiled collective (async XLA dispatch)
        and return ``(finalize, needs_host, rep)``: a finalize() ->
        results closure, whether it blocks on a host fetch (numpy-typed
        entries), and one representative output array of the dispatched
        program (for the drain thread's pipeline-depth window).
        Blocking finalizes run only on the completion thread;
        device-resident ones may complete inline.  ``notify`` is this
        dispatch's cold-compile bracket, threaded down to
        ``mc._compiled``."""
        op = g["op_type"]
        dtype = g["dtype"]
        if op == "allreduce":
            # Fused group in negotiated order (missing = joined rank ->
            # zero contribution, synthesized on device).  One compiled
            # program takes every entry and XLA's all-reduce combiner
            # fuses the collectives; payloads never transit numpy.
            # The controller rejects joined + Min/Max/Product/Adasum at
            # negotiation and rewrites Average to Sum with a live-count
            # divisor; by the time a zero-fill reaches this executor the
            # reduction must be Sum (the only op whose identity is zero).
            if (any(arr is None for _, arr in taken)
                    and g["red_op"] != SUM):
                raise HorovodInternalError(
                    "zero-contribution join reached the executor with "
                    "op=%s; only Sum may be zero-filled" % g["red_op"])
            lengths = [int(n) for n in g["aux_sizes"]]
            outs = mc.fused_allreduce(
                [arr for _, arr in taken], lengths, dtype,
                g["red_op"], g["prescale"], g["postscale"], notify,
                names=[e["name"] for e in g["entries"]])
            needs_host = any(arr is None or not _is_device_array(arr)
                             for _, arr in taken)

            def finalize():
                # One batched device_get for every numpy-typed entry (a
                # per-entry fetch would serialize N host round-trips on
                # the thread that gates all handles).
                import jax
                import jax.numpy as jnp
                to_host = [i for i, (_, arr) in enumerate(taken)
                           if arr is None or not _is_device_array(arr)]
                fetched = dict(zip(to_host, jax.device_get(  # graftlint: disable=host-bounce issue=ISSUE-1 -- THE documented batched fetch for numpy-typed entries; runs on the completion thread only
                    [outs[i] for i in to_host]))) if to_host else {}
                results = []
                for i, ((py, arr), out, ln) in enumerate(
                        zip(taken, outs, lengths)):
                    shape = arr.shape if arr is not None else (ln,)
                    if i in fetched:
                        results.append(
                            np.asarray(fetched[i]).reshape(shape))  # graftlint: disable=host-bounce issue=ISSUE-1 -- reshape of already-fetched host data, no device sync
                    else:
                        results.append(jnp.reshape(out, shape))
                return results
            return finalize, needs_host, outs[0]
        (py, arr) = taken[0]
        needs_host = arr is None or not _is_device_array(arr)
        if op == "allgather":
            out = mc.allgather(arr, g["aux_sizes"], notify)
            return (lambda: [self._match(out, arr)]), needs_host, out
        if op == "broadcast":
            # root_rank is a GLOBAL rank; map to member index.
            ranks = self._resolve_process_set(g["process_set_id"])
            members = ranks if ranks is not None else list(
                range(mc.size))
            root_idx = members.index(g["root_rank"])
            out = mc.broadcast(arr, root_idx, notify)
            return (lambda: [self._match(out, arr)]), needs_host, out
        if op == "alltoall":
            out, recv = mc.alltoall(arr, np.asarray(g["aux_sizes"]),  # graftlint: disable=host-bounce issue=ISSUE-1 -- negotiated splits metadata, never payload bytes
                                    notify)
            return ((lambda: [(self._match(out, arr), recv)]),
                    needs_host, out)
        if op == "reducescatter":
            out = mc.reducescatter(arr, g["red_op"], notify,
                                   name=g["entries"][0]["name"])
            return (lambda: [self._match(out, arr)]), needs_host, out
        raise NotImplementedError("multihost op %r" % op)

    # -- shutdown ----------------------------------------------------------

    def shutdown(self):
        # Thaw first (flush re-parks staged payloads in the pending
        # map, swept into "engine shut down" errors below), and drop
        # out of the thaw_all registry before the drain thread dies.
        self._fp.thaw("membership", detail="engine shutdown")
        fastpath.unregister(self._fp)
        self._shutdown = True
        self._thread.join(timeout=10.0)
        # Stop the completion thread with a sentinel AFTER the queued
        # work, so every dispatched group still resolves its handles.
        # The put is bounded: the queue may be permanently full if a
        # completion is wedged on a collective whose peer died — give
        # up after the deadline (the thread is a daemon) rather than
        # hanging shutdown in exactly the failure it must clean up.
        deadline = time.monotonic() + 10.0
        while True:
            try:
                self._done_q.put_nowait(None)
                break
            except queue_mod.Full:
                if (time.monotonic() > deadline
                        or not self._done_thread.is_alive()):
                    break
                time.sleep(0.05)
        self._done_thread.join(timeout=10.0)
        # Fail anything stranded: groups still queued (a wedged
        # completion, or a drain thread that outlived its join and
        # enqueued past the sentinel) would otherwise leave their
        # already-_take()n handles unresolved forever.
        while True:
            try:
                item = self._done_q.get_nowait()
            except queue_mod.Empty:
                break
            if item is None or item[0] == "observe":
                continue
            g, names, taken, entries = item[:4]
            self._complete_error(
                g, names, taken, entries,
                HorovodInternalError("engine shut down"))
        with self._lock:
            pending, self._pending = self._pending, {}
        for py, _ in pending.values():
            if not py.poll():
                py._set_error(
                    HorovodInternalError("engine shut down"))
