"""Pluggable collective backend registry with a priority walk.

Reference parity: ``horovod/common/ops/operation_manager.cc`` — per-op
priority lists where the first backend whose ``Enabled(entries)`` test
passes executes the op (there: NCCL > DDL > GPU > MPI > Gloo ...).
TPU translation: the planes are ICI/DCN device collectives (in-process
engine or multihost engine) and host-TCP CPU collectives (the native
core).  Selection is per-request — a backend may accept large device
payloads and decline tiny host-side ones, or vice versa — and the walk
order can be overridden with ``HVD_TPU_BACKENDS`` / ``HOROVOD_BACKENDS``
(comma list of backend names, highest priority first) or extended at
runtime with :func:`register_backend`.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from .engine import HorovodInternalError

DEVICE_OPS = ("allreduce", "allgather", "broadcast", "alltoall",
              "reducescatter")


class OpRequest:
    """One collective submission (a group for grouped allreduce)."""

    __slots__ = ("op_type", "tensors", "names", "red_op", "prescale",
                 "postscale", "root_rank", "splits", "process_set_id",
                 "ps_size", "is_group")

    def __init__(self, op_type, tensors, names, red_op=None, prescale=1.0,
                 postscale=1.0, root_rank=0, splits=None,
                 process_set_id=0, ps_size=1, is_group=False):
        self.op_type = op_type
        self.tensors = tensors        # list (len 1 unless is_group)
        self.names = names            # matching names
        self.red_op = red_op
        self.prescale = prescale
        self.postscale = postscale
        self.root_rank = root_rank
        self.splits = splits
        self.process_set_id = process_set_id
        self.ps_size = ps_size
        self.is_group = is_group

    def __repr__(self):
        return "OpRequest(%s, %s)" % (self.op_type, self.names)


class CollectiveBackend:
    """Base class (reference ``HorovodOp`` + the manager's entries).

    ``enabled`` may inspect the request per-tensor; the first enabled
    backend in priority order wins.  ``submit`` returns one handle, or a
    list of handles for a group request.
    """

    name = "backend"

    def enabled(self, req: OpRequest) -> bool:
        raise NotImplementedError

    def submit(self, req: OpRequest):
        raise NotImplementedError


class OpManager:
    """Priority walk over registered backends (operation_manager.cc)."""

    def __init__(self, backends: Sequence[CollectiveBackend]):
        self.backends: List[CollectiveBackend] = list(backends)

    def register(self, backend: CollectiveBackend, index: int = 0):
        """Insert a backend at priority ``index`` (0 = highest)."""
        self.backends.insert(index, backend)

    def submit(self, req: OpRequest):
        for b in self.backends:
            if b.enabled(req):
                return b.submit(req)
        raise HorovodInternalError(
            "no enabled backend for %r (registered: %s)"
            % (req, [b.name for b in self.backends]))

    def backend_for(self, req: OpRequest) -> Optional[str]:
        """Name of the backend the walk would select (introspection)."""
        for b in self.backends:
            if b.enabled(req):
                return b.name
        return None


def order_from_env(backends: Sequence[CollectiveBackend], env: str
                   ) -> List[CollectiveBackend]:
    """Reorder/filter builtin backends per the env override; unknown
    names raise (a typo silently dropping a plane would be miserable to
    debug at pod scale)."""
    names = [n.strip() for n in env.split(",") if n.strip()]
    by_name = {b.name: b for b in backends}
    unknown = [n for n in names if n not in by_name]
    if unknown:
        raise ValueError(
            "unknown backend(s) %s in backend order override; available: %s"
            % (unknown, sorted(by_name)))
    return [by_name[n] for n in names]


# -- builtin backends -------------------------------------------------------


def _np(tensor):
    return np.ascontiguousarray(np.asarray(tensor))


class MultihostIciBackend(CollectiveBackend):
    """Device payload plane of multihost mode: the native core
    negotiates order, the multihost engine executes compiled XLA
    collectives over the global mesh (ICI/DCN on pods)."""

    name = "multihost_ici"

    def __init__(self, get_engine: Callable, get_core: Callable):
        self._get_engine = get_engine
        self._get_core = get_core

    def enabled(self, req: OpRequest) -> bool:
        from .xla_ops import ADASUM
        # Adasum allreduce is device-resident (adasum_combine: ppermute
        # XOR-tree under shard_map — the adasum_gpu_operations.cc
        # analog); other Adasum ops stay on the host plane (TreeAdasum).
        if req.red_op == ADASUM and req.op_type != "allreduce":
            return False
        return req.op_type in DEVICE_OPS

    def submit(self, req: OpRequest):
        eng = self._get_engine()
        if req.is_group:
            # Atomic negotiation for any grouped op (reference
            # group_table.cc covers allgather/reducescatter too).
            self._get_core().register_group(req.names)
        if req.op_type == "allreduce":
            hs = [eng.enqueue_allreduce(
                n, t, red_op=req.red_op, prescale=req.prescale,
                postscale=req.postscale, process_set_id=req.process_set_id)
                for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        if req.op_type == "allgather":
            hs = [eng.enqueue_allgather(
                n, t, process_set_id=req.process_set_id)
                for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        if req.op_type == "reducescatter":
            hs = [eng.enqueue_reducescatter(
                n, t, red_op=req.red_op,
                process_set_id=req.process_set_id)
                for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        t, n = req.tensors[0], req.names[0]
        if req.op_type == "broadcast":
            return eng.enqueue_broadcast(
                n, t, root_rank=req.root_rank,
                process_set_id=req.process_set_id)
        if req.op_type == "alltoall":
            splits = (None if req.splits is None
                      else list(np.asarray(req.splits)))
            return eng.enqueue_alltoall(
                n, t, splits=splits,
                process_set_id=req.process_set_id)
        raise HorovodInternalError("unsupported op %s" % req.op_type)


class HostTcpBackend(CollectiveBackend):
    """Host payload plane: the native core moves bytes over TCP rings
    (the reference's Gloo CPU path; also Adasum's home)."""

    name = "host_tcp"

    def __init__(self, get_core: Callable):
        self._get_core = get_core

    def enabled(self, req: OpRequest) -> bool:
        return req.op_type in DEVICE_OPS

    def submit(self, req: OpRequest):
        core = self._get_core()
        if req.is_group:
            core.register_group(req.names)
        if req.op_type == "allreduce":
            hs = [core.allreduce_async(
                _np(t), n, op=req.red_op, prescale=req.prescale,
                postscale=req.postscale, process_set_id=req.process_set_id)
                for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        if req.op_type == "allgather":
            hs = [core.allgather_async(
                _np(t), n, process_set_id=req.process_set_id)
                for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        if req.op_type == "reducescatter":
            hs = [core.reducescatter_async(
                _np(t), n, op=req.red_op,
                process_set_id=req.process_set_id)
                for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        t, n = req.tensors[0], req.names[0]
        if req.op_type == "broadcast":
            return core.broadcast_async(
                _np(t), n, root_rank=req.root_rank,
                process_set_id=req.process_set_id)
        if req.op_type == "alltoall":
            splits = (None if req.splits is None
                      else list(np.asarray(req.splits)))
            return core.alltoall_async(
                _np(t), n, splits=splits,
                process_set_id=req.process_set_id)
        raise HorovodInternalError("unsupported op %s" % req.op_type)


class InProcessIciBackend(CollectiveBackend):
    """Single-controller SPMD plane: rank-major stacked inputs, the
    background engine fuses and executes compiled XLA collectives over
    the local mesh."""

    name = "inprocess_ici"

    def __init__(self, get_engine: Callable):
        self._get_engine = get_engine

    def enabled(self, req: OpRequest) -> bool:
        return req.op_type in DEVICE_OPS

    def _stack(self, tensor, ps_size):
        import jax.numpy as jnp
        if isinstance(tensor, (list, tuple)):
            arr = jnp.stack([jnp.asarray(t) for t in tensor])
        else:
            arr = jnp.asarray(tensor)
        if arr.shape[0] != ps_size:
            raise ValueError(
                "expected rank-major stacked input with leading dim %d "
                "(one slice per rank), got shape %s"
                % (ps_size, arr.shape))
        return arr

    def submit(self, req: OpRequest):
        import jax.numpy as jnp
        from .engine import CollectiveHandle
        from .xla_ops import ADASUM
        eng = self._get_engine()
        if req.op_type == "allreduce":
            if req.red_op == ADASUM:
                from ..utils.adasum import adasum_reduce_stacked
                hs = []
                for t, n in zip(req.tensors, req.names):
                    h = CollectiveHandle(n)
                    try:
                        if eng._joined_member_indices(req.process_set_id):
                            # Zero rows are not a neutral element for
                            # Adasum's dot-product combine; reject
                            # rather than mis-reduce.
                            raise HorovodInternalError(
                                "Adasum allreduce submitted while ranks "
                                "are joined; only Sum/Average allreduce "
                                "supports zero-contribution join")
                        h._set_result(adasum_reduce_stacked(
                            self._stack(t, req.ps_size)))
                    except Exception as exc:  # noqa: BLE001
                        h._set_error(exc)
                    hs.append(h)
                return hs if req.is_group else hs[0]
            hs = [eng.enqueue_allreduce(
                n, self._stack(t, req.ps_size), req.red_op,
                req.prescale, req.postscale, req.process_set_id)
                for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        if req.op_type == "allgather":
            def one_allgather(t, n):
                if isinstance(t, (list, tuple)):
                    per_rank = [jnp.asarray(x) for x in t]
                    if len(per_rank) != req.ps_size:
                        raise ValueError("need one tensor per rank")
                else:
                    arr = jnp.asarray(t)
                    per_rank = [arr[r] for r in range(req.ps_size)]
                return eng.enqueue_allgather(n, per_rank,
                                             req.process_set_id)
            hs = [one_allgather(t, n)
                  for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        if req.op_type == "reducescatter":
            hs = [eng.enqueue_reducescatter(
                n, self._stack(t, req.ps_size), req.red_op,
                req.process_set_id)
                for t, n in zip(req.tensors, req.names)]
            return hs if req.is_group else hs[0]
        t, n = req.tensors[0], req.names[0]
        if req.op_type == "broadcast":
            return eng.enqueue_broadcast(
                n, self._stack(t, req.ps_size), req.root_rank,
                req.process_set_id)
        if req.op_type == "alltoall":
            splits = req.splits
            if isinstance(t, (list, tuple)):
                t = jnp.stack([jnp.asarray(x) for x in t]) \
                    if splits is None else [jnp.asarray(x) for x in t]
            if splits is not None:
                splits = np.asarray(splits)
                if isinstance(t, list):
                    t = jnp.stack(t) if len(
                        {x.shape for x in t}) == 1 else t
            return eng.enqueue_alltoall(n, t, splits, req.process_set_id)
        raise HorovodInternalError("unsupported op %s" % req.op_type)
