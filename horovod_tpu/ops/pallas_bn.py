"""Fused BatchNorm(+residual add)(+ReLU) Pallas TPU kernels.

The headline ResNet-50 benchmark spends ~31% of its step in train-mode
BatchNorm (docs/benchmarks.md), an HBM-bandwidth-bound op.  The XLA
lowering of flax ``nn.BatchNorm`` + relu + residual-add costs ~8
activation traversals per layer (fwd+bwd, measured); these kernels do
the minimum the semantics allow:

* forward: one stats pass (sum + sum-of-squares in a single read of
  ``x``, f32 VMEM accumulators) + one apply pass that fuses normalize,
  affine, the residual add, and the ReLU into a single read+write;
* backward: one fused reduction pass producing BOTH dbeta and dgamma
  (with the ReLU mask recomputed in-register from ``x`` — the mask is
  never materialized in HBM) + one dx pass that also emits the residual
  gradient.

Reference parity note: the reference has no BN kernel of its own (BN
backward rides cuDNN, ``torch.nn.BatchNorm2d``); this is the
TPU-native equivalent of that vendor-kernel dependence, in the same
spirit as ``pallas_kernels.py`` (SURVEY.md §7 phase 7).

All kernels run compiled on TPU and through the Pallas interpreter
off-TPU, so the CPU test world exercises the same code path; tests
compare y/dx/dgamma/dbeta/dres against an f32 XLA oracle
(tests/test_pallas_bn.py).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


def _largest_divisor(m: int, cap: int) -> Optional[int]:
    """Largest d <= cap with m % d == 0 and d % 8 == 0 (sublane tiling)."""
    for d in range(min(cap, m), 7, -1):
        if m % d == 0 and d % 8 == 0:
            return d
    return None


def _plan(m: int, c: int):
    """(fold, c_block) for the (M/fold, fold*C) view, or None when the
    shape doesn't tile.

    Small channel counts are folded: viewing row-major (M, C) as
    (M/k, k*C) is free and fills the 128-wide VPU lanes; per-channel
    sums are then k partial sums combined outside the kernel.  Each
    kernel wrapper picks its own M block from a VMEM budget scaled by
    its operand count (_m_for).
    """
    fold = 1
    if c < 128:
        if c % 8 or 128 % c:
            return None
        fold = 128 // c
        if m % fold:
            return None
        m, c = m // fold, c * fold
    if c <= 256:
        c_blk = c
    elif c % 256 == 0:
        c_blk = 256
    elif c % 128 == 0:
        c_blk = 128
    else:
        return None
    if _m_for(m, c_blk, 5) is None:
        return None
    return fold, c_blk


def _m_for(m: int, c_blk: int, n_ops: int) -> Optional[int]:
    """M block size for a kernel moving n_ops activation-sized
    operands: double-buffered blocks must fit a ~8 MiB VMEM budget."""
    cap = max(8, (8 << 20) // (c_blk * 2 * 2 * n_ops))
    return _largest_divisor(m, cap)


# ---------------------------------------------------------------------------
# kernels (all operate on x reshaped to (M, C))
# ---------------------------------------------------------------------------


def _stats_kernel(x_ref, sum_ref, sq_ref, s_scr, q_scr):
    # grid = (nc, nm): the channel tile's f32 accumulators live in VMEM
    # scratch across the inner M axis; x is read exactly once.  Outputs
    # are raw column sums — the (tiny) mean/var math happens outside so
    # the folded small-C view can combine its partial columns first.
    t = pl.program_id(1)
    nm = pl.num_programs(1)

    @pl.when(t == 0)
    def _init():
        s_scr[:] = jnp.zeros_like(s_scr)
        q_scr[:] = jnp.zeros_like(q_scr)

    xb = x_ref[...].astype(jnp.float32)
    s_scr[:] += jnp.sum(xb, axis=0, keepdims=True)
    q_scr[:] += jnp.sum(xb * xb, axis=0, keepdims=True)

    @pl.when(t == nm - 1)
    def _finish():
        sum_ref[...] = s_scr[:]
        sq_ref[...] = q_scr[:]


def _apply_kernel(x_ref, mean_ref, var_ref, gamma_ref, beta_ref, *rest,
                  eps, relu, residual):
    if residual:
        res_ref, y_ref = rest
    else:
        (y_ref,) = rest
    xb = x_ref[...].astype(jnp.float32)
    rinv = jax.lax.rsqrt(var_ref[...] + eps)
    z = (xb - mean_ref[...]) * (rinv * gamma_ref[...]) + beta_ref[...]
    if residual:
        z = z + res_ref[...].astype(jnp.float32)
    if relu:
        z = jnp.maximum(z, 0.0)
    y_ref[...] = z.astype(y_ref.dtype)


def _dy_eff(xh, dy_raw, gamma_ref, beta_ref, res_ref, relu, residual):
    """ReLU-masked upstream gradient; the pre-activation is recomputed
    in-register (never stored)."""
    dy = dy_raw.astype(jnp.float32)
    if relu:
        z = xh * gamma_ref[...] + beta_ref[...]
        if residual:
            z = z + res_ref[...].astype(jnp.float32)
        dy = jnp.where(z > 0.0, dy, 0.0)
    return dy


def _bwd_red_kernel(x_ref, dy_ref, mean_ref, var_ref, gamma_ref,
                    beta_ref, *rest, eps, relu, residual):
    # One read of (x, dy) produces BOTH reductions.
    if residual:
        res_ref, db_ref, dg_ref, db_scr, dg_scr = rest
    else:
        db_ref, dg_ref, db_scr, dg_scr = rest
        res_ref = None
    t = pl.program_id(1)
    nm = pl.num_programs(1)

    @pl.when(t == 0)
    def _init():
        db_scr[:] = jnp.zeros_like(db_scr)
        dg_scr[:] = jnp.zeros_like(dg_scr)

    xb = x_ref[...].astype(jnp.float32)
    rinv = jax.lax.rsqrt(var_ref[...] + eps)
    xh = (xb - mean_ref[...]) * rinv
    dy = _dy_eff(xh, dy_ref[...], gamma_ref, beta_ref, res_ref, relu,
                 residual)
    db_scr[:] += jnp.sum(dy, axis=0, keepdims=True)
    dg_scr[:] += jnp.sum(dy * xh, axis=0, keepdims=True)

    @pl.when(t == nm - 1)
    def _finish():
        db_ref[...] = db_scr[:]
        dg_ref[...] = dg_scr[:]


def _bwd_dx_kernel(x_ref, dy_ref, mean_ref, var_ref, gamma_ref,
                   beta_ref, db_ref, dg_ref, *rest, eps, relu,
                   residual, inv_m):
    if residual:
        res_ref, dx_ref, dres_ref = rest
    else:
        (dx_ref,) = rest
        res_ref = None
    xb = x_ref[...].astype(jnp.float32)
    rinv = jax.lax.rsqrt(var_ref[...] + eps)
    xh = (xb - mean_ref[...]) * rinv
    dy = _dy_eff(xh, dy_ref[...], gamma_ref, beta_ref, res_ref, relu,
                 residual)
    dx = (gamma_ref[...] * rinv) * (
        dy - db_ref[...] * inv_m - xh * (dg_ref[...] * inv_m))
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if residual:
        dres_ref[...] = dy.astype(dres_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call orchestration (2-D (M, C) views)
# ---------------------------------------------------------------------------


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, dtype)


def _params(interpret, reduce_m: bool):
    """Mosaic grid semantics: channel tiles are independent
    ("parallel"); the inner M axis accumulates into VMEM scratch for
    reduction kernels ("arbitrary") and is independent otherwise."""
    if interpret:
        return None
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.CompilerParams(
        dimension_semantics=("parallel",
                             "arbitrary" if reduce_m else "parallel"))


def _row_spec(m_blk, c_blk):
    return pl.BlockSpec((m_blk, c_blk), lambda c, t: (t, c))


def _chan_spec(c_blk):
    return pl.BlockSpec((1, c_blk), lambda c, t: (0, c))


def _stats(x2, c_blk, interpret):
    m, c = x2.shape
    m_blk = _m_for(m, c_blk, 1)
    out = jax.ShapeDtypeStruct((1, c), jnp.float32)
    sums, sqs = pl.pallas_call(
        _stats_kernel,
        grid=(c // c_blk, m // m_blk),
        in_specs=[_row_spec(m_blk, c_blk)],
        out_specs=[_chan_spec(c_blk), _chan_spec(c_blk)],
        out_shape=[out, out],
        scratch_shapes=[_vmem((1, c_blk), jnp.float32),
                        _vmem((1, c_blk), jnp.float32)],
        compiler_params=_params(interpret, reduce_m=True),
        interpret=interpret,
    )(x2)
    return sums, sqs


def _apply(x2, mean, var, gamma, beta, res2, c_blk, eps, relu,
           interpret):
    m, c = x2.shape
    residual = res2 is not None
    m_blk = _m_for(m, c_blk, 3 if residual else 2)
    args = [x2, mean, var, gamma, beta] + ([res2] if residual else [])
    return pl.pallas_call(
        functools.partial(_apply_kernel, eps=eps, relu=relu,
                          residual=residual),
        grid=(c // c_blk, m // m_blk),
        in_specs=[_row_spec(m_blk, c_blk)] + [_chan_spec(c_blk)] * 4
        + ([_row_spec(m_blk, c_blk)] if residual else []),
        out_specs=_row_spec(m_blk, c_blk),
        out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
        compiler_params=_params(interpret, reduce_m=False),
        interpret=interpret,
    )(*args)


def _bwd_reductions(x2, dy2, mean, var, gamma, beta, res2, c_blk,
                    eps, relu, interpret):
    m, c = x2.shape
    residual = res2 is not None
    m_blk = _m_for(m, c_blk, 3 if residual else 2)
    args = [x2, dy2, mean, var, gamma, beta] + (
        [res2] if residual else [])
    out = jax.ShapeDtypeStruct((1, c), jnp.float32)
    db, dg = pl.pallas_call(
        functools.partial(_bwd_red_kernel, eps=eps, relu=relu,
                          residual=residual),
        grid=(c // c_blk, m // m_blk),
        in_specs=[_row_spec(m_blk, c_blk)] * 2 + [_chan_spec(c_blk)] * 4
        + ([_row_spec(m_blk, c_blk)] if residual else []),
        out_specs=[_chan_spec(c_blk), _chan_spec(c_blk)],
        out_shape=[out, out],
        scratch_shapes=[_vmem((1, c_blk), jnp.float32),
                        _vmem((1, c_blk), jnp.float32)],
        compiler_params=_params(interpret, reduce_m=True),
        interpret=interpret,
    )(*args)
    return db, dg


def _bwd_dx(x2, dy2, mean, var, gamma, beta, db, dg, res2, c_blk,
            eps, relu, total_m, interpret):
    m, c = x2.shape
    residual = res2 is not None
    m_blk = _m_for(m, c_blk, 5 if residual else 3)
    args = [x2, dy2, mean, var, gamma, beta, db, dg] + (
        [res2] if residual else [])
    outs = [jax.ShapeDtypeStruct((m, c), x2.dtype)]
    if residual:
        outs.append(jax.ShapeDtypeStruct((m, c), res2.dtype))
    out_specs = [_row_spec(m_blk, c_blk)] * len(outs)
    res = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, eps=eps, relu=relu,
                          residual=residual, inv_m=1.0 / total_m),
        grid=(c // c_blk, m // m_blk),
        in_specs=[_row_spec(m_blk, c_blk)] * 2 + [_chan_spec(c_blk)] * 6
        + ([_row_spec(m_blk, c_blk)] if residual else []),
        out_specs=out_specs if residual else out_specs[0],
        out_shape=outs if residual else outs[0],
        compiler_params=_params(interpret, reduce_m=False),
        interpret=interpret,
    )(*args)
    return res if residual else (res, None)


# ---------------------------------------------------------------------------
# custom-vjp op
# ---------------------------------------------------------------------------


def _tile_cols(vec_c, fold, cv):
    """[C] per-channel vector -> [1, fold*C] row matching the folded
    view's column order (column j holds channel j % C)."""
    return jnp.tile(vec_c.reshape(-1), fold).reshape(1, cv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _bn_act_apply(x2, gamma, beta, res2, mean, var, eps, relu, plan):
    """Normalize+affine(+add)(+relu) with the full fused BN backward.

    Operates on the (M/fold, fold*C) view; every per-channel vector
    arrives pre-tiled to the view's columns.  ``mean``/``var`` arrive
    stop-gradiented: their x-dependence is already inside the backward
    formula (the standard BN dx), so the stats pass itself never needs
    differentiating.
    """
    fold, c_blk = plan
    return _apply(x2, mean, var, gamma, beta, res2, c_blk, eps,
                  relu, not _on_tpu())


def _bn_act_apply_fwd(x2, gamma, beta, res2, mean, var, eps, relu,
                      plan):
    y = _bn_act_apply(x2, gamma, beta, res2, mean, var, eps, relu,
                      plan)
    return y, (x2, gamma, beta, res2, mean, var)


def _bn_act_apply_bwd(eps, relu, plan, saved, dy):
    x2, gamma, beta, res2, mean, var = saved
    fold, c_blk = plan
    interpret = not _on_tpu()
    mv, cv = x2.shape
    c = cv // fold
    # Raw per-view-column sums: exactly the cotangents of the TILED
    # gamma/beta rows (jnp.tile's transpose outside folds them to [C]).
    db_v, dg_v = _bwd_reductions(x2, dy, mean, var, gamma, beta, res2,
                                 c_blk, eps, relu, interpret)
    if fold > 1:
        db_t = _tile_cols(db_v.reshape(fold, c).sum(0), fold, cv)
        dg_t = _tile_cols(dg_v.reshape(fold, c).sum(0), fold, cv)
    else:
        db_t, dg_t = db_v, dg_v
    dx, dres = _bwd_dx(x2, dy, mean, var, gamma, beta, db_t, dg_t,
                       res2, c_blk, eps, relu, mv * fold,
                       interpret)
    return (dx, dg_v.astype(gamma.dtype), db_v.astype(beta.dtype),
            dres, jnp.zeros_like(mean), jnp.zeros_like(var))


_bn_act_apply.defvjp(_bn_act_apply_fwd, _bn_act_apply_bwd)


def batch_norm_act(x, gamma, beta, residual=None, *, eps: float = 1e-5,
                   relu: bool = True):
    """Fused train-mode BN (+residual add) (+ReLU) over the last axis.

    Returns ``(y, mean, var)``; mean/var are f32 batch statistics for
    the running-stats update and are NOT differentiated through (their
    effect on dx is already inside the fused backward -- they are
    stop-gradient side outputs, exactly flax's running-stats usage).
    Returns None when the shape doesn't tile -- caller falls back to
    the XLA path.
    """
    c = x.shape[-1]
    m = x.size // c
    plan = _plan(m, c)
    if plan is None:
        return None
    fold, c_blk = plan
    mv, cv = m // fold, c * fold
    x2 = x.reshape(mv, cv)  # row-major: free view
    res2 = None if residual is None else residual.reshape(mv, cv)
    interpret = not _on_tpu()
    # stop_gradient BEFORE the stats kernel: its x-dependence is folded
    # into the fused backward's dx formula, so the pallas_call itself
    # must never be traced for autodiff.
    sums, sqs = _stats(jax.lax.stop_gradient(x2), c_blk, interpret)
    s = sums.reshape(fold, c).sum(0)
    q = sqs.reshape(fold, c).sum(0)
    mean = jax.lax.stop_gradient(s / m)
    var = jax.lax.stop_gradient(
        jnp.maximum(q / m - jnp.square(s / m), 0.0))
    g = gamma.astype(jnp.float32)
    b = beta.astype(jnp.float32)
    y = _bn_act_apply(x2, _tile_cols(g, fold, cv),
                      _tile_cols(b, fold, cv), res2,
                      _tile_cols(mean, fold, cv),
                      _tile_cols(var, fold, cv), eps, relu, plan)
    return (y.reshape(x.shape), mean, var)
