"""Pallas TPU kernels for the hot ops.

The reference keeps its hot device code in hand-written CUDA
(``horovod/common/ops/cuda/cuda_kernels.cu`` — batched fusion memcpy,
fused scale+sum).  The TPU-native equivalents live here as Pallas
kernels (SURVEY.md §7 phase 7):

* ``flash_attention`` — fused blocked attention with online softmax:
  scores never materialize in HBM (O(seq) memory instead of O(seq²)),
  K/V stream through VMEM block by block, matmuls hit the MXU at
  (block_q × block_k) tiles.  This is the hot op of the transformer
  family; the sequence-parallel ring attention composes with it (ring
  moves KV between chips, this kernel computes each local block).
* ``fused_scale_sum`` — the reference's fused prescale+sum kernel
  (``ScaleAdd`` in cuda_kernels.cu): one VPU pass over fused gradient
  buffers instead of two HBM round trips.

Both run compiled on TPU and fall back to the interpreter off-TPU, so
the CPU test world exercises the same kernel code path.
"""

from __future__ import annotations

import functools
import logging
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..common import jax_compat  # noqa: F401 - installs jax.typeof shim

LOG = logging.getLogger("horovod_tpu")

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------

def _flash_attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr,
                       l_scr, acc_scr, *, block_q: int, block_k: int,
                       causal: bool):
    # grid = (bh, nq, nk): K/V stream through VMEM one block per inner
    # step (double-buffered by the Pallas pipeline); the online-softmax
    # state (m, l, acc) persists in VMEM scratch across the inner axis.
    j = pl.program_id(1)
    t = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(t == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: blocks entirely above the diagonal contribute nothing
    block_live = jnp.logical_or(
        jnp.logical_not(causal),
        t * block_k <= j * block_q + block_q - 1)

    @pl.when(block_live)
    def _update():
        # matmuls stay in the input dtype (bf16 hits the MXU at full
        # rate; accumulation is f32 via preferred_element_type)
        # q arrives PRE-SCALED by 1/sqrt(d) (one cheap (BH,S,D) pass
        # outside the kernel) — a per-block (BQ,BK) scale multiply
        # here would cost ~16x more VPU work over the whole grid.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (BQ, BK)
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = t * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)

    @pl.when(t == nk - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)
        # log-sum-exp per row: the backward recomputes softmax as
        # exp(s - lse) without a second online pass.
        lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l_scr[:], 1e-30))


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting ``like``'s varying-manual-axes so
    pallas_call outputs type-check inside ``check_vma=True`` shard_maps
    (per-shard kernel outputs vary exactly like their inputs)."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # older jax without the vma kwarg
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_attention_fwd_flat(q, k, v, *, causal: bool, block_q: int,
                              block_k: int, interpret: bool):
    """(BH, S, D) → ((BH, S, D) output, (BH, S, 1) lse), D lane-padded."""
    from jax.experimental.pallas import tpu as pltpu
    bh, seq, d = q.shape
    grid = (bh, seq // block_q, seq // block_k)
    kernel = functools.partial(
        _flash_attn_kernel, block_q=block_q, block_k=block_k,
        causal=causal)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, t, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            # unit lane dim keeps the (sublane, lane) tiling legal and
            # broadcasts against (block_q, block_k) scores directly
            pl.BlockSpec((1, block_q, 1), lambda i, j, t: (i, j, 0)),
        ],
        out_shape=[
            _sds((bh, seq, d), q.dtype, q),
            _sds((bh, seq, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _reference_attention(q, k, v, causal: bool):
    """Plain attention on (B, S, H, D): the single oracle shared with
    the model's non-TPU path and the SP tests."""
    from ..parallel.ring_attention import local_attention
    return local_attention(q, k, v, causal=causal)


def _chunked_attention_bwd(q, k, v, g, causal: bool, block_q: int):
    """Memory-efficient attention backward: iterate q blocks, so peak
    extra memory is O(block_q·seq) per (batch,head) instead of the
    O(seq²) score matrix (the standard flash-attention backward
    recurrence, expressed in XLA ops)."""
    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)   # (B,H,S,D)
    kf = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vf = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    gf = jnp.swapaxes(g, 1, 2).astype(jnp.float32)
    nq = s // block_q

    def step(carry, i):
        dk, dv = carry
        start = i * block_q
        q_blk = jax.lax.dynamic_slice_in_dim(qf, start, block_q, 2)
        g_blk = jax.lax.dynamic_slice_in_dim(gf, start, block_q, 2)
        s_blk = jnp.einsum("bhqd,bhkd->bhqk", q_blk, kf) * scale
        if causal:
            rows = start + jnp.arange(block_q)[:, None]
            cols = jnp.arange(s)[None, :]
            s_blk = jnp.where(cols <= rows, s_blk, _NEG_INF)
        p = jax.nn.softmax(s_blk, axis=-1)             # (B,H,BQ,S)
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", p, g_blk)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g_blk, vf)
        ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        dq_blk = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", ds, q_blk) * scale
        return (dk, dv), dq_blk

    (dk, dv), dq_blocks = jax.lax.scan(
        step, (jnp.zeros_like(kf), jnp.zeros_like(vf)),
        jnp.arange(nq))
    dq = jnp.moveaxis(dq_blocks, 0, 2).reshape(b, h, s, d)
    to_out = lambda x, like: jnp.swapaxes(x, 1, 2).astype(like.dtype)
    return to_out(dq, q), to_out(dk, k), to_out(dv, v)


# (seq, d_pad) -> (block_q, block_k) pinned by autotune_flash_blocks:
# the measured winner of the on-device block sweep.  Env overrides
# still win (an explicit A/B must never be silently retuned); the
# default chains below are only the cold fallback.
_TUNED_BLOCKS: dict = {}

_BLOCK_Q_DEFAULTS = (512, 256, 128, 64)
_BLOCK_K_DEFAULTS = (1024, 512, 256, 128, 64)


def export_tuned_blocks() -> dict:
    """The pinned-block registry as a JSON-safe dict
    (``"<seq>x<d_pad>" -> [block_q, block_k]``) — the flash-block leg
    of the persistent plan cache (``utils/plancache.py``), so kernel
    and collective plans persist in one plane."""
    return {"%dx%d" % key: [int(bq), int(bk)]
            for key, (bq, bk) in _TUNED_BLOCKS.items()}


def seed_tuned_blocks(blocks: dict):
    """Seed the registry from a persisted plan (``hvd.init()`` warm
    start).  Entries a live ``autotune_flash_blocks`` sweep pins later
    overwrite these; env block overrides are handled by the CALLER
    (they win and suppress seeding, the r9 precedence rule) and by
    ``_plan`` itself at trace time.  Malformed entries are skipped
    loudly — a corrupt plan must never pin an invalid block shape."""
    for key, pair in (blocks or {}).items():
        try:
            s, d_pad = (int(v) for v in str(key).split("x"))
            bq, bk = int(pair[0]), int(pair[1])
            if min(bq, bk) < 64 or bq % 16 or bk % 16 or s % bq or s % bk:
                raise ValueError("invalid block pair")
            _TUNED_BLOCKS[(s, d_pad)] = (bq, bk)
        except (ValueError, TypeError, IndexError):
            LOG.warning("ignoring malformed tuned-block entry %r: %r",
                        key, pair)


def _d_pad(d: int) -> int:
    return max(128, ((d + 127) // 128) * 128)


def _plan(s: int, d: int):
    """Block plan shared by fwd and bwd.  Large tiles amortize
    per-grid-step overhead; MXU tiles are 128-aligned so any divisor
    ≥64 works.  The head dim is lane-padded to 128 (zero columns add 0
    to every dot product).  Precedence: HVD_TPU_FLASH_BLOCK_Q/K env
    overrides (must divide the sequence length) > blocks pinned by
    ``autotune_flash_blocks`` (the measured sweep) > the default
    chains."""
    import os

    def _env_block(name, tuned, dflt_chain):
        v = os.environ.get(name)
        if v:
            # Fail loudly, like HVD_TPU_FLASH_BWD below: a silently
            # ignored override would mislabel an A/B comparison.
            try:
                b = int(v)
            except ValueError:
                raise ValueError("%s=%r is not an integer" % (name, v))
            if b < 64 or b % 16 or s % b:
                raise ValueError(
                    "%s=%d invalid: blocks must be >=64, sublane-"
                    "aligned (multiple of 16), and divide the "
                    "sequence length %d" % (name, b, s))
            return b
        if tuned is not None:
            return tuned
        return next((b for b in dflt_chain if s % b == 0), None)

    d_pad = _d_pad(d)
    tuned = _TUNED_BLOCKS.get((s, d_pad))
    block_q = _env_block("HVD_TPU_FLASH_BLOCK_Q",
                         tuned[0] if tuned else None, _BLOCK_Q_DEFAULTS)
    block_k = _env_block("HVD_TPU_FLASH_BLOCK_K",
                         tuned[1] if tuned else None, _BLOCK_K_DEFAULTS)
    # The FULL attention scale folds into one pre-multiply of q (the
    # kernels do no scaling at all): one (BH,S,D) pass replaces a
    # (BQ,BK) pass per grid block (~16x more elements at seq 2048,
    # d 128) in the fwd and both bwd kernels.  Padding needs no
    # correction precisely because the kernels don't scale.
    pre_scale = 1.0 / math.sqrt(d)
    return block_q, block_k, d_pad, pre_scale


def _to_flat(x, d_pad):
    b, s, h, d = x.shape
    x = jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)
    if d_pad != d:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, d_pad - d)))
    return x


def _from_flat(x, b, h, d, like):
    s = x.shape[1]
    x = x[:, :, :d].reshape(b, h, s, d)
    return jnp.swapaxes(x, 1, 2).astype(like.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_attention(q, k, v, causal):
    return _flash_attention_impl(q, k, v, causal)


def _flash_attention_impl(q, k, v, causal):
    return _flash_fwd(q, k, v, causal)[0]


def _flash_fwd(q, k, v, causal):
    b, s, h, d = q.shape
    block_q, block_k, d_pad, pre_scale = _plan(s, d)
    if block_q is None or block_k is None:
        out = _reference_attention(q, k, v, causal)
        return out, (q, k, v, None, None)
    out, lse = _flash_attention_fwd_flat(
        _to_flat(q * pre_scale, d_pad), _to_flat(k, d_pad),
        _to_flat(v, d_pad), causal=causal, block_q=block_q,
        block_k=block_k, interpret=not _on_tpu())
    out = out[:, :, :d].reshape(b, h, s, d)
    out = jnp.swapaxes(out, 1, 2)
    return out, (q, k, v, out, lse)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, block_q: int, block_k: int,
                         causal: bool):
    # grid = (bh, nq, nk): K/V stream along the inner axis while this
    # q block's dq accumulates in VMEM scratch (mirror of the fwd).
    j = pl.program_id(1)
    t = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(t == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    block_live = jnp.logical_or(
        jnp.logical_not(causal),
        t * block_k <= j * block_q + block_q - 1)

    @pl.when(block_live)
    def _update():
        # q pre-scaled by 1/sqrt(d): s needs no per-block multiply.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        # softmax from saved stats: p = exp(s - lse)
        p = jnp.exp(s - lse_ref[0])
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = t * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dp = jax.lax.dot_general(
            g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        # ds carries NO scale: the caller folds 1/sqrt(d) into the
        # final (BH,S,D) dq multiply — one pass instead of one per
        # (BQ,BK) block.
        ds = p * (dp - delta_ref[0])
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, D)

    @pl.when(t == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          block_q: int, block_k: int, causal: bool):
    # grid = (bh, nk, nq): Q/G stream along the inner axis while this
    # k block's dk/dv accumulate in VMEM scratch.
    t = pl.program_id(1)
    j = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    block_live = jnp.logical_or(
        jnp.logical_not(causal),
        j * block_q + block_q - 1 >= t * block_k)

    @pl.when(block_live)
    def _update():
        # q pre-scaled by 1/sqrt(d): s needs no per-block multiply.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        p = jnp.exp(s - lse_ref[0])
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = t * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(g_ref.dtype), g_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BK, D)
        dp = jax.lax.dot_general(
            g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        # ds @ q_prescaled == scale * (ds_raw @ q): with q carrying
        # 1/sqrt(d), dk needs NO scale anywhere.
        ds = p * (dp - delta_ref[0])
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BK, D)

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_attention_bwd_flat(q, k, v, g, lse, delta, *, causal: bool,
                              block_q: int, block_k: int,
                              interpret: bool):
    """Flat (BH, S, D) backward via the two Pallas kernels above;
    returns (dq, dk, dv) with dq still in the fwd's q scaling."""
    from jax.experimental.pallas import tpu as pltpu
    bh, seq, d = q.shape
    qspec = pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, t, 0))
    rowspec = pl.BlockSpec((1, block_q, 1), lambda i, j, t: (i, j, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(bh, seq // block_q, seq // block_k),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda i, j, t: (i, j, 0)),
        out_shape=_sds((bh, seq, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # dkv grid: (bh, k block, q block) — inner axis streams q.
    qspec2 = pl.BlockSpec((1, block_q, d), lambda i, t, j: (i, j, 0))
    kspec2 = pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0))
    rowspec2 = pl.BlockSpec((1, block_q, 1), lambda i, t, j: (i, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(bh, seq // block_k, seq // block_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0)),
        ],
        out_shape=[
            _sds((bh, seq, d), k.dtype, k),
            _sds((bh, seq, d), v.dtype, v),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _flash_bwd_onepass_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref,
                              delta_ref, dqp_ref, dk_ref, dv_ref,
                              dk_scr, dv_scr, *, block_q: int,
                              block_k: int, causal: bool):
    # grid = (bh, nk, nq): ONE kernel for dq/dk/dv.  Q/G stream along
    # the inner axis while this k block's dk/dv accumulate in VMEM
    # scratch (as in the two-pass dkv kernel); the dq contribution of
    # each (k block, q block) tile is emitted as an f32 PARTIAL block
    # (indexed by the k-block axis) and reduced outside the kernel.
    # Trade measured on hardware, not assumed: Q/K/V/G are each read
    # from HBM once per tile pair instead of twice (the two-pass cost),
    # against nk x extra dq-partial HBM writes + one cheap XLA sum.
    t = pl.program_id(1)
    j = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    block_live = jnp.logical_or(
        jnp.logical_not(causal),
        j * block_q + block_q - 1 >= t * block_k)

    @pl.when(block_live)
    def _update():
        # q pre-scaled by 1/sqrt(d): s needs no per-block multiply.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        p = jnp.exp(s - lse_ref[0])
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = t * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(g_ref.dtype), g_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BK, D)
        dp = jax.lax.dot_general(
            g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        ds = p * (dp - delta_ref[0])
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BK, D)
        dqp_ref[0, 0] = jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, D)

    @pl.when(jnp.logical_not(block_live))
    def _dead():
        # Causal-dead tiles still own an output block in the partial
        # array: write zeros or the sum reads uninitialized memory.
        dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_attention_bwd_onepass_flat(q, k, v, g, lse, delta, *,
                                      causal: bool, block_q: int,
                                      block_k: int, interpret: bool):
    """Flat (BH, S, D) backward via the single one-pass kernel above;
    returns (dq_f32, dk, dv) with dq still in the fwd's q scaling (the
    nk partial blocks are summed here, one cheap XLA reduce)."""
    from jax.experimental.pallas import tpu as pltpu
    bh, seq, d = q.shape
    nk = seq // block_k
    qspec = pl.BlockSpec((1, block_q, d), lambda i, t, j: (i, j, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0))
    rowspec = pl.BlockSpec((1, block_q, 1), lambda i, t, j: (i, j, 0))
    dqp, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_onepass_kernel, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(bh, nk, seq // block_q),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda i, t, j: (i, t, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0)),
        ],
        out_shape=[
            _sds((bh, nk, seq, d), jnp.float32, q),
            _sds((bh, seq, d), k.dtype, k),
            _sds((bh, seq, d), v.dtype, v),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return jnp.sum(dqp, axis=1), dk, dv


def _flash_bwd_chunked(causal, res, g):
    q, k, v = res
    b, s, h, _ = q.shape
    # bigger blocks = fewer scan steps (measured 23% faster at 2048 vs
    # 512 for seq 4096 on one chip).  Peak extra memory per step is ~3
    # concurrent (b,h,block,s) f32 score-shaped temporaries (p, dp,
    # ds); cap that at ~4 GB (a quarter of a 16 GB-HBM chip) when
    # choosing the block.
    budget = 4 << 30
    per_block_row = 3 * b * h * s * 4
    cap = max(64, budget // max(1, per_block_row))
    block = next((bq for bq in (2048, 1024, 512, 256, 128, 64)
                  if bq <= cap and s % bq == 0), None)
    if block is None:  # irregular/large: direct vjp on the reference
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _reference_attention(q_, k_, v_, causal),
            q, k, v)
        return vjp(g)
    return _chunked_attention_bwd(q, k, v, g, causal, block)


def _flash_bwd(causal, res, g):
    q, k, v, o, lse = res
    if lse is None:  # fwd fell back to plain XLA attention
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _reference_attention(q_, k_, v_, causal),
            q, k, v)
        return vjp(g)
    import os
    # Read at TRACE time: under jit the choice is baked into the
    # compiled function — set before the first train step, not between
    # steps.  Unknown values fail loudly so a typo can't silently
    # invalidate an A/B comparison.
    choice = os.environ.get("HVD_TPU_FLASH_BWD", "pallas")
    if choice not in ("pallas", "pallas_onepass", "chunked"):
        raise ValueError(
            "HVD_TPU_FLASH_BWD must be 'pallas', 'pallas_onepass' or "
            "'chunked', got %r" % choice)
    if choice == "chunked":
        # A/B escape hatch (docs/benchmarks.md records the comparison).
        return _flash_bwd_chunked(causal, (q, k, v), g)
    b, s, h, d = q.shape
    block_q, block_k, d_pad, pre_scale = _plan(s, d)
    # delta = rowsum(g ⊙ o): the softmax-jacobian correction term,
    # cheap in XLA (one elementwise pass).  Unit lane dim to match the
    # lse layout.
    delta = jnp.sum(jnp.swapaxes(g, 1, 2).astype(jnp.float32)
                    * jnp.swapaxes(o, 1, 2).astype(jnp.float32),
                    axis=-1).reshape(b * h, s, 1)
    bwd_flat = (_flash_attention_bwd_onepass_flat
                if choice == "pallas_onepass"
                else _flash_attention_bwd_flat)
    dq, dk, dv = bwd_flat(
        _to_flat(q * pre_scale, d_pad), _to_flat(k, d_pad),
        _to_flat(v, d_pad), _to_flat(g, d_pad), lse, delta,
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=not _on_tpu())
    # The kernels differentiate w.r.t. the PRE-SCALED q, so
    # d(loss)/d(q) = dq_flat * pre_scale; dk comes out exact with no
    # correction (ds^T @ q_prescaled == scale * ds_raw^T @ q).  The
    # scale multiply runs in f32 BEFORE the final dtype cast so dq
    # picks up one rounding, not two.
    return (_from_flat(dq.astype(jnp.float32) * pre_scale, b, h, d, q),
            _from_flat(dk, b, h, d, k),
            _from_flat(dv, b, h, d, v))


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = True):
    """Fused blocked attention, layout ``(batch, seq, heads, dim)``
    (the framework's attention layout).  Differentiable; compiled
    Pallas on TPU, interpreted elsewhere.  Sequences not divisible by
    64 fall back to plain XLA attention.  GQA (kv_heads < heads) is
    handled by repeating KV head groups."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return _flash_attention(q, k, v, causal)


# ---------------------------------------------------------------------------
# flash block autotune (the kernel-parameter leg of the autotune plane)
# ---------------------------------------------------------------------------

def flash_plan_info(s: int, d: int) -> dict:
    """Attribution record for the benchmark JSON: which blocks the plan
    would pick for (seq, head_dim) and WHY (env override, autotuned
    pin, or default chain), plus the active backward variant.  Pure
    metadata — never traces or compiles anything."""
    import os
    block_q, block_k, d_pad, _ = _plan(s, d)
    if os.environ.get("HVD_TPU_FLASH_BLOCK_Q") or \
            os.environ.get("HVD_TPU_FLASH_BLOCK_K"):
        source = "env"
    elif (s, d_pad) in _TUNED_BLOCKS:
        source = "autotuned"
    elif block_q is None or block_k is None:
        source = "fallback_xla"
    else:
        source = "default"
    return {"block_q": block_q, "block_k": block_k, "d_pad": d_pad,
            "source": source,
            "bwd": os.environ.get("HVD_TPU_FLASH_BWD", "pallas")}


def flash_block_candidates(seq: int, d: int,
                           vmem_budget_bytes: int = 12 << 20):
    """(block_q, block_k) sweep grid for one (seq, head_dim) shape:
    every sublane-aligned pair dividing the sequence whose resident
    f32 working set (scores + dq/dk/dv accumulators + double-buffered
    in/out blocks) fits the VMEM budget (~16 MB/core minus headroom)."""
    d_pad = _d_pad(d)
    out = []
    for bq in (64, 128, 256, 512, 1024):
        if seq % bq:
            continue
        for bk in (64, 128, 256, 512, 1024, 2048):
            if seq % bk:
                continue
            est = (4 * (2 * bq * bk + bq * d_pad + 2 * bk * d_pad)
                   + 2 * 2 * (bq + bk) * d_pad)
            if est <= vmem_budget_bytes:
                out.append((bq, bk))
    return out


def _time_device(fn, args, iters: int) -> float:
    """Per-call seconds via differential timing (2N − N dispatch loops
    around one scalar-fetch barrier — the bench.py discipline; on the
    tunnel runtime block_until_ready alone is not a reliable
    completion barrier)."""
    import time

    def first_leaf(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        return leaves[0]

    fetch = jax.jit(lambda v: v.reshape(-1)[0].astype(jnp.float32))

    def run(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn(*args)
        float(np.asarray(fetch(first_leaf(out))))
        return time.perf_counter() - t0

    run(max(1, iters // 2))  # warm (compile + dispatch path)
    t1, t2 = run(iters), run(2 * iters)
    return max(t2 - t1, 1e-9) / iters


def autotune_flash_blocks(seq: int, d: int, *, batch_heads: int = 8,
                          dtype=None, causal: bool = True,
                          iters: int = 4, candidates=None,
                          include_bwd: bool = True,
                          allreduce_scores=None, report_core=True,
                          pin: bool = True):
    """Measure fwd(+bwd) TFLOP/s for each (block_q, block_k) candidate
    on the local device and PIN the winner into the plan registry, so
    the blocks the kernels run with are tuned, not hardcoded (the
    kernel-parameter leg of the autotune plane; fusion/cycle stay with
    the GP tuner).

    SPMD safety: every rank must compile the SAME kernel.  Pass
    ``allreduce_scores`` (e.g. ``lambda v: hvd.allreduce(v, op=Average)``)
    to average the per-candidate scores across ranks before the argmax
    — a deterministic reduction of identical-length vectors, so every
    rank pins the same pair.  Scores are also reported to the native
    core's KernelTuner (``hvd_tcp_kernel_tune_record``) when the TCP
    control plane is up, for cross-run observability.

    Returns the attribution dict: candidates, per-candidate TFLOP/s,
    the winner, and whether an env override suppressed pinning.
    """
    import os

    from ..utils.autotune import KernelBlockTuner

    dtype = dtype or jnp.bfloat16
    d_pad = _d_pad(d)
    cands = list(candidates or flash_block_candidates(seq, d))
    if not cands:
        return {"candidates": [], "best": None, "pinned": False}
    interp = not _on_tpu()
    bh = int(batch_heads)
    rng = np.random.RandomState(0)
    # Random payloads: the tunnel runtime dedups value-identical
    # executions, which would time cache hits instead of kernels.
    q = jnp.asarray(rng.randn(bh, seq, d_pad), dtype)
    k = jnp.asarray(rng.randn(bh, seq, d_pad), dtype)
    v = jnp.asarray(rng.randn(bh, seq, d_pad), dtype)
    g = jnp.asarray(rng.randn(bh, seq, d_pad), dtype)
    # Causal attention touches half the tiles; 2 matmuls fwd, 5 bwd.
    tile_frac = 0.5 if causal else 1.0
    fwd_flops = 4.0 * bh * seq * seq * d_pad * tile_frac
    bwd_flops = 2.5 * fwd_flops

    tuner = KernelBlockTuner(cands)
    records = {}
    for idx, (bq, bk) in enumerate(cands):
        fwd = jax.jit(functools.partial(
            _flash_attention_fwd_flat, causal=causal, block_q=bq,
            block_k=bk, interpret=interp))
        t_fwd = _time_device(fwd, (q, k, v), iters)
        total_t, total_f = t_fwd, fwd_flops
        t_bwd = None
        if include_bwd:
            out, lse = fwd(q, k, v)
            delta = jnp.sum(g.astype(jnp.float32)
                            * out.astype(jnp.float32),
                            axis=-1, keepdims=True)
            bwd = jax.jit(functools.partial(
                _flash_attention_bwd_flat, causal=causal, block_q=bq,
                block_k=bk, interpret=interp))
            t_bwd = _time_device(bwd, (q, k, v, g, lse, delta), iters)
            total_t += t_bwd
            total_f += bwd_flops
        score = total_f / total_t
        tuner.record(idx, score)
        records[(bq, bk)] = {
            "fwd_tflops": fwd_flops / t_fwd / 1e12,
            "bwd_tflops": (bwd_flops / t_bwd / 1e12
                           if t_bwd else None),
            "score_tflops": score / 1e12,
        }

    scores = tuner.scores_vector()
    if allreduce_scores is not None:
        # Cross-rank mean: identical argmax input on every rank.
        scores = np.asarray(allreduce_scores(
            np.asarray(scores, np.float64)))
    if report_core:
        try:
            from ..common import basics
            core = basics._get_tcp_core()
            if core is not None:
                for idx in range(len(cands)):
                    core.kernel_tune_record(idx, float(scores[idx]))
        except Exception:  # noqa: BLE001 - observability only
            pass
    best = cands[int(np.argmax(scores))]
    pinned = False
    if pin and not (os.environ.get("HVD_TPU_FLASH_BLOCK_Q")
                    or os.environ.get("HVD_TPU_FLASH_BLOCK_K")):
        # Env overrides win over the tuner (explicit A/Bs must stay
        # what the operator asked for).
        _TUNED_BLOCKS[(seq, d_pad)] = best
        pinned = True
    return {"candidates": cands, "samples": records, "best": best,
            "pinned": pinned,
            "scores_tflops": [float(x) / 1e12 for x in scores]}


# ---------------------------------------------------------------------------
# fused scale + sum (the reference's ScaleAdd fusion kernel)
# ---------------------------------------------------------------------------

def _scale_sum_kernel(a_ref, b_ref, o_ref, *, alpha: float, beta: float):
    o_ref[:] = (alpha * a_ref[:].astype(jnp.float32) +
                beta * b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def fused_scale_sum(a, b, alpha: float = 1.0, beta: float = 1.0):
    """``alpha*a + beta*b`` in one VPU pass (reference ``ScaleAdd`` in
    ``cuda_kernels.cu``, used for pre/postscaled fusion-buffer math).
    Gridded in ~2MB tiles so fusion buffers far larger than VMEM
    stream through."""
    flat_a = a.reshape(-1)
    flat_b = b.reshape(-1)
    n = flat_a.shape[0]
    lane = 128
    block_rows = 4096                       # 4096×128 f32 = 2 MiB/tile
    rows = (n + lane - 1) // lane
    rows = ((rows + block_rows - 1) // block_rows) * block_rows
    pad = rows * lane - n
    if pad:
        flat_a = jnp.pad(flat_a, (0, pad))
        flat_b = jnp.pad(flat_b, (0, pad))
    kernel = functools.partial(_scale_sum_kernel, alpha=alpha,
                               beta=beta)
    out = pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, lane), lambda i: (i, 0)),
                  pl.BlockSpec((block_rows, lane), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, lane), lambda i: (i, 0)),
        out_shape=_sds((rows, lane), a.dtype, a),
        interpret=not _on_tpu(),
    )(flat_a.reshape(rows, lane), flat_b.reshape(rows, lane))
    return out.reshape(-1)[:n].reshape(a.shape)
