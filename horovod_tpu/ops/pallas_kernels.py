"""Pallas TPU kernels for the hot ops.

The reference keeps its hot device code in hand-written CUDA
(``horovod/common/ops/cuda/cuda_kernels.cu`` — batched fusion memcpy,
fused scale+sum).  The TPU-native equivalents live here as Pallas
kernels (SURVEY.md §7 phase 7):

* ``flash_attention`` — fused blocked attention with online softmax:
  scores never materialize in HBM (O(seq) memory instead of O(seq²)),
  K/V stream through VMEM block by block, matmuls hit the MXU at
  (block_q × block_k) tiles.  This is the hot op of the transformer
  family; the sequence-parallel ring attention composes with it (ring
  moves KV between chips, this kernel computes each local block).
* ``fused_scale_sum`` — the reference's fused prescale+sum kernel
  (``ScaleAdd`` in cuda_kernels.cu): one VPU pass over fused gradient
  buffers instead of two HBM round trips.

Both run compiled on TPU and fall back to the interpreter off-TPU, so
the CPU test world exercises the same kernel code path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..common import jax_compat  # noqa: F401 - installs jax.typeof shim

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------

def _flash_attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr,
                       l_scr, acc_scr, *, block_q: int, block_k: int,
                       causal: bool):
    # grid = (bh, nq, nk): K/V stream through VMEM one block per inner
    # step (double-buffered by the Pallas pipeline); the online-softmax
    # state (m, l, acc) persists in VMEM scratch across the inner axis.
    j = pl.program_id(1)
    t = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(t == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: blocks entirely above the diagonal contribute nothing
    block_live = jnp.logical_or(
        jnp.logical_not(causal),
        t * block_k <= j * block_q + block_q - 1)

    @pl.when(block_live)
    def _update():
        # matmuls stay in the input dtype (bf16 hits the MXU at full
        # rate; accumulation is f32 via preferred_element_type)
        # q arrives PRE-SCALED by 1/sqrt(d) (one cheap (BH,S,D) pass
        # outside the kernel) — a per-block (BQ,BK) scale multiply
        # here would cost ~16x more VPU work over the whole grid.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (BQ, BK)
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = t * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)

    @pl.when(t == nk - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)
        # log-sum-exp per row: the backward recomputes softmax as
        # exp(s - lse) without a second online pass.
        lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l_scr[:], 1e-30))


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting ``like``'s varying-manual-axes so
    pallas_call outputs type-check inside ``check_vma=True`` shard_maps
    (per-shard kernel outputs vary exactly like their inputs)."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # older jax without the vma kwarg
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_attention_fwd_flat(q, k, v, *, causal: bool, block_q: int,
                              block_k: int, interpret: bool):
    """(BH, S, D) → ((BH, S, D) output, (BH, S, 1) lse), D lane-padded."""
    from jax.experimental.pallas import tpu as pltpu
    bh, seq, d = q.shape
    grid = (bh, seq // block_q, seq // block_k)
    kernel = functools.partial(
        _flash_attn_kernel, block_q=block_q, block_k=block_k,
        causal=causal)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, t, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0)),
            # unit lane dim keeps the (sublane, lane) tiling legal and
            # broadcasts against (block_q, block_k) scores directly
            pl.BlockSpec((1, block_q, 1), lambda i, j, t: (i, j, 0)),
        ],
        out_shape=[
            _sds((bh, seq, d), q.dtype, q),
            _sds((bh, seq, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _reference_attention(q, k, v, causal: bool):
    """Plain attention on (B, S, H, D): the single oracle shared with
    the model's non-TPU path and the SP tests."""
    from ..parallel.ring_attention import local_attention
    return local_attention(q, k, v, causal=causal)


def _chunked_attention_bwd(q, k, v, g, causal: bool, block_q: int):
    """Memory-efficient attention backward: iterate q blocks, so peak
    extra memory is O(block_q·seq) per (batch,head) instead of the
    O(seq²) score matrix (the standard flash-attention backward
    recurrence, expressed in XLA ops)."""
    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)   # (B,H,S,D)
    kf = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vf = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    gf = jnp.swapaxes(g, 1, 2).astype(jnp.float32)
    nq = s // block_q

    def step(carry, i):
        dk, dv = carry
        start = i * block_q
        q_blk = jax.lax.dynamic_slice_in_dim(qf, start, block_q, 2)
        g_blk = jax.lax.dynamic_slice_in_dim(gf, start, block_q, 2)
        s_blk = jnp.einsum("bhqd,bhkd->bhqk", q_blk, kf) * scale
        if causal:
            rows = start + jnp.arange(block_q)[:, None]
            cols = jnp.arange(s)[None, :]
            s_blk = jnp.where(cols <= rows, s_blk, _NEG_INF)
        p = jax.nn.softmax(s_blk, axis=-1)             # (B,H,BQ,S)
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", p, g_blk)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g_blk, vf)
        ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        dq_blk = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", ds, q_blk) * scale
        return (dk, dv), dq_blk

    (dk, dv), dq_blocks = jax.lax.scan(
        step, (jnp.zeros_like(kf), jnp.zeros_like(vf)),
        jnp.arange(nq))
    dq = jnp.moveaxis(dq_blocks, 0, 2).reshape(b, h, s, d)
    to_out = lambda x, like: jnp.swapaxes(x, 1, 2).astype(like.dtype)
    return to_out(dq, q), to_out(dk, k), to_out(dv, v)


def _plan(s: int, d: int):
    """Block plan shared by fwd and bwd.  Large tiles amortize
    per-grid-step overhead; MXU tiles are 128-aligned so any divisor
    ≥64 works.  The head dim is lane-padded to 128 (zero columns add 0
    to every dot product).  HVD_TPU_FLASH_BLOCK_Q/K override the
    defaults for A/B tuning (must divide the sequence length)."""
    import os

    def _env_block(name, dflt_chain):
        v = os.environ.get(name)
        if v:
            # Fail loudly, like HVD_TPU_FLASH_BWD below: a silently
            # ignored override would mislabel an A/B comparison.
            try:
                b = int(v)
            except ValueError:
                raise ValueError("%s=%r is not an integer" % (name, v))
            if b < 64 or b % 16 or s % b:
                raise ValueError(
                    "%s=%d invalid: blocks must be >=64, sublane-"
                    "aligned (multiple of 16), and divide the "
                    "sequence length %d" % (name, b, s))
            return b
        return next((b for b in dflt_chain if s % b == 0), None)

    block_q = _env_block("HVD_TPU_FLASH_BLOCK_Q", (512, 256, 128, 64))
    block_k = _env_block("HVD_TPU_FLASH_BLOCK_K",
                         (1024, 512, 256, 128, 64))
    d_pad = max(128, ((d + 127) // 128) * 128)
    # The FULL attention scale folds into one pre-multiply of q (the
    # kernels do no scaling at all): one (BH,S,D) pass replaces a
    # (BQ,BK) pass per grid block (~16x more elements at seq 2048,
    # d 128) in the fwd and both bwd kernels.  Padding needs no
    # correction precisely because the kernels don't scale.
    pre_scale = 1.0 / math.sqrt(d)
    return block_q, block_k, d_pad, pre_scale


def _to_flat(x, d_pad):
    b, s, h, d = x.shape
    x = jnp.swapaxes(x, 1, 2).reshape(b * h, s, d)
    if d_pad != d:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, d_pad - d)))
    return x


def _from_flat(x, b, h, d, like):
    s = x.shape[1]
    x = x[:, :, :d].reshape(b, h, s, d)
    return jnp.swapaxes(x, 1, 2).astype(like.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_attention(q, k, v, causal):
    return _flash_attention_impl(q, k, v, causal)


def _flash_attention_impl(q, k, v, causal):
    return _flash_fwd(q, k, v, causal)[0]


def _flash_fwd(q, k, v, causal):
    b, s, h, d = q.shape
    block_q, block_k, d_pad, pre_scale = _plan(s, d)
    if block_q is None or block_k is None:
        out = _reference_attention(q, k, v, causal)
        return out, (q, k, v, None, None)
    out, lse = _flash_attention_fwd_flat(
        _to_flat(q * pre_scale, d_pad), _to_flat(k, d_pad),
        _to_flat(v, d_pad), causal=causal, block_q=block_q,
        block_k=block_k, interpret=not _on_tpu())
    out = out[:, :, :d].reshape(b, h, s, d)
    out = jnp.swapaxes(out, 1, 2)
    return out, (q, k, v, out, lse)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, block_q: int, block_k: int,
                         causal: bool):
    # grid = (bh, nq, nk): K/V stream along the inner axis while this
    # q block's dq accumulates in VMEM scratch (mirror of the fwd).
    j = pl.program_id(1)
    t = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(t == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    block_live = jnp.logical_or(
        jnp.logical_not(causal),
        t * block_k <= j * block_q + block_q - 1)

    @pl.when(block_live)
    def _update():
        # q pre-scaled by 1/sqrt(d): s needs no per-block multiply.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        # softmax from saved stats: p = exp(s - lse)
        p = jnp.exp(s - lse_ref[0])
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = t * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dp = jax.lax.dot_general(
            g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        # ds carries NO scale: the caller folds 1/sqrt(d) into the
        # final (BH,S,D) dq multiply — one pass instead of one per
        # (BQ,BK) block.
        ds = p * (dp - delta_ref[0])
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, D)

    @pl.when(t == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          block_q: int, block_k: int, causal: bool):
    # grid = (bh, nk, nq): Q/G stream along the inner axis while this
    # k block's dk/dv accumulate in VMEM scratch.
    t = pl.program_id(1)
    j = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    block_live = jnp.logical_or(
        jnp.logical_not(causal),
        j * block_q + block_q - 1 >= t * block_k)

    @pl.when(block_live)
    def _update():
        # q pre-scaled by 1/sqrt(d): s needs no per-block multiply.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        p = jnp.exp(s - lse_ref[0])
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = t * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(cols <= rows, p, 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(g_ref.dtype), g_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BK, D)
        dp = jax.lax.dot_general(
            g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BQ, BK)
        # ds @ q_prescaled == scale * (ds_raw @ q): with q carrying
        # 1/sqrt(d), dk needs NO scale anywhere.
        ds = p * (dp - delta_ref[0])
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (BK, D)

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_attention_bwd_flat(q, k, v, g, lse, delta, *, causal: bool,
                              block_q: int, block_k: int,
                              interpret: bool):
    """Flat (BH, S, D) backward via the two Pallas kernels above;
    returns (dq, dk, dv) with dq still in the fwd's q scaling."""
    from jax.experimental.pallas import tpu as pltpu
    bh, seq, d = q.shape
    qspec = pl.BlockSpec((1, block_q, d), lambda i, j, t: (i, j, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda i, j, t: (i, t, 0))
    rowspec = pl.BlockSpec((1, block_q, 1), lambda i, j, t: (i, j, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(bh, seq // block_q, seq // block_k),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda i, j, t: (i, j, 0)),
        out_shape=_sds((bh, seq, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # dkv grid: (bh, k block, q block) — inner axis streams q.
    qspec2 = pl.BlockSpec((1, block_q, d), lambda i, t, j: (i, j, 0))
    kspec2 = pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0))
    rowspec2 = pl.BlockSpec((1, block_q, 1), lambda i, t, j: (i, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(bh, seq // block_k, seq // block_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, t, j: (i, t, 0)),
        ],
        out_shape=[
            _sds((bh, seq, d), k.dtype, k),
            _sds((bh, seq, d), v.dtype, v),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _flash_bwd_chunked(causal, res, g):
    q, k, v = res
    b, s, h, _ = q.shape
    # bigger blocks = fewer scan steps (measured 23% faster at 2048 vs
    # 512 for seq 4096 on one chip).  Peak extra memory per step is ~3
    # concurrent (b,h,block,s) f32 score-shaped temporaries (p, dp,
    # ds); cap that at ~4 GB (a quarter of a 16 GB-HBM chip) when
    # choosing the block.
    budget = 4 << 30
    per_block_row = 3 * b * h * s * 4
    cap = max(64, budget // max(1, per_block_row))
    block = next((bq for bq in (2048, 1024, 512, 256, 128, 64)
                  if bq <= cap and s % bq == 0), None)
    if block is None:  # irregular/large: direct vjp on the reference
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _reference_attention(q_, k_, v_, causal),
            q, k, v)
        return vjp(g)
    return _chunked_attention_bwd(q, k, v, g, causal, block)


def _flash_bwd(causal, res, g):
    q, k, v, o, lse = res
    if lse is None:  # fwd fell back to plain XLA attention
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _reference_attention(q_, k_, v_, causal),
            q, k, v)
        return vjp(g)
    import os
    # Read at TRACE time: under jit the choice is baked into the
    # compiled function — set before the first train step, not between
    # steps.  Unknown values fail loudly so a typo can't silently
    # invalidate an A/B comparison.
    choice = os.environ.get("HVD_TPU_FLASH_BWD", "pallas")
    if choice not in ("pallas", "chunked"):
        raise ValueError(
            "HVD_TPU_FLASH_BWD must be 'pallas' or 'chunked', got %r"
            % choice)
    if choice == "chunked":
        # A/B escape hatch (docs/benchmarks.md records the comparison).
        return _flash_bwd_chunked(causal, (q, k, v), g)
    b, s, h, d = q.shape
    block_q, block_k, d_pad, pre_scale = _plan(s, d)
    # delta = rowsum(g ⊙ o): the softmax-jacobian correction term,
    # cheap in XLA (one elementwise pass).  Unit lane dim to match the
    # lse layout.
    delta = jnp.sum(jnp.swapaxes(g, 1, 2).astype(jnp.float32)
                    * jnp.swapaxes(o, 1, 2).astype(jnp.float32),
                    axis=-1).reshape(b * h, s, 1)
    dq, dk, dv = _flash_attention_bwd_flat(
        _to_flat(q * pre_scale, d_pad), _to_flat(k, d_pad),
        _to_flat(v, d_pad), _to_flat(g, d_pad), lse, delta,
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=not _on_tpu())
    # The kernels differentiate w.r.t. the PRE-SCALED q, so
    # d(loss)/d(q) = dq_flat * pre_scale; dk comes out exact with no
    # correction (ds^T @ q_prescaled == scale * ds_raw^T @ q).  The
    # scale multiply runs in f32 BEFORE the final dtype cast so dq
    # picks up one rounding, not two.
    return (_from_flat(dq.astype(jnp.float32) * pre_scale, b, h, d, q),
            _from_flat(dk, b, h, d, k),
            _from_flat(dv, b, h, d, v))


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = True):
    """Fused blocked attention, layout ``(batch, seq, heads, dim)``
    (the framework's attention layout).  Differentiable; compiled
    Pallas on TPU, interpreted elsewhere.  Sequences not divisible by
    64 fall back to plain XLA attention.  GQA (kv_heads < heads) is
    handled by repeating KV head groups."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return _flash_attention(q, k, v, causal)


# ---------------------------------------------------------------------------
# fused scale + sum (the reference's ScaleAdd fusion kernel)
# ---------------------------------------------------------------------------

def _scale_sum_kernel(a_ref, b_ref, o_ref, *, alpha: float, beta: float):
    o_ref[:] = (alpha * a_ref[:].astype(jnp.float32) +
                beta * b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def fused_scale_sum(a, b, alpha: float = 1.0, beta: float = 1.0):
    """``alpha*a + beta*b`` in one VPU pass (reference ``ScaleAdd`` in
    ``cuda_kernels.cu``, used for pre/postscaled fusion-buffer math).
    Gridded in ~2MB tiles so fusion buffers far larger than VMEM
    stream through."""
    flat_a = a.reshape(-1)
    flat_b = b.reshape(-1)
    n = flat_a.shape[0]
    lane = 128
    block_rows = 4096                       # 4096×128 f32 = 2 MiB/tile
    rows = (n + lane - 1) // lane
    rows = ((rows + block_rows - 1) // block_rows) * block_rows
    pad = rows * lane - n
    if pad:
        flat_a = jnp.pad(flat_a, (0, pad))
        flat_b = jnp.pad(flat_b, (0, pad))
    kernel = functools.partial(_scale_sum_kernel, alpha=alpha,
                               beta=beta)
    out = pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, lane), lambda i: (i, 0)),
                  pl.BlockSpec((block_rows, lane), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, lane), lambda i: (i, 0)),
        out_shape=_sds((rows, lane), a.dtype, a),
        interpret=not _on_tpu(),
    )(flat_a.reshape(rows, lane), flat_b.reshape(rows, lane))
    return out.reshape(-1)[:n].reshape(a.shape)
