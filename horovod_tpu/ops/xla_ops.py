"""XLA collective executables over a device mesh.

TPU-native replacement for the reference's vendor-collective backends
(``horovod/common/ops/nccl_operations.cc`` / ``mpi_operations.cc`` /
``gloo_operations.cc``): on TPU there is no NCCL-style library call —
collectives are XLA HLO ops (``all-reduce``, ``all-gather``,
``all-to-all``, ``reduce-scatter``, ``collective-permute``) compiled via
PJRT and executed over ICI (within a slice) / DCN (across slices).  This
module builds and caches those tiny compiled executables; the engine
(``horovod_tpu.ops.engine``) feeds them fused buffers.

Eager tensor convention (single-controller SPMD world): a collective input
is "rank-major stacked" — leading axis indexes ranks, i.e. ``x[r]`` is what
rank ``r`` contributes.  The engine shards that axis over the mesh so every
device holds exactly its own contribution, then runs the collective.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common import jax_compat  # noqa: F401 - installs older-jax shims

from .executable_cache import ExecutableCache

AXIS = "hvd"

# Reduction ops (reference: horovod/common/common.h ReduceOp enum).
SUM = "Sum"
AVERAGE = "Average"
MIN = "Min"
MAX = "Max"
PRODUCT = "Product"
ADASUM = "Adasum"

_REDUCE_OPS = (SUM, AVERAGE, MIN, MAX, PRODUCT, ADASUM)


def alltoall_chunk_reduce(x, axis_name: str, size: int, red_op: str):
    """Bytes-proportional Min/Max/Product reduce-scatter (per-shard
    code): ``x`` [size*k, ...] -> this shard's reduced [k, ...] chunk
    via one ``all_to_all`` + a local reduce.  1× payload bytes on the
    wire — the all-gather fallback these ops used moved N× — with
    exact arithmetic (no log/exp decomposition).  An allreduce is this
    plus a tiled all_gather (2× total, the Sum paths' bus bytes)."""
    from jax import lax
    import jax.numpy as jnp
    k = x.shape[0] // size
    blocks = x.reshape((size, k) + x.shape[1:])
    w = lax.all_to_all(blocks, axis_name, split_axis=0, concat_axis=0)
    if red_op == MIN:
        return w.min(axis=0)
    if red_op == MAX:
        return w.max(axis=0)
    if red_op == PRODUCT:
        return jnp.prod(w, axis=0)
    raise NotImplementedError("chunk reduce op %r" % red_op)


def product_allreduce(flat, axis_name: str, size: int):
    """Exact bytes-proportional Product allreduce (per-shard code):
    reduce-scatter chunks via ``alltoall_chunk_reduce``, then a tiled
    all_gather — ~2× payload bytes like the Sum path, instead of the
    N× of all_gather + local product."""
    from jax import lax
    import jax.numpy as jnp
    n = flat.shape[0]
    if n == 0 or size == 1:
        return flat
    c = -(-n // size)
    if size * c > n:
        flat = jnp.concatenate(
            [flat, jnp.ones((size * c - n,), flat.dtype)])
    chunk = alltoall_chunk_reduce(flat, axis_name, size, PRODUCT)
    full = lax.all_gather(chunk, axis_name, tiled=True)
    return full[:n]


def uneven_chunks(total_rows: int, n: int):
    """Reference ReducescatterOp chunk math: earlier members take the
    larger shards (cpu_ops.cc uses the same base/remainder split).
    Shared by the in-process engine and multihost mode so the shard
    boundaries can never desynchronize."""
    base, rem = divmod(total_rows, n)
    rows = [base + (1 if i < rem else 0) for i in range(n)]
    offs = [sum(rows[:i]) for i in range(n)]
    return rows, offs


def handle_average_backwards_compatibility(op, average):
    """Reconcile the legacy ``average=`` kwarg with ``op=`` (reference:
    horovod/common/util.py check_num_rank_power_of_2 /
    handle_average_backwards_compatibility)."""
    if op is not None and average is not None:
        raise ValueError("`average` and `op` are mutually exclusive")
    if op is None:
        if average is None or average:
            return AVERAGE
        return SUM
    return op


class MeshCollectives:
    """Compiled XLA collectives over one mesh (one per process set).

    Each public method returns the result of a cached compiled executable;
    compile cache keys are (op, dtype, shape/bucket), so steady-state
    training dispatches without retracing — the XLA analog of the
    reference's response-cache fast path.
    """

    def __init__(self, devices: Sequence, cache: Optional[ExecutableCache] = None,
                 name: str = "global"):
        self.devices = list(devices)
        self.size = len(self.devices)
        self.name = name
        self.mesh = Mesh(np.asarray(self.devices), (AXIS,))
        self.cache = cache if cache is not None else ExecutableCache()
        self._stacked_sharding = NamedSharding(self.mesh, P(AXIS))
        self._replicated_sharding = NamedSharding(self.mesh, P())
        # Sightings per (shape, splits) / grouping key: compiled fused
        # programs are built only for keys that repeat.
        self._ragged_seen: dict = {}
        self._grouping_seen: dict = {}

    # -- helpers -----------------------------------------------------------

    def shard_stacked(self, x):
        """Place a rank-major stacked array so row r lives on device r."""
        return jax.device_put(jnp.asarray(x), self._stacked_sharding)

    def _key(self, op: str, dtype, shape, extra=()) -> tuple:
        return (self.name, op, str(dtype), tuple(shape)) + tuple(extra)

    # -- allreduce ---------------------------------------------------------

    def _allreduce_shard_fn(self, red_op: str):
        """The unjitted shard_map collective, shared by the plain and
        fused allreduce programs."""
        size = self.size

        def block_fn(x, pre, post):
            # x: this rank's block [1, ...]; pre/post: scalar factors.
            x = x * pre.astype(x.dtype)
            if red_op in (SUM, AVERAGE, ADASUM):
                r = lax.psum(x, AXIS)
                if red_op == AVERAGE:
                    # Average in f32 accumulation for low-precision inputs.
                    r = (r / size).astype(x.dtype) if jnp.issubdtype(
                        x.dtype, jnp.floating) else r // size
            elif red_op == MIN:
                r = lax.pmin(x, AXIS)
            elif red_op == MAX:
                r = lax.pmax(x, AXIS)
            elif red_op == PRODUCT:
                r = product_allreduce(
                    x.reshape(-1), AXIS, size).reshape(x.shape)
            else:
                raise NotImplementedError(red_op)
            return r * post.astype(x.dtype)

        # check_vma off for Product: the reduce-scatter + tiled
        # all_gather result is replicated in value but not statically
        # inferable as such.
        return jax.shard_map(block_fn, mesh=self.mesh,
                             in_specs=(P(AXIS), P(), P()),
                             out_specs=P(), check_vma=(red_op != PRODUCT))

    def _build_allreduce(self, red_op: str):
        return jax.jit(self._allreduce_shard_fn(red_op))

    def allreduce(self, stacked, red_op: str = SUM,
                  prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0):
        """Reduce rank-major stacked [size, ...] -> replicated [...]."""
        stacked = self.shard_stacked(stacked)
        key = self._key("allreduce", stacked.dtype, stacked.shape, (red_op,))
        fn = self.cache.get_or_build(
            key, lambda: self._build_allreduce(red_op))
        pre = jnp.asarray(prescale_factor, dtype=jnp.float32)
        post = jnp.asarray(postscale_factor, dtype=jnp.float32)
        out = fn(stacked, pre, post)
        # Block shape [1, ...] -> logical [...]
        return out[0]

    def _build_fused_allreduce(self, red_op, shapes, joined_idx, bucket):
        size = self.size
        shard_fn = self._allreduce_shard_fn(red_op)
        lengths = [int(np.prod(s[1:], dtype=np.int64)) for s in shapes]
        total = sum(lengths)

        def prog(pre, post, *payloads):
            flats = []
            for p, joined in zip(payloads, joined_idx):
                f = p.reshape(size, -1)
                if joined:
                    f = f.at[jnp.asarray(list(joined))].set(0)
                flats.append(f)
            if bucket > total:
                flats.append(jnp.zeros((size, bucket - total),
                                       dtype=flats[0].dtype))
            fused = jnp.concatenate(flats, axis=1)
            out = shard_fn(fused, pre, post)[0]
            outs, off = [], 0
            for ln, s in zip(lengths, shapes):
                outs.append(out[off:off + ln].reshape(s[1:]))
                off += ln
            return tuple(outs)

        return jax.jit(prog)

    def fused_allreduce(self, payloads, red_op: str,
                        prescale_factor: float, postscale_factor: float,
                        joined_idx, bucket: int):
        """Fusion-group allreduce.

        A grouping seen for the SECOND time gets one compiled program
        (flatten + zero joined rows + concat into the padded bucket +
        the collective + per-entry slices — XLA owns the fusion
        buffer as compiler scratch).  A first-seen grouping takes the
        eager path, whose big collective executable is keyed only on
        the power-of-two bucket shape and therefore shared across
        groupings — so shifting chunk boundaries (e.g. while the
        autotuner moves the fusion threshold) don't compile a fresh
        program every cycle."""
        payloads = [self.shard_stacked(p) for p in payloads]
        joined_idx = tuple(tuple(j) for j in joined_idx)
        shapes = tuple(p.shape for p in payloads)
        key = self._key("fused_allreduce", payloads[0].dtype, shapes,
                        (red_op, joined_idx, bucket))
        if len(self._grouping_seen) > 4096:  # bound the sighting memo
            self._grouping_seen.clear()
        seen = self._grouping_seen.get(key, 0)
        self._grouping_seen[key] = seen + 1
        pre = jnp.asarray(prescale_factor, dtype=jnp.float32)
        post = jnp.asarray(postscale_factor, dtype=jnp.float32)
        if seen == 0:
            flats = []
            for p, joined in zip(payloads, joined_idx):
                f = p.reshape(self.size, -1)
                if joined:
                    f = f.at[jnp.asarray(list(joined))].set(0)
                flats.append(f)
            lengths = [f.shape[1] for f in flats]
            total = sum(lengths)
            if bucket > total:
                flats.append(jnp.zeros((self.size, bucket - total),
                                       dtype=flats[0].dtype))
            fused = jnp.concatenate(flats, axis=1)
            out = self.allreduce(fused, red_op, prescale_factor,
                                 postscale_factor)
            outs, off = [], 0
            for ln, s in zip(lengths, shapes):
                outs.append(out[off:off + ln].reshape(s[1:]))
                off += ln
            return tuple(outs)
        fn = self.cache.get_or_build(
            key, lambda: self._build_fused_allreduce(
                red_op, shapes, joined_idx, bucket))
        return fn(pre, post, *payloads)

    # -- allgather ---------------------------------------------------------

    def _build_allgather(self):
        def block_fn(x):
            # x: [1, k, ...] -> gather to [size*k, ...] on every rank.
            g = lax.all_gather(x[0], AXIS, tiled=True)
            return g

        fn = jax.shard_map(block_fn, mesh=self.mesh,
                           in_specs=P(AXIS), out_specs=P(),
                           check_vma=False)
        return jax.jit(fn)

    def allgather(self, per_rank: List):
        """Concatenate per-rank tensors along axis 0 (ragged allowed).

        Matches reference AllgatherOp semantics: first dims may differ
        across ranks; other dims must match.
        """
        dims0 = {np.shape(t)[0] if np.ndim(t) else 1 for t in per_rank}
        if len(dims0) == 1:
            stacked = jnp.stack([jnp.asarray(t) for t in per_rank])
            stacked = self.shard_stacked(stacked)
            key = self._key("allgather", stacked.dtype, stacked.shape)
            fn = self.cache.get_or_build(key, self._build_allgather)
            return fn(stacked)
        # Ragged path: single-controller concat, compiled per shape-sig.
        sig = tuple(tuple(np.shape(t)) for t in per_rank)
        key = self._key("allgather_ragged", np.asarray(per_rank[0]).dtype, (), (sig,))
        fn = self.cache.get_or_build(
            key, lambda: jax.jit(
                lambda *ts: jnp.concatenate(ts, axis=0),
                out_shardings=self._replicated_sharding))
        return fn(*[jnp.asarray(t) for t in per_rank])

    # -- broadcast ---------------------------------------------------------

    def broadcast(self, stacked, root_rank: int):
        """Select rank ``root``'s row and replicate it to all devices."""
        stacked = self.shard_stacked(stacked)
        key = self._key("broadcast", stacked.dtype, stacked.shape)
        fn = self.cache.get_or_build(
            key,
            lambda: jax.jit(
                lambda x, r: lax.dynamic_index_in_dim(
                    x, r, axis=0, keepdims=False),
                out_shardings=self._replicated_sharding))
        return fn(stacked, jnp.asarray(root_rank, dtype=jnp.int32))

    # -- alltoall ----------------------------------------------------------

    def _build_alltoall(self):
        def block_fn(x):
            # x: [1, size*k, ...]; split dim1 into `size` chunks, chunk j
            # goes to rank j; received chunks concatenate along dim1.
            y = lax.all_to_all(x[0], AXIS, split_axis=0, concat_axis=0,
                               tiled=True)
            return y[None]

        fn = jax.shard_map(block_fn, mesh=self.mesh,
                           in_specs=P(AXIS), out_specs=P(AXIS))
        return jax.jit(fn)

    def alltoall(self, stacked, splits: Optional[np.ndarray] = None):
        """All-to-all exchange.

        ``stacked``: [size, N, ...] where rank r's tensor is ``stacked[r]``.
        Uniform case (``splits is None`` and N % size == 0): compiled XLA
        ``all-to-all``.  Ragged case (per-rank split sizes, reference
        ``AlltoallOp`` with ``splits`` argument): single-controller
        reassembly; returns (stacked_out_list, recv_splits).
        """
        stacked = jnp.asarray(stacked)
        n = stacked.shape[1] if stacked.ndim > 1 else 0
        if splits is None:
            if stacked.shape[0] != self.size or n % self.size != 0:
                raise ValueError(
                    "uniform alltoall needs dim1 divisible by size")
            stacked = self.shard_stacked(stacked)
            key = self._key("alltoall", stacked.dtype, stacked.shape)
            fn = self.cache.get_or_build(key, self._build_alltoall)
            return fn(stacked), None
        # Ragged: splits[r][j] = #rows rank r sends to rank j.
        #
        # Output shapes depend on the exact splits matrix, so a
        # compiled program is only worth building for splits that
        # REPEAT (e.g. fixed-capacity MoE dispatch); per-step varying
        # splits would recompile every step.  First sighting (or a
        # pathologically skewed pad) takes the eager reassembly; a
        # repeat compiles one program that fuses the pack/unpack
        # around a single device all_to_all collective.
        splits = np.asarray(splits)
        maxc = int(splits.max(initial=0))
        if maxc == 0:
            empty = stacked[:, :0] if stacked.ndim > 1 else stacked[:0]
            return [empty[0] for _ in range(self.size)], splits.T.copy()
        key = self._key("alltoall_ragged", stacked.dtype, stacked.shape,
                        (splits.tobytes(),))
        pad_blowup = (self.size * self.size * maxc
                      > 4 * int(splits.sum()))
        if len(self._ragged_seen) > 4096:  # bound the sighting memo
            self._ragged_seen.clear()
        seen = self._ragged_seen.get(key, 0)
        self._ragged_seen[key] = seen + 1
        if pad_blowup or seen == 0:
            out_rows: List[List] = [[] for _ in range(self.size)]
            for r in range(self.size):
                off = 0
                for j in range(self.size):
                    c = int(splits[r, j])
                    out_rows[j].append(stacked[r][off:off + c])
                    off += c
            outs = [jnp.concatenate(rows, axis=0) for rows in out_rows]
            return outs, splits.T.copy()
        fn = self.cache.get_or_build(
            key, lambda: self._build_alltoall_ragged(splits))
        return list(fn(stacked)), splits.T.copy()

    def _build_alltoall_ragged(self, splits: np.ndarray):
        size = self.size
        maxc = int(splits.max())

        def block_fn(x):
            # x: [1, size, maxc, ...] -> row j to rank j; received rows
            # stack in sender order.
            y = lax.all_to_all(x[0], AXIS, split_axis=0, concat_axis=0,
                               tiled=True)
            return y[None]

        shuffle = jax.shard_map(block_fn, mesh=self.mesh,
                                in_specs=P(AXIS), out_specs=P(AXIS))

        def prog(stacked):
            rest_ndim = stacked.ndim - 2
            send = []
            for r in range(size):
                off, chunks = 0, []
                for j in range(size):
                    c = int(splits[r, j])
                    blk = stacked[r, off:off + c]
                    off += c
                    chunks.append(jnp.pad(
                        blk, [(0, maxc - c)] + [(0, 0)] * rest_ndim))
                send.append(jnp.stack(chunks))
            recv = shuffle(jnp.stack(send))  # [recv_rank, send_rank, maxc, ...]
            outs = []
            for j in range(size):
                rows = [recv[j, r, :int(splits[r, j])]
                        for r in range(size)]
                outs.append(jnp.concatenate(rows, axis=0))
            return tuple(outs)

        return jax.jit(prog)

    # -- reducescatter -----------------------------------------------------

    # (uneven chunk layout shared with the engine and multihost mode
    # lives in module scope: uneven_chunks below)

    def _build_reducescatter(self, red_op: str):
        size = self.size

        def block_fn(x):
            # x: [1, size*k, ...] -> this rank's reduced shard [k, ...].
            if red_op in (SUM, AVERAGE):
                y = lax.psum_scatter(x[0], AXIS, scatter_dimension=0,
                                     tiled=True)
                if red_op == AVERAGE:
                    y = (y / size).astype(y.dtype)
            else:
                # No scatter-variant collective exists for these ops;
                # one all_to_all + a local reduce keeps the wire at 1×
                # payload bytes (the full-reduce-then-slice fallback
                # moved N×).
                y = alltoall_chunk_reduce(x[0], AXIS, size, red_op)
            return y[None]

        fn = jax.shard_map(block_fn, mesh=self.mesh,
                           in_specs=P(AXIS), out_specs=P(AXIS))
        return jax.jit(fn)

    def reducescatter(self, stacked, red_op: str = SUM):
        """[size, N, ...] -> [size, N/size, ...]: row r is rank r's reduced
        shard.  Requires N % size == 0; the engine routes uneven N
        through a full reduce + chunk slicing that matches the native
        core's layout (reference ReducescatterOp gives earlier ranks
        the larger shards)."""
        stacked = self.shard_stacked(stacked)
        key = self._key("reducescatter", stacked.dtype, stacked.shape,
                        (red_op,))
        fn = self.cache.get_or_build(
            key, lambda: self._build_reducescatter(red_op))
        return fn(stacked)

    # -- barrier -----------------------------------------------------------

    def barrier(self):
        """Device-visible barrier: a tiny psum all must participate in."""
        one = jnp.ones((self.size,), dtype=jnp.int32)
        out = self.allreduce(one.reshape(self.size, 1), SUM)
        jax.block_until_ready(out)
        return int(out[0])
