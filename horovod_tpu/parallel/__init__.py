"""parallel subpackage."""
