"""Latency-hiding collective matmuls.

Beyond-reference extension (SURVEY.md §7 phase 7): the tensor-parallel
building blocks that overlap communication with MXU compute instead of
serializing ``all_gather → matmul`` / ``matmul → reduce_scatter``.
The technique is the standard TPU "collective matmul" decomposition
(as popularized by the scaling playbook): walk the ring one shard per
step with ``ppermute`` while multiplying the shard already on-chip —
XLA's async collective-permute then hides the hop latency behind each
partial matmul.

Both functions are written for use inside ``shard_map`` over a named
axis and are exact (bitwise-equal chunk math, no approximation):

* ``all_gather_matmul(x, w, axis_name)``   ≡ ``all_gather(x) @ w``
* ``matmul_reduce_scatter(x, w, axis_name)`` ≡
  ``reduce_scatter(x @ w)`` (row shard of the summed product)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["all_gather_matmul", "matmul_reduce_scatter"]


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def all_gather_matmul(x, w, axis_name: str):
    """``all_gather(x, axis) @ w`` with the gather overlapped.

    ``x``: this device's row shard ``(m_loc, k)``;
    ``w``: the local weight ``(k, n_loc)`` (replicated or col-sharded —
    either way it never moves).  Returns ``(n_dev*m_loc, n_loc)``: the
    full row dimension, each block computed the step its shard arrived.
    """
    n_dev = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    m_loc = x.shape[0]
    out = jnp.zeros((n_dev * m_loc, w.shape[1]), dtype=x.dtype)
    perm = _ring_perm(n_dev)

    def step(t, carry):
        buf, out = carry
        src = (idx - t) % n_dev          # whose shard we hold at step t
        y = jnp.dot(buf, w, preferred_element_type=jnp.float32) \
            .astype(out.dtype)
        out = lax.dynamic_update_slice(out, y, (src * m_loc, 0))
        # rotate every step (ring_attention's pattern): an unconditional
        # trailing ppermute lets XLA split it into start/done and overlap
        # it with the slice update; the final hop returns x home unused
        buf = lax.ppermute(buf, axis_name, perm)
        return buf, out

    _, out = lax.fori_loop(0, n_dev, step, (x, out))
    return out


def matmul_reduce_scatter(x, w, axis_name: str):
    """``reduce_scatter(x @ w, axis)`` with the scatter overlapped.

    ``x``: local activation ``(m, k_loc)``; ``w``: local weight shard
    ``(k_loc, n)`` — each device holds a partial product ``x @ w`` that
    must be summed over the axis and row-scattered.  Instead of
    materializing the full ``(m, n)`` partial and reduce-scattering it,
    the ring walks ``n_dev`` row chunks: each step multiplies ONE
    ``(m/n_dev, ·)`` chunk and adds it to the accumulator arriving from
    the neighbor.  Returns this device's ``(m/n_dev, n)`` row of the
    summed product.
    """
    n_dev = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    m = x.shape[0]
    if m % n_dev:
        raise ValueError("row dim %d not divisible by axis size %d"
                         % (m, n_dev))
    m_loc = m // n_dev
    perm = _ring_perm(n_dev)

    def chunk(i):
        return lax.dynamic_slice(x, (i * m_loc, 0), (m_loc, x.shape[1]))

    def step(t, acc):
        # permute-then-add with the hop FIRST keeps the loop free of
        # conditionals (XLA can overlap the permute with this step's
        # dot, which does not depend on the arriving accumulator);
        # chunk schedule q(d,t) = (d - t - 1) mod n lands each row sum
        # on its home device at t = n-1
        acc = lax.ppermute(acc, axis_name, perm)
        src = (idx - t - 1) % n_dev
        part = jnp.dot(chunk(src), w,
                       preferred_element_type=jnp.float32) \
            .astype(acc.dtype)
        return acc + part

    # step 0 needs no incoming hop: seed with this device's first chunk
    first = jnp.dot(chunk((idx - 1) % n_dev), w,
                    preferred_element_type=jnp.float32).astype(x.dtype)
    return lax.fori_loop(1, n_dev, step, first)
