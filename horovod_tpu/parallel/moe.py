"""Expert parallelism: Mixture-of-Experts with all-to-all dispatch.

Beyond-reference extension (SURVEY.md §2.5: the reference ships the
``alltoall`` collective but no MoE strategy; this module is the strategy).
Switch/GShard-style top-k routing with capacity: tokens are dispatched to
experts sharded over the 'ep' mesh axis via XLA ``all-to-all`` — the exact
use case the reference's AlltoallOp existed to serve, here fused into the
compiled step.

All functions run inside a shard_map body.  Shapes per shard:
tokens ``x: [T, d]``; experts_per_shard local experts; global expert count
E = ep_size * experts_per_shard.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common import jax_compat  # noqa: F401 - installs lax.axis_size shim


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    n_experts: int
    d_model: int
    d_ff: int
    top_k: int = 2
    capacity_factor: float = 1.25


def init_moe_params(key, cfg: MoeConfig, experts_per_shard: int,
                    dtype=jnp.float32):
    """Per-shard expert weights (swiglu FFN per expert) + replicated router.

    In the ep-sharded world each shard holds ``experts_per_shard`` experts;
    stacking over shards yields the full expert set.
    """
    k1, k2, k3, k4 = jax.random.split(key, 4)
    d, f = cfg.d_model, cfg.d_ff
    s = 1.0 / math.sqrt(d)
    return {
        "router": (jax.random.normal(k1, (d, cfg.n_experts)) * s
                   ).astype(dtype),
        "w1": (jax.random.normal(k2, (experts_per_shard, d, f)) * s
               ).astype(dtype),
        "w3": (jax.random.normal(k3, (experts_per_shard, d, f)) * s
               ).astype(dtype),
        "w2": (jax.random.normal(k4, (experts_per_shard, f, d)) *
               (1.0 / math.sqrt(f))).astype(dtype),
    }


def _dispatch_tensors(gates, top_k: int, n_experts: int, capacity: int):
    """Build dispatch/combine tensors (GShard-style cumsum position slots).

    gates: [T, E] softmax router probabilities.
    Returns dispatch [T, E, C] (bool) and combine [T, E, C] (weights).
    """
    t = gates.shape[0]
    topk_w, topk_e = lax.top_k(gates, top_k)
    # Renormalize selected weights.
    topk_w = topk_w / jnp.maximum(topk_w.sum(-1, keepdims=True), 1e-9)
    dispatch = jnp.zeros((t, n_experts, capacity), bool)
    combine = jnp.zeros((t, n_experts, capacity), gates.dtype)
    # Fill expert slots choice-by-choice so earlier choices get priority,
    # mirroring the reference MoE implementations' greedy capacity rule.
    used = jnp.zeros((n_experts,), jnp.int32)
    for j in range(top_k):
        e = topk_e[:, j]
        onehot = jax.nn.one_hot(e, n_experts, dtype=jnp.int32)
        pos = (jnp.cumsum(onehot, axis=0) - 1) + used[None, :]
        pos_t = (pos * onehot).sum(-1)
        keep = pos_t < capacity
        slot = jax.nn.one_hot(pos_t, capacity, dtype=jnp.bool_)
        d_j = (onehot.astype(bool)[:, :, None] & slot[:, None, :]
               & keep[:, None, None])
        dispatch = dispatch | d_j
        combine = combine + d_j.astype(combine.dtype) * \
            topk_w[:, j][:, None, None]
        used = used + onehot.sum(0)
    return dispatch, combine


def moe_ffn(params, x, cfg: MoeConfig, axis_name: Optional[str] = "ep"):
    """Top-k routed swiglu FFN with expert parallelism.

    ``x: [T, d]`` per shard.  When ``axis_name`` is None (or ep=1) the
    all-to-alls drop out and this is a dense-local MoE.
    """
    n_shards = lax.axis_size(axis_name) if axis_name else 1
    t, d = x.shape
    e_total = cfg.n_experts
    e_local = params["w1"].shape[0]
    assert e_local * n_shards == e_total, (e_local, n_shards, e_total)
    capacity = max(1, int(math.ceil(
        t * cfg.top_k * cfg.capacity_factor / e_total)))

    logits = x @ params["router"].astype(x.dtype)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    dispatch, combine = _dispatch_tensors(gates, cfg.top_k, e_total, capacity)

    # [T, E, C] x [T, d] -> [E, C, d]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)

    if n_shards > 1:
        # [E, C, d] -> [ep, E_local, C, d]; shard i keeps its experts,
        # receiving one [E_local, C, d] slab from every source shard.
        expert_in = expert_in.reshape(n_shards, e_local, capacity, d)
        expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                                   concat_axis=0, tiled=True)
        expert_in = expert_in.reshape(n_shards, e_local, capacity, d)
        # -> [E_local, ep*C, d]: fold source shards into the slot axis.
        expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
            e_local, n_shards * capacity, d)
    else:
        expert_in = expert_in.reshape(e_local, capacity, d)

    # Per-expert swiglu, batched over local experts on the MXU.
    h = jnp.einsum("esd,edf->esf", expert_in, params["w1"].astype(x.dtype))
    g = jnp.einsum("esd,edf->esf", expert_in, params["w3"].astype(x.dtype))
    act = jax.nn.silu(h) * g
    expert_out = jnp.einsum("esf,efd->esd", act,
                            params["w2"].astype(x.dtype))

    if n_shards > 1:
        expert_out = expert_out.reshape(
            e_local, n_shards, capacity, d).transpose(1, 0, 2, 3)
        expert_out = expert_out.reshape(n_shards * e_local, capacity, d)
        expert_out = lax.all_to_all(expert_out, axis_name, split_axis=0,
                                    concat_axis=0, tiled=True)
        expert_out = expert_out.reshape(e_total, capacity, d)
    else:
        expert_out = expert_out.reshape(e_total, capacity, d)

    # Weighted return to token positions: [T, E, C] x [E, C, d] -> [T, d]
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return y, aux_load_balance_loss(gates, dispatch)


def aux_load_balance_loss(gates, dispatch):
    """Switch-transformer load-balancing auxiliary loss."""
    e = gates.shape[1]
    frac_tokens = dispatch.any(-1).astype(jnp.float32).mean(0)
    frac_gates = gates.mean(0)
    return e * jnp.sum(frac_tokens * frac_gates)
