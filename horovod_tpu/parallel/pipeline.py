"""Pipeline parallelism: GPipe-style microbatching over the 'pp' axis.

Beyond-reference extension (SURVEY.md §2.5: PP is absent from the
reference).  TPU-native design: stages are mesh shards; activations flow
stage-to-stage with ``collective-permute`` (``lax.ppermute``) inside one
compiled program, microbatches filling the pipeline in a ``lax.fori_loop``
(M + n_stages - 1 ticks).  Backward is jax AD straight through the loop —
the transposed program pipelines gradients in the reverse direction with
the transposed permutes.

Layer-stacked parameters ``[L, ...]`` are sharded over 'pp' on dim 0, so
every shard holds a contiguous group of layers (its stage).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..common import jax_compat  # noqa: F401 - installs lax.axis_size shim


def pipeline_apply(stage_params, microbatches, stage_fn: Callable,
                   axis_name: str = "pp"):
    """Run microbatches through the stage pipeline.

    stage_params: this shard's layer-group params (pytree; leaves stacked
      [L_local, ...] to be scanned by ``stage_fn``).
    microbatches: [M, mb, ...] — every shard receives the same stacked
      microbatch inputs (only stage 0 actually consumes them).
    stage_fn(stage_params, activation) -> activation for one stage.

    Returns [M, mb, ...] final-stage outputs, replicated to all shards.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    act_shape = microbatches.shape[1:]
    total_ticks = m + n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    state = jnp.zeros(act_shape, microbatches.dtype)
    outputs = jnp.zeros((m,) + act_shape, microbatches.dtype)

    def tick(t, carry):
        state, outputs = carry
        # Stage 0 injects microbatch t (when one remains); other stages
        # consume what arrived from their predecessor last tick.
        mb_index = jnp.minimum(t, m - 1)
        inject = lax.dynamic_index_in_dim(microbatches, mb_index, axis=0,
                                          keepdims=False)
        inp = jnp.where(idx == 0, inject, state)
        act = stage_fn(stage_params, inp)
        # The last stage's act for tick t belongs to microbatch t-(n-1).
        out_index = jnp.clip(t - (n - 1), 0, m - 1)
        is_valid = (idx == n - 1) & (t >= n - 1)
        current = lax.dynamic_index_in_dim(outputs, out_index, axis=0,
                                           keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(is_valid, act, current), out_index, axis=0)
        state = lax.ppermute(act, axis_name, fwd_perm)
        return state, outputs

    _, outputs = lax.fori_loop(0, total_ticks, tick, (state, outputs))
    # Replicate the last stage's outputs to every shard (cheap vs compute;
    # keeps loss computation and out_specs uniform).
    mask = (idx == n - 1).astype(outputs.dtype)
    return lax.psum(outputs * mask, axis_name)


def split_microbatches(batch, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...] for pipeline_apply."""
    b = batch.shape[0]
    if b % num_microbatches:
        raise ValueError("batch %d not divisible by microbatches %d"
                         % (b, num_microbatches))
    return batch.reshape((num_microbatches, b // num_microbatches)
                         + batch.shape[1:])
