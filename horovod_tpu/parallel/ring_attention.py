"""Ring attention: sequence/context parallelism over the ICI ring.

Beyond-reference extension (SURVEY.md §5: the reference predates
long-context techniques; its only related primitive is the raw alltoall).
On TPU the natural long-sequence design is blockwise ring attention:
each sequence-parallel shard holds a Q block and rotates K/V blocks around
the 'sp' mesh axis with ``collective-permute`` (``lax.ppermute``), which
maps directly onto the physical ICI ring; softmax is accumulated online
(flash-attention style, max/sum carried in f32) so the full [S, S] score
matrix never materializes.

Shapes inside the shard_map body (per shard): q/k/v are
``[batch, seq_local, heads, head_dim]``; output matches q.  GQA is
supported by passing fewer KV heads (they are repeated locally).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common import jax_compat  # noqa: F401 - installs lax.axis_size shim

NEG_INF = -1e30


def _block_attn(q, k, v, mask, m_prev, l_prev, acc):
    """One flash-attention accumulation step for a KV block.

    q: [B, Sq, H, D]; k/v: [B, Skv, H, D]; mask broadcastable to
    [B, H, Sq, Skv]; carries in f32.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    # Guard fully-masked rows: keep exp argument finite.
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(jnp.where(m_prev <= NEG_INF / 2, NEG_INF, m_prev) - m_safe)
    corr = jnp.where(m_prev <= NEG_INF / 2, 0.0, corr)
    l_new = l_prev * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def _repeat_kv(kv, n_rep: int):
    if n_rep == 1:
        return kv
    b, s, h, d = kv.shape
    return jnp.repeat(kv, n_rep, axis=2)


def pvary_missing(v, axes):
    """Mark ``v`` varying over any of ``axes`` it is not already
    varying over (vma tracking for check_vma=True shard_maps); identity
    when tracking is off.  Loop carries must enter with the
    varying-axes superset their outputs acquire."""
    try:
        have = jax.typeof(v).vma
    except Exception:  # noqa: BLE001 - no vma tracking in this trace
        return v
    missing = tuple(a for a in axes if a not in have)
    if not missing:
        return v
    if hasattr(lax, "pcast"):
        return lax.pcast(v, missing, to="varying")
    return lax.pvary(v, missing)  # older jax spelling


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                   query_offset=None, kv_offset=None):
    """Blockwise ring attention inside a shard_map over ``axis_name``.

    Each shard computes attention of its local Q block against every KV
    block; KV blocks travel around the ring, one ppermute per step, so
    communication overlaps the block computation XLA schedules between
    permutes.  Causal masking uses *global* positions derived from the
    shard index (or explicit ``query_offset``/``kv_offset`` arrays).
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s_q, h, d = q.shape
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    s_kv = k.shape[1]

    if query_offset is None:
        query_offset = idx * s_q
    q_pos = query_offset + jnp.arange(s_q)

    m0 = jnp.full((b, h, s_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_q), jnp.float32)
    acc0 = jnp.zeros((b, s_q, h, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        k_cur, v_cur, m, l, acc = carry
        # After t rotations shard ``idx`` holds the KV block that started
        # on shard (idx - t) mod n.
        src = (idx - t) % n
        base = kv_offset if kv_offset is not None else src * s_kv
        kv_pos = base + jnp.arange(s_kv) if kv_offset is None else \
            base + jnp.arange(s_kv)
        if causal:
            mask = kv_pos[None, None, None, :] <= q_pos[None, None, :, None]
        else:
            mask = jnp.ones((1, 1, s_q, s_kv), bool)
        m, l, acc = _block_attn(q, k_cur, v_cur, mask, m, l, acc)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    vma = getattr(jax.typeof(q), "vma", ())
    init = tuple(pvary_missing(c, tuple(vma)) for c in
                 (k, v, m0, l0, acc0))
    _, _, m, l, acc = lax.fori_loop(0, n, body, init)
    l_t = l.transpose(0, 2, 1)[..., None]
    out = acc / jnp.maximum(l_t, 1e-30)
    return out.astype(q.dtype)


def local_attention(q, k, v, causal: bool = True):
    """Single-shard reference attention (same math, no ring) — used by the
    dense model when sp=1 and by tests as the ground truth."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        s_q, s_kv = q.shape[1], k.shape[1]
        mask = jnp.arange(s_kv)[None, :] <= jnp.arange(s_q)[:, None]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
