"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The alltoall-based alternative to ring attention (DeepSpeed-Ulysses
pattern; SURVEY.md §2.5 notes the reference's ``hvd.alltoall`` is exactly
the primitive this strategy needs — here it becomes XLA ``all-to-all``
over the 'sp' axis).  Layout A (sequence-sharded, heads full) is what the
rest of the transformer uses; attention wants layout B (heads sharded,
sequence full).  Two all-to-alls bracket any attention kernel:

    A: [B, S/n, H, D]  --seq_to_heads-->  B: [B, S, H/n, D]
    B                  --heads_to_seq-->  A

Works for any attention implementation in between (including a Pallas
flash kernel), at the cost of 2 all-to-alls vs ring's n ppermutes.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..common import jax_compat  # noqa: F401 - installs lax.axis_size shim

from .ring_attention import local_attention


def seq_to_heads(x, axis_name: str = "sp"):
    """[B, S/n, H, D] -> [B, S, H/n, D] via all-to-all."""
    # Split the head axis across shards, gather the sequence axis.
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def heads_to_seq(x, axis_name: str = "sp"):
    """[B, S, H/n, D] -> [B, S/n, H, D] via all-to-all (inverse)."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                      attn_fn=None):
    """Attention with Ulysses layout exchange inside a shard_map body.

    q/k/v: [B, S/n, H, D] (sequence-sharded).  Requires H divisible by the
    axis size.  ``attn_fn(q, k, v, causal)`` runs with full sequence and
    sharded heads; defaults to the exact local attention.
    """
    n = lax.axis_size(axis_name)
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(
            "Ulysses needs heads (%d q / %d kv) divisible by sp=%d"
            % (q.shape[2], k.shape[2], n))
    attn_fn = attn_fn or local_attention
    q_h = seq_to_heads(q, axis_name)
    k_h = seq_to_heads(k, axis_name)
    v_h = seq_to_heads(v, axis_name)
    out_h = attn_fn(q_h, k_h, v_h, causal=causal)
    return heads_to_seq(out_h, axis_name)
