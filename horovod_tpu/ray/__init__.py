"""Ray platform integration.

Reference parity: ``horovod/ray/runner.py`` (``RayExecutor``) — actor
workers placed across a Ray cluster, each given its Horovod rank
environment, bootstrapping through the driver's rendezvous KV server
and running collectives over the native TCP core.

ray is not bundled in this environment; imports are lazy so the module
stays importable (and the placement math unit-testable) without it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..runner import util
from ..runner.http_server import RendezvousServer

__all__ = ["RayExecutor", "ElasticRayExecutor", "RayHostDiscovery",
           "plan_ranks"]


def _require_ray():
    try:
        import ray
        return ray
    except ImportError as exc:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.ray requires ray (pip install ray)") from exc


def plan_ranks(worker_nodes: List[str]) -> List[Dict[str, int]]:
    """Rank/local/cross assignment for workers grouped by node ip
    (reference: RayExecutor's hostname grouping)."""
    unique: List[str] = []
    for h in worker_nodes:
        if h not in unique:
            unique.append(h)
    local_counts = {h: 0 for h in unique}
    out = []
    for rank, h in enumerate(worker_nodes):
        out.append({
            "rank": rank,
            "size": len(worker_nodes),
            "local_rank": local_counts[h],
            "local_size": worker_nodes.count(h),
            "cross_rank": unique.index(h),
            "cross_size": len(unique),
        })
        local_counts[h] += 1
    return out


_driver_ip = util.routable_ip


class RayExecutor:
    """Actor-based distributed runner (reference ``RayExecutor``)::

        executor = RayExecutor(num_workers=4, cpus_per_worker=1)
        executor.start()
        results = executor.run(train_fn, args=(cfg,))
        executor.shutdown()
    """

    def __init__(self, num_workers: Optional[int] = None,
                 cpus_per_worker: int = 1, use_gpu: bool = False,
                 gpus_per_worker: int = 1,
                 num_hosts: Optional[int] = None,
                 num_workers_per_host: int = 1,
                 extra_env: Optional[Dict[str, str]] = None):
        if num_workers is None and num_hosts is None:
            raise ValueError("give num_workers (packed placement) or "
                             "num_hosts × num_workers_per_host "
                             "(spread placement)")
        if num_workers is not None and num_hosts is not None:
            raise ValueError("num_workers and num_hosts are mutually "
                             "exclusive placement specs")
        gpus = gpus_per_worker if use_gpu else 0
        from .strategy import PackStrategy, SpreadStrategy
        if num_hosts is not None:
            self.strategy = SpreadStrategy(
                num_hosts, num_workers_per_host,
                cpus_per_worker, gpus)
            self.num_workers = num_hosts * num_workers_per_host
        else:
            self.strategy = PackStrategy(
                num_workers, cpus_per_worker, gpus)
            self.num_workers = num_workers
        self.gpus_per_worker = gpus_per_worker
        self.cpus_per_worker = cpus_per_worker
        self.use_gpu = use_gpu
        self.extra_env = dict(extra_env or {})
        self._workers = []
        self._pg = None
        self._server: Optional[RendezvousServer] = None
        self._secret = util.make_secret()

    def start(self):
        ray = _require_ray()

        @ray.remote(num_cpus=self.cpus_per_worker,
                    num_gpus=self.gpus_per_worker if self.use_gpu
                    else 0)
        class _Worker:
            def node_ip(self):
                import ray as _ray
                return _ray.util.get_node_ip_address()

            def setup(self, env: Dict[str, str]):
                import os
                os.environ.update(env)
                return True

            def execute(self, fn, args, kwargs):
                return fn(*args, **(kwargs or {}))

        # placement-group scheduling (reference strategy.py): bundles
        # from the chosen strategy; PACK for plain num_workers,
        # STRICT_SPREAD for num_hosts × num_workers_per_host.  Only a
        # missing PG API falls back to plain scheduling — a PG that
        # cannot be satisfied is a real error (its reservation is
        # already cleaned up by create_placement_group).
        try:
            from ray.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy)
        except ImportError:
            self._workers = [_Worker.remote()
                             for _ in range(self.num_workers)]
        else:
            self._pg, plan = self.strategy.create_placement_group()
            self._workers = [
                _Worker.options(
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=self._pg,
                        placement_group_bundle_index=b)).remote()
                for b in plan.worker_to_bundle]
        ips = ray.get([w.node_ip.remote() for w in self._workers])
        self._server = RendezvousServer(secret=self._secret)
        port = self._server.start()
        addr = "%s:%d" % (_driver_ip(), port)
        plans = plan_ranks(ips)
        setups = []
        for w, ip, plan in zip(self._workers, ips, plans):
            env = dict(self.extra_env)
            env.update({
                "HOROVOD_RANK": str(plan["rank"]),
                "HOROVOD_SIZE": str(plan["size"]),
                "HOROVOD_LOCAL_RANK": str(plan["local_rank"]),
                "HOROVOD_LOCAL_SIZE": str(plan["local_size"]),
                "HOROVOD_CROSS_RANK": str(plan["cross_rank"]),
                "HOROVOD_CROSS_SIZE": str(plan["cross_size"]),
                "HOROVOD_RENDEZVOUS_ADDR": addr,
                "HOROVOD_SECRET_KEY": self._secret,
                "HOROVOD_HOSTNAME": ip,
                "HOROVOD_CONTROLLER": "tcp",
            })
            setups.append(w.setup.remote(env))
        ray.get(setups)

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict] = None) -> List[Any]:
        """Execute ``fn`` on every worker simultaneously; returns
        per-rank results."""
        if not self._workers:
            raise RuntimeError(
                "RayExecutor not started; call start() first")
        ray = _require_ray()
        return ray.get([w.execute.remote(fn, args, kwargs)
                        for w in self._workers])

    def execute(self, fn: Callable) -> List[Any]:
        """Reference API: run a function taking no arguments."""
        return self.run(fn)

    def run_remote(self, fn: Callable, args: tuple = (),
                   kwargs: Optional[Dict] = None) -> List[Any]:
        """Reference API: launch on every worker and return the ray
        ObjectRefs WITHOUT blocking (caller ray.get()s them)."""
        if not self._workers:
            raise RuntimeError(
                "RayExecutor not started; call start() first")
        return [w.execute.remote(fn, args, kwargs)
                for w in self._workers]

    def execute_single(self, fn: Callable) -> Any:
        """Reference API: run a no-argument function on the rank-0
        worker only."""
        if not self._workers:
            raise RuntimeError(
                "RayExecutor not started; call start() first")
        ray = _require_ray()
        return ray.get(self._workers[0].execute.remote(fn, (), None))

    def shutdown(self):
        # each step independent: a dead actor / already-invalidated PG
        # must not leak the remaining resources (esp. the rendezvous
        # server thread)
        ray = _require_ray()
        for w in self._workers:
            try:
                ray.kill(w)
            except Exception:
                pass
        self._workers = []
        if self._pg is not None:
            try:
                from ray.util.placement_group import \
                    remove_placement_group
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
        if self._server is not None:
            self._server.stop()
            self._server = None


from .elastic import ElasticRayExecutor, RayHostDiscovery  # noqa: E402,F401
