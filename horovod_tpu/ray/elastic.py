"""Elastic training on Ray.

Reference parity: ``horovod/ray/elastic_v2.py`` — a ``RayHostDiscovery``
that treats the live Ray cluster membership as the host set (autoscaler
adds/removes nodes → the elastic world grows/shrinks), plus an
``ElasticRayExecutor`` wiring that discovery into the framework's
elastic machinery (``horovod_tpu.elastic``): min/max np, blacklist,
re-rendezvous, worker retry via ``hvd.elastic.run``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..elastic.discovery import HostDiscovery

__all__ = ["RayHostDiscovery", "ElasticRayExecutor"]


class RayHostDiscovery(HostDiscovery):
    """Host discovery backed by ``ray.nodes()`` (reference
    ``RayHostDiscovery``): every alive node contributes
    ``floor(resource / per-worker)`` slots for the chosen resource
    (GPU when ``use_gpu``, CPU otherwise)."""

    def __init__(self, use_gpu: bool = False, cpus_per_slot: int = 1,
                 gpus_per_slot: int = 1):
        self.use_gpu = use_gpu
        self.cpus_per_slot = cpus_per_slot
        self.gpus_per_slot = gpus_per_slot

    def _nodes(self) -> List[Dict[str, Any]]:
        import ray
        return ray.nodes()

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        hosts: Dict[str, int] = {}
        for node in self._nodes():
            if not node.get("Alive", False):
                continue
            res = node.get("Resources", {}) or {}
            ip = node.get("NodeManagerAddress")
            if not ip:
                continue
            if self.use_gpu:
                slots = int(res.get("GPU", 0) // self.gpus_per_slot)
            else:
                slots = int(res.get("CPU", 0) // self.cpus_per_slot)
            if slots > 0:
                hosts[ip] = slots
        return hosts


class ElasticRayExecutor:
    """Elastic actor-based runner (reference ``ElasticRayExecutor``):
    worker actors run ``fn`` under the elastic retry decorator; the
    world is re-discovered and resized within ``[min_np, max_np]`` at
    every (re)start boundary — i.e. after a worker failure or host
    change, not mid-run (growth is picked up on the next restart).

    Failures surface as ``HorovodInternalError`` /
    ``HostsUpdatedInterrupt`` (collective plane) or Ray actor errors
    (a node died); all tear the world down and retry, with state
    rolling back to the last ``state.commit()``.
    """

    def __init__(self, min_np: int = 1, max_np: Optional[int] = None,
                 use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: int = 1,
                 retries: int = 3, cooldown_s: float = 1.0,
                 override_discovery: Optional[HostDiscovery] = None,
                 extra_env: Optional[Dict[str, str]] = None):
        self.min_np = min_np
        self.max_np = max_np
        self.discovery = override_discovery or RayHostDiscovery(
            use_gpu=use_gpu, cpus_per_slot=cpus_per_worker,
            gpus_per_slot=gpus_per_worker)
        self.use_gpu = use_gpu
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker
        self.retries = retries
        self.cooldown_s = cooldown_s
        self.extra_env = dict(extra_env or {})
        self._executor = None

    def _current_np(self) -> int:
        hosts = self.discovery.find_available_hosts_and_slots()
        total = sum(hosts.values())
        if total < self.min_np:
            raise RuntimeError(
                "elastic: only %d slots discovered, min_np=%d"
                % (total, self.min_np))
        return min(total, self.max_np) if self.max_np else total

    def start(self):
        from . import RayExecutor
        np_now = self._current_np()
        self._executor = RayExecutor(
            num_workers=np_now, cpus_per_worker=self.cpus_per_worker,
            use_gpu=self.use_gpu,
            gpus_per_worker=self.gpus_per_worker,
            extra_env=self.extra_env)
        self._executor.start()

    @staticmethod
    def _retryable_exceptions() -> tuple:
        from ..ops.engine import HorovodInternalError
        from ..elastic.worker import HostsUpdatedInterrupt
        excs = [HorovodInternalError, HostsUpdatedInterrupt]
        try:
            # a worker actor dying (node removed) surfaces from
            # ray.get as a RayError, not a collective-plane error
            from ray.exceptions import RayError
            excs.append(RayError)
        except ImportError:
            pass
        return tuple(excs)

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict] = None) -> List[Any]:
        """Run ``fn`` elastically: on a membership change or worker
        failure the world is torn down, re-discovered, and ``fn``
        re-invoked (callers use ``hvd.elastic.run``-decorated fns with
        committed state for exactly-once semantics).  Gives up after
        ``retries`` consecutive failed attempts, with ``cooldown_s``
        between rebuilds."""
        import time
        retryable = self._retryable_exceptions()
        failures = 0
        while True:
            if self._executor is None:
                self.start()
            try:
                return self._executor.run(fn, args=args, kwargs=kwargs)
            except retryable:
                self.shutdown()
                failures += 1
                if failures > self.retries:
                    raise
                time.sleep(self.cooldown_s)

    def shutdown(self):
        if self._executor is not None:
            self._executor.shutdown()
            self._executor = None
