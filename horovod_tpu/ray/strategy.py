"""Worker placement strategies for the Ray executor.

Reference parity: ``horovod/ray/strategy.py`` — two ways of turning a
worker count into Ray placement-group bundles:

* ``PackStrategy`` (reference ``PGStrategy``): ``num_workers`` workers
  packed onto as few nodes as possible (strategy ``PACK``).
* ``SpreadStrategy`` (reference ``ColocationStrategy``):
  ``num_hosts × num_workers_per_host``, one bundle per host, strictly
  spread (``STRICT_SPREAD``).

The bundle math is pure (unit-testable without ray); only
``create_placement_group`` touches the ray runtime.
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["PlacementPlan", "PackStrategy", "SpreadStrategy"]


class PlacementPlan:
    """Bundles + per-worker bundle index + ray PG strategy name."""

    def __init__(self, bundles: List[Dict[str, float]],
                 worker_to_bundle: List[int], strategy: str):
        self.bundles = bundles
        self.worker_to_bundle = worker_to_bundle
        self.strategy = strategy

    @property
    def num_workers(self) -> int:
        return len(self.worker_to_bundle)


class _BaseStrategy:
    def __init__(self, cpus_per_worker: int = 1,
                 gpus_per_worker: int = 0):
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker

    def _worker_resources(self) -> Dict[str, float]:
        res = {"CPU": float(self.cpus_per_worker)}
        if self.gpus_per_worker:
            res["GPU"] = float(self.gpus_per_worker)
        return res

    def plan(self) -> PlacementPlan:
        raise NotImplementedError

    def create_placement_group(self, timeout_s: Optional[float] = 100):
        """Materialize the plan as a ray placement group (requires
        ray).  On a ready-timeout the reservation is removed before
        re-raising, so a failed attempt cannot starve the cluster."""
        import ray
        from ray.util.placement_group import (placement_group,
                                              remove_placement_group)
        p = self.plan()
        pg = placement_group(p.bundles, strategy=p.strategy)
        try:
            ray.get(pg.ready(), timeout=timeout_s)
        except Exception:
            remove_placement_group(pg)
            raise
        return pg, p


class PackStrategy(_BaseStrategy):
    """``num_workers`` anywhere, packed (reference ``PGStrategy``):
    one bundle per worker, ray packs bundles onto nodes."""

    def __init__(self, num_workers: int, cpus_per_worker: int = 1,
                 gpus_per_worker: int = 0):
        super().__init__(cpus_per_worker, gpus_per_worker)
        if num_workers <= 0:
            raise ValueError("num_workers must be positive")
        self.num_workers = num_workers

    def plan(self) -> PlacementPlan:
        bundles = [self._worker_resources()
                   for _ in range(self.num_workers)]
        return PlacementPlan(bundles, list(range(self.num_workers)),
                             "PACK")


class SpreadStrategy(_BaseStrategy):
    """``num_hosts × num_workers_per_host``, one bundle per host
    (reference ``ColocationStrategy``): each bundle carries the whole
    host's worker resources so co-located workers share it."""

    def __init__(self, num_hosts: int, num_workers_per_host: int = 1,
                 cpus_per_worker: int = 1, gpus_per_worker: int = 0):
        super().__init__(cpus_per_worker, gpus_per_worker)
        if num_hosts <= 0 or num_workers_per_host <= 0:
            raise ValueError("num_hosts and num_workers_per_host must "
                             "be positive")
        self.num_hosts = num_hosts
        self.num_workers_per_host = num_workers_per_host

    def plan(self) -> PlacementPlan:
        per_host = {
            "CPU": float(self.cpus_per_worker *
                         self.num_workers_per_host)}
        if self.gpus_per_worker:
            per_host["GPU"] = float(self.gpus_per_worker *
                                    self.num_workers_per_host)
        bundles = [dict(per_host) for _ in range(self.num_hosts)]
        worker_to_bundle = [h for h in range(self.num_hosts)
                            for _ in range(self.num_workers_per_host)]
        return PlacementPlan(bundles, worker_to_bundle,
                             "STRICT_SPREAD")
