"""Launcher layer (reference: horovod/runner/): CLI + programmatic run(),
rendezvous KV server, driver/task services, safe process spawning."""

from .launch import gloo_run, parse_args, run_commandline  # noqa: F401
from .run_api import run  # noqa: F401
