"""runner subpackage."""
