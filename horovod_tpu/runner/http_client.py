"""Rendezvous KV client (reference: horovod/runner/http/http_client.py):
PUT/GET against the launcher's RendezvousServer with HMAC auth."""

from __future__ import annotations

import time
import urllib.error
import urllib.request
from typing import Optional

from .http_server import SECRET_HEADER, compute_digest


class RendezvousClient:
    def __init__(self, addr: str, secret: Optional[str] = None):
        # addr: "host:port"
        self.base = "http://" + addr
        self.secret = secret

    def _headers(self, payload: bytes) -> dict:
        if not self.secret:
            return {}
        return {SECRET_HEADER: compute_digest(self.secret, payload)}

    def put(self, key: str, value: str):
        path = "/" + key.lstrip("/")
        body = value.encode()
        req = urllib.request.Request(self.base + path, data=body,
                                     method="PUT",
                                     headers=self._headers(body))
        with urllib.request.urlopen(req, timeout=10) as resp:
            if resp.status != 200:
                raise RuntimeError("rendezvous PUT failed: %d" % resp.status)

    def get(self, key: str) -> Optional[str]:
        path = "/" + key.lstrip("/")
        req = urllib.request.Request(self.base + path, method="GET",
                                     headers=self._headers(path.encode()))
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def get_blocking(self, key: str, timeout: float = 60.0,
                     interval: float = 0.1) -> str:
        deadline = time.monotonic() + timeout
        while True:
            v = self.get(key)
            if v is not None:
                return v
            if time.monotonic() > deadline:
                raise TimeoutError("rendezvous key %r never appeared" % key)
            time.sleep(interval)

    def delete(self, key: str):
        path = "/" + key.lstrip("/")
        req = urllib.request.Request(self.base + path, method="DELETE",
                                     headers=self._headers(path.encode()))
        urllib.request.urlopen(req, timeout=10)
