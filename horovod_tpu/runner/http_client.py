"""Rendezvous KV client (reference: horovod/runner/http/http_client.py):
PUT/GET against the launcher's RendezvousServer with HMAC auth.

Also home of the runner control plane's shared retry/backoff layer
(``request_with_retry``): transient failures — connection refused or
reset, timeouts, server 5xx — are absorbed with exponential backoff and
full jitter up to a bounded retry budget and per-call deadline, while
fatal ones (HMAC-auth 403, client errors) raise immediately.  The
message service (``runner/services.py``) routes its sends through the
same helper, so ``HVD_TPU_FAULT=runner.rpc.request:drop...`` covers
every retried control-plane RPC from one seam.
"""

from __future__ import annotations

import errno
import http.client
import logging
import os
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, List, Optional, TypeVar

from ..common import faultline, metrics
from ..common.envutil import env_float, env_int
from .http_server import SECRET_HEADER, TERM_HEADER, compute_digest

LOG = logging.getLogger("horovod_tpu.runner.rpc")

T = TypeVar("T")

# Errno set treated as transient on a bare OSError: the peer (or the
# network to it) is momentarily gone, not wrong — including LOCAL
# resource pressure (fd exhaustion, ephemeral-port depletion from
# per-poll connections in TIME_WAIT), which passes as fast as the
# kernel recycles resources.
_TRANSIENT_ERRNOS = frozenset({
    errno.ECONNREFUSED, errno.ECONNRESET, errno.ECONNABORTED,
    errno.EPIPE, errno.EHOSTUNREACH, errno.ENETUNREACH,
    errno.EHOSTDOWN, errno.ENETDOWN,
    errno.ETIMEDOUT, errno.EAGAIN,
    errno.EADDRNOTAVAIL, errno.EADDRINUSE,
    errno.EMFILE, errno.ENFILE, errno.ENOBUFS,
})

# Per-sleep cap on the backoff (the deadline bounds the total anyway).
_BACKOFF_CAP_S = 5.0


def rpc_retry_config() -> "tuple[int, float, float]":
    """(max_retries, initial_backoff_s, deadline_s) from the env.

    The ONE read point for the retry knobs so bootstrap defaults cannot
    fork across call sites (graftlint env-default-conflict discipline):
    ``HOROVOD_RPC_MAX_RETRIES`` (default 3 retries after the first
    attempt), ``HOROVOD_RPC_RETRY_BACKOFF`` (default 0.1 s, doubled per
    failure with full jitter), ``HOROVOD_RPC_DEADLINE`` (default 30 s
    wall budget per retried call)."""
    return (env_int("HOROVOD_RPC_MAX_RETRIES", 3, minimum=0),
            env_float("HOROVOD_RPC_RETRY_BACKOFF", 0.1, minimum=0.0),
            env_float("HOROVOD_RPC_DEADLINE", 30.0, minimum=0.0))


def jittered(seconds: float) -> float:
    """Full jitter over [0.5x, 1.5x): the ONE place the control
    plane's desynchronization window is defined — N peers sleeping the
    same nominal interval must not re-converge on one server in
    lockstep."""
    return seconds * (0.5 + random.random())


def is_transient(exc: BaseException) -> bool:
    """Whether a control-plane RPC failure is worth retrying.

    Transient: connection refused/reset/aborted, closed peers,
    timeouts, DNS hiccups, torn HTTP responses, and server-side 5xx
    (the handler crashed; the server itself is alive).  Fatal: auth
    rejections (HTTP 403, bad MAC ``PermissionError``) and every other
    client error — retrying those hammers a server that already gave a
    definitive answer."""
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500
    if isinstance(exc, urllib.error.URLError):
        reason = exc.reason
        if isinstance(reason, BaseException):
            return is_transient(reason)
        return True  # opaque urllib failure: assume the network burped
    if isinstance(exc, PermissionError):
        return False  # HMAC rejection: retrying cannot help
    if isinstance(exc, (ConnectionError, TimeoutError, socket.timeout,
                        socket.gaierror)):
        return True
    if isinstance(exc, http.client.HTTPException):
        return True  # torn response from a dying/restarting server
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    return False


def request_with_retry(attempt: Callable[[], T], what: str = "rpc",
                       max_retries: Optional[int] = None,
                       backoff: Optional[float] = None,
                       deadline: Optional[float] = None) -> T:
    """Run ``attempt`` until it returns, retrying transient failures
    with exponential backoff + full jitter, bounded by both a retry
    count and a wall-clock deadline.  Non-transient exceptions (and the
    last transient one once the budget is spent) propagate unchanged —
    exhaustion escalates to the caller's fail-fast path, it never
    downgrades the error."""
    env_retries, env_backoff, env_deadline = rpc_retry_config()
    retries = env_retries if max_retries is None else max(0, max_retries)
    base = env_backoff if backoff is None else max(0.0, backoff)
    budget = env_deadline if deadline is None else max(0.0, deadline)
    give_up_at = time.monotonic() + budget
    failures = 0
    while True:
        try:
            metrics.counter("rpc_attempts_total").inc()
            if faultline.site("runner.rpc.request"):
                raise ConnectionResetError(
                    "injected transient RPC failure (faultline "
                    "runner.rpc.request) in %s" % what)
            return attempt()
        except Exception as exc:  # noqa: BLE001 — classified below
            if not is_transient(exc):
                raise
            metrics.counter("rpc_transient_failures_total").inc()
            failures += 1
            now = time.monotonic()
            if failures > retries or now >= give_up_at:
                metrics.counter("rpc_giveups_total").inc()
                metrics.event("rpc_giveup", what=what,
                              failures=failures, error=str(exc))
                LOG.warning("%s failed after %d attempt(s), giving up "
                            "(retries=%d deadline=%.1fs): %s",
                            what, failures, retries, budget, exc)
                raise
            sleep = min(base * (2 ** (failures - 1)), _BACKOFF_CAP_S)
            sleep = min(jittered(sleep), max(0.0, give_up_at - now))
            LOG.debug("%s transient failure %d/%d (%s); retrying in "
                      "%.3fs", what, failures, retries, exc, sleep)
            time.sleep(sleep)


def rendezvous_endpoints() -> List[str]:
    """Ordered KV endpoint candidates from
    ``HOROVOD_RENDEZVOUS_ENDPOINTS`` (comma-separated ``host:port``
    list, leader first) — the ONE read point for the HA endpoint list.
    Re-read on every call on purpose: a mid-run env update (or a
    client constructed before failover config landed) is picked up by
    the next request, not only by the next client."""
    raw = os.environ.get("HOROVOD_RENDEZVOUS_ENDPOINTS", "")
    return [e.strip() for e in raw.split(",") if e.strip()]


class RendezvousClient:
    """KV client with HA endpoint failover: requests walk an ordered
    endpoint list (explicit ``addr`` first, then
    ``HOROVOD_RENDEZVOUS_ENDPOINTS``), rotating to the next candidate
    when one endpoint exhausts its transient-retry budget (the r8
    classification: refused/reset/timeout/5xx) or answers 409
    (fenced/stale leader).  The client carries the highest leader term
    it has seen in ``X-Hvd-Term``, so a paused-and-resumed old leader
    learns it was superseded and fences itself instead of accepting a
    write the new leader never sees."""

    def __init__(self, addr: Optional[str] = None,
                 secret: Optional[str] = None,
                 namespace: Optional[str] = None,
                 endpoints: Optional[List[str]] = None):
        # addr: "host:port" (optional once the env lists endpoints)
        self._addr = addr
        self._explicit = list(endpoints) if endpoints is not None \
            else None
        self.secret = secret
        self._active = 0       # index of the endpoint that last worked
        self._term = 0         # highest leader term seen
        self._rot_lock = threading.Lock()
        # Tenant-scoped key namespace: on a multi-tenant pod every
        # client prefixes its keys with the tenant id (the scheduler
        # exports HOROVOD_TENANT_ID per tenant), so one tenant's
        # coordinator/address-table entries can never collide with
        # another tenant's — even against a shared KV server.  An
        # explicit ``namespace`` argument wins over the env; empty/
        # unset means the un-prefixed single-tenant namespace.
        if namespace is None:
            namespace = os.environ.get("HOROVOD_TENANT_ID")
        self._prefix = "/tenant-%s" % namespace if namespace else ""

    def _path(self, key: str) -> str:
        return self._prefix + "/" + key.lstrip("/")

    def _headers(self, payload: bytes) -> dict:
        headers = {}
        if self.secret:
            headers[SECRET_HEADER] = compute_digest(self.secret, payload)
        with self._rot_lock:
            if self._term > 0:
                headers[TERM_HEADER] = str(self._term)
        return headers

    def _endpoints(self) -> List[str]:
        """The current ordered candidate list: the explicitly-passed
        address first (the world this client was bootstrapped into),
        then every configured failover endpoint not already listed."""
        eps: List[str] = []
        if self._addr:
            eps.append(self._addr)
        extra = (self._explicit if self._explicit is not None
                 else rendezvous_endpoints())
        for e in extra:
            if e not in eps:
                eps.append(e)
        if not eps:
            raise ValueError(
                "no rendezvous endpoint: pass addr= or set "
                "HOROVOD_RENDEZVOUS_ENDPOINTS")
        return eps

    @property
    def base(self) -> str:
        """Back-compat: the currently-active endpoint's URL base."""
        eps = self._endpoints()
        with self._rot_lock:
            return "http://" + eps[min(self._active, len(eps) - 1)]

    def _note_term(self, headers) -> None:
        try:
            seen = int((headers or {}).get(TERM_HEADER) or 0)
        except (TypeError, ValueError):
            return
        with self._rot_lock:
            if seen > self._term:
                self._term = seen

    def _request(self, build_attempt, what: str):
        """Run one logical KV operation with endpoint failover:
        ``build_attempt(base_url)`` returns the single-attempt closure
        for one endpoint; each endpoint gets the full retry/backoff
        budget, and the client rotates to the next candidate on
        transient-exhaustion or a 409 fence.  Non-transient answers
        (auth 403, other 4xx) are definitive and raise immediately.

        A cycle where EVERY endpoint failed transiently or answered
        409 is the leaderless failover window — the old leader is dead
        and the standby's lease has not yet expired, so *nobody* can
        answer.  The whole list is retried (with backoff) under the
        shared ``HOROVOD_RPC_DEADLINE`` wall budget; only its
        exhaustion escalates the last error."""
        _retries, backoff, budget = rpc_retry_config()
        give_up_at = time.monotonic() + budget
        while True:
            eps = self._endpoints()
            with self._rot_lock:
                start = min(self._active, len(eps) - 1)
            last_exc: Optional[BaseException] = None
            for k in range(len(eps)):
                i = (start + k) % len(eps)
                if k > 0:
                    metrics.event("kv_endpoint_rotate", what=what,
                                  frm=eps[(i - 1) % len(eps)], to=eps[i])
                    LOG.warning("%s: rotating rendezvous endpoint to %s "
                                "(%s)", what, eps[i], last_exc)
                try:
                    out = request_with_retry(
                        build_attempt("http://" + eps[i]), what=what)
                    with self._rot_lock:
                        self._active = i
                    return out
                except urllib.error.HTTPError as exc:
                    self._note_term(getattr(exc, "headers", None))
                    if exc.code == 409:
                        # Fenced or stale leader: a definitive "not
                        # me" — try the next endpoint with the
                        # adopted term.
                        last_exc = exc
                        continue
                    raise
                except Exception as exc:  # noqa: BLE001 — classified
                    if is_transient(exc):
                        last_exc = exc
                        continue
                    raise
            assert last_exc is not None
            now = time.monotonic()
            if now >= give_up_at:
                raise last_exc
            sleep = min(jittered(max(0.05, backoff)),
                        max(0.0, give_up_at - now))
            LOG.warning("%s: no rendezvous endpoint answered this "
                        "cycle (%s); failover may be in flight, "
                        "retrying the list in %.2fs", what, last_exc,
                        sleep)
            time.sleep(sleep)

    def put(self, key: str, value: str):
        path = self._path(key)
        body = value.encode()

        def build(base):
            def attempt():
                req = urllib.request.Request(base + path, data=body,
                                             method="PUT",
                                             headers=self._headers(body))
                with urllib.request.urlopen(req, timeout=10) as resp:
                    self._note_term(resp.headers)
                    if resp.status != 200:
                        raise RuntimeError(
                            "rendezvous PUT failed: %d" % resp.status)
            return attempt

        self._request(build, what="rendezvous PUT %s" % key)

    def get(self, key: str) -> Optional[str]:
        path = self._path(key)

        def build(base):
            def attempt():
                req = urllib.request.Request(base + path, method="GET",
                                             headers=self._headers(
                                                 path.encode()))
                try:
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        self._note_term(resp.headers)
                        return resp.read().decode()
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        self._note_term(getattr(e, "headers", None))
                        return None  # a missing key is an answer
                    raise
            return attempt

        return self._request(build, what="rendezvous GET %s" % key)

    def put_json(self, key: str, obj):
        """PUT one JSON document (the collective-plan plane publishes
        plan sets through the KV with this)."""
        import json
        self.put(key, json.dumps(obj, sort_keys=True))

    def get_json(self, key: str):
        """GET one JSON document, or None for a missing key."""
        import json
        v = self.get(key)
        return json.loads(v) if v is not None else None

    def get_blocking(self, key: str, timeout: float = 60.0,
                     interval: float = 0.1) -> str:
        deadline = time.monotonic() + timeout
        while True:
            # Each poll goes through self.get, which re-resolves the
            # endpoint list (explicit addr + env) and the active index
            # PER ITERATION: a failover that lands mid-poll is picked
            # up on the next loop instead of the client spinning
            # against the dead leader it resolved at entry.
            v = self.get(key)
            if v is not None:
                return v
            if time.monotonic() > deadline:
                raise TimeoutError("rendezvous key %r never appeared" % key)
            # Jittered poll: at world bootstrap N workers poll one KV
            # server for the same key — a fixed interval phase-locks
            # their polls into synchronized request bursts.
            time.sleep(jittered(interval))

    def delete(self, key: str):
        path = self._path(key)

        def build(base):
            def attempt():
                req = urllib.request.Request(base + path,
                                             method="DELETE",
                                             headers=self._headers(
                                                 path.encode()))
                with urllib.request.urlopen(req, timeout=10) as resp:
                    self._note_term(resp.headers)
            return attempt

        self._request(build, what="rendezvous DELETE %s" % key)
