"""Rendezvous key-value server over HTTP.

Equivalent of the reference's ``horovod/runner/http/http_server.py``
``RendezvousServer``: an in-memory KV store the launcher runs on the
driver host; workers PUT their address/topology and GET everyone else's —
the MPI-free bootstrap path (used by the TCP core the way Gloo used it),
and the re-rendezvous point for elastic mode.

Requests are authenticated with an HMAC of the body/path using the
launcher-distributed secret (reference: horovod/runner/common/util/secret.py).
"""

from __future__ import annotations

import hashlib
import hmac
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

SECRET_HEADER = "X-Hvd-Secret"


def compute_digest(secret: Optional[str], payload: bytes) -> str:
    if not secret:
        return ""
    return hmac.new(secret.encode(), payload, hashlib.sha256).hexdigest()


class _KvHandler(BaseHTTPRequestHandler):
    server_version = "HvdTpuRendezvous/1.0"

    def _authorized(self, payload: bytes) -> bool:
        secret = self.server.secret  # type: ignore[attr-defined]
        if not secret:
            return True
        given = self.headers.get(SECRET_HEADER, "")
        return hmac.compare_digest(given, compute_digest(secret, payload))

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length)
        if not self._authorized(body):
            self.send_response(403)
            self.end_headers()
            return
        with self.server.lock:  # type: ignore[attr-defined]
            self.server.store[self.path] = body  # type: ignore
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        if not self._authorized(self.path.encode()):
            self.send_response(403)
            self.end_headers()
            return
        with self.server.lock:  # type: ignore[attr-defined]
            value = self.server.store.get(self.path)  # type: ignore
        if value is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_DELETE(self):
        if not self._authorized(self.path.encode()):
            self.send_response(403)
            self.end_headers()
            return
        with self.server.lock:  # type: ignore[attr-defined]
            self.server.store.pop(self.path, None)  # type: ignore
        self.send_response(200)
        self.end_headers()

    def log_message(self, *args):  # quiet
        pass


class RendezvousServer:
    """In-memory KV over HTTP; scope keys like /global/addr/0
    (reference scopes: global/local/cross)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 secret: Optional[str] = None):
        self._httpd = ThreadingHTTPServer((host, port), _KvHandler)
        self._httpd.store = {}          # type: ignore[attr-defined]
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.secret = secret     # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    # Test/introspection access.
    def snapshot(self) -> Dict[str, bytes]:
        with self._httpd.lock:  # type: ignore[attr-defined]
            return dict(self._httpd.store)  # type: ignore[attr-defined]

    def reset(self):
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.store.clear()  # type: ignore[attr-defined]
