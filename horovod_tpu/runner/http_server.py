"""Rendezvous key-value server over HTTP.

Equivalent of the reference's ``horovod/runner/http/http_server.py``
``RendezvousServer``: an in-memory KV store the launcher runs on the
driver host; workers PUT their address/topology and GET everyone else's —
the MPI-free bootstrap path (used by the TCP core the way Gloo used it),
and the re-rendezvous point for elastic mode.

Requests are authenticated with an HMAC of the body/path using the
launcher-distributed secret (reference: horovod/runner/common/util/secret.py).

``GET /metrics`` is the one unauthenticated path: it serves the metrics
plane's Prometheus exposition (read-only operational telemetry, no
payload data, and scrapers cannot compute the launcher HMAC).  By
default it renders this process's registry; the elastic driver installs
a provider that merges every worker's snapshot into a fleet-wide scrape
(``metrics_provider``).

HA control plane: the server is no longer a SPOF.  With a journal
directory (runner/journal.py) every mutation is write-ahead journaled
and snapshotted, so a restarted server replays its store.  Leadership
carries a **monotonic term** (Raft-style fencing): every response
advertises the server's term in ``X-Hvd-Term``; clients echo the
highest term they have seen, and a server that receives proof of a
newer term **fences itself** — every subsequent KV request is answered
409 until (if ever) it is re-promoted.  A :class:`StandbyServer` tails
the leader's journal stream over ``GET /control/journal`` and promotes
itself with a bumped term when the leader's lease
(``HOROVOD_CONTROL_LEASE_SECS``) expires, so a paused-and-resumed old
leader's writes are rejected instead of forking the store
(split-brain-proof, test-asserted).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..common import faultline, metrics

LOG = logging.getLogger("horovod_tpu.runner.rendezvous")

SECRET_HEADER = "X-Hvd-Secret"
# Leader-term fencing header: servers advertise their term on every
# response; clients echo the highest term seen so a stale leader
# learns it has been superseded and fences itself.
TERM_HEADER = "X-Hvd-Term"
SEQ_HEADER = "X-Hvd-Seq"


def compute_digest(secret: Optional[str], payload: bytes) -> str:
    if not secret:
        return ""
    return hmac.new(secret.encode(), payload, hashlib.sha256).hexdigest()


class _KvHandler(BaseHTTPRequestHandler):
    server_version = "HvdTpuRendezvous/1.0"

    def _authorized(self, payload: bytes) -> bool:
        secret = self.server.secret  # type: ignore[attr-defined]
        if not secret:
            return True
        given = self.headers.get(SECRET_HEADER, "")
        return hmac.compare_digest(given, compute_digest(secret, payload))

    def _server_error(self, exc: Exception):
        """A handler exception is OUR fault, not the client's: answer
        500 so the client's retry layer classifies it as transient and
        backs off, instead of a torn connection it cannot tell apart
        from an auth drop."""
        LOG.warning("rendezvous handler failed on %s %s: %s",
                    self.command, self.path, exc)
        try:
            self._respond(500)
        except Exception:  # noqa: BLE001 — socket already gone
            pass

    def _respond(self, code: int, body: Optional[bytes] = None,
                 ctype: Optional[str] = None,
                 extra: Optional[Dict[str, str]] = None):
        """Send one response; every response carries the server's
        current term so clients track leadership passively."""
        self.send_response(code)
        self.send_header(TERM_HEADER,
                         str(self.server.term))  # type: ignore
        if ctype:
            self.send_header("Content-Type", ctype)
        if extra:
            for k, v in extra.items():
                self.send_header(k, v)
        if body is not None:
            self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body is not None:
            self.wfile.write(body)

    def _kv_gate(self) -> bool:
        """Per-request HA gate for KV verbs; True = request handled
        (caller returns).  Order: injected server death first (the
        ``kv.server.die`` seam — drop answers 503, a transient the
        client retry layer absorbs; die kills the process), then term
        fencing: a client that has seen a newer term fences this
        server; a fenced server or a follower answers 409 so the
        client rotates to the real leader."""
        if faultline.site("kv.server.die"):
            self._respond(503)
            return True
        srv = self.server
        client_term = self.headers.get(TERM_HEADER)
        with srv.lock:  # type: ignore[attr-defined]
            if client_term is not None:
                try:
                    ct = int(client_term)
                except ValueError:
                    ct = 0
                if ct > srv.term and not srv.fenced:  # type: ignore
                    LOG.warning(
                        "KV server (term %d) saw proof of newer term "
                        "%d: fencing self — every mutating request is "
                        "now rejected", srv.term, ct)  # type: ignore
                    metrics.event("control_leader_fenced",
                                  own_term=srv.term,  # type: ignore
                                  seen_term=ct)
                    srv.fenced = True  # type: ignore[attr-defined]
            rejected = srv.fenced or srv.follower  # type: ignore
        if rejected:
            self._respond(409)
            return True
        return False

    def do_POST(self):
        """``POST /serve/<deployment>`` — the serving plane's request
        endpoint (serving/router.py installs the provider).  Rides the
        same HMAC auth as the KV paths: in-harness synthetic load
        holds the launcher secret; a public front door would terminate
        auth upstream.  No provider installed = 404 (this server is a
        rendezvous KV first)."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if not self._authorized(body):
                self._respond(403)
                return
            provider = getattr(self.server, "serving_provider", None)
            if provider is None or not self.path.startswith("/serve/"):
                self._respond(404)
                return
            deployment = self.path[len("/serve/"):]
            out = provider(deployment, body)
        except Exception as exc:  # noqa: BLE001 — report as 5xx
            self._server_error(exc)
            return
        self._respond(200, out, "application/json")

    def do_PUT(self):
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if not self._authorized(body):
                self._respond(403)
                return
            if self._kv_gate():
                return
            with self.server.lock:  # type: ignore[attr-defined]
                jnl = self.server.journal  # type: ignore[attr-defined]
                if jnl is not None:
                    # store IS journal.state: the record append applies
                    # the mutation, so don't double-apply here.
                    jnl.record_put(self.path, body)
                else:
                    self.server.store[self.path] = body  # type: ignore
        except Exception as exc:  # noqa: BLE001 — report as 5xx
            self._server_error(exc)
            return
        self._respond(200)

    def _serve_metrics(self):
        provider = getattr(self.server, "metrics_provider", None)
        from ..common import metrics as _metrics
        text = provider() if provider is not None \
            else _metrics.render_prometheus()
        self._respond(200, text.encode(),
                      "text/plain; version=0.0.4; charset=utf-8")

    def _serve_skew(self):
        """``GET /skew`` — the skew observatory's fleet JSON (per-rank
        straggler scores, detections, plan-staleness classes).  Same
        auth stance as ``/metrics``: read-only operational telemetry
        with no payload data, served unauthenticated so fleet tooling
        that cannot compute the launcher HMAC can still watch it.  No
        provider installed (non-elastic servers) = 404."""
        provider = getattr(self.server, "skew_provider", None)
        if provider is None:
            self._respond(404)
            return
        self._respond(200, provider().encode(), "application/json")

    def _serve_control(self):
        """``GET /control/...`` — the HA replication/introspection
        endpoints (HMAC over the full path, query string included):

        * ``/control/status`` — ``{term, seq, fenced, role}``.
        * ``/control/journal?since=N`` — concatenated journal frames
          newer than N (the standby's replication feed), with the
          current term and sequence in response headers.
        * ``/control/dump`` — the full store (values base64) + term +
          seq, for standby bootstrap and bitwise recovery assertions.
        """
        srv = self.server
        parsed = urllib.parse.urlparse(self.path)
        with srv.lock:  # type: ignore[attr-defined]
            term = srv.term  # type: ignore[attr-defined]
            jnl = srv.journal  # type: ignore[attr-defined]
            seq = jnl.seq if jnl is not None else 0
            if parsed.path == "/control/status":
                body = json.dumps({
                    "term": term, "seq": seq,
                    "fenced": bool(srv.fenced),  # type: ignore
                    "role": ("follower" if srv.follower  # type: ignore
                             else "leader"),
                }, sort_keys=True).encode()
                self._respond(200, body, "application/json")
                return
            if parsed.path == "/control/dump":
                body = json.dumps({
                    "term": term, "seq": seq,
                    "kv": {k: base64.b64encode(v).decode("ascii")
                           for k, v in srv.store.items()},  # type: ignore
                }, sort_keys=True).encode()
                self._respond(200, body, "application/json")
                return
            if parsed.path == "/control/journal":
                if jnl is None:
                    self._respond(404)
                    return
                qs = urllib.parse.parse_qs(parsed.query)
                try:
                    since = int(qs.get("since", ["0"])[0])
                except ValueError:
                    since = 0
                tail = jnl.tail_since(since)
                self._respond(200, tail, "application/octet-stream",
                              extra={SEQ_HEADER: str(seq)})
                return
        self._respond(404)

    def do_GET(self):
        try:
            if self.path == "/metrics":
                self._serve_metrics()
                return
            if self.path == "/skew":
                self._serve_skew()
                return
            if not self._authorized(self.path.encode()):
                self._respond(403)
                return
            if self.path.startswith("/control/"):
                self._serve_control()
                return
            if self._kv_gate():
                return
            with self.server.lock:  # type: ignore[attr-defined]
                value = self.server.store.get(self.path)  # type: ignore
        except Exception as exc:  # noqa: BLE001 — report as 5xx
            self._server_error(exc)
            return
        if value is None:
            self._respond(404)
            return
        self._respond(200, value)

    def do_DELETE(self):
        try:
            if not self._authorized(self.path.encode()):
                self._respond(403)
                return
            if self._kv_gate():
                return
            with self.server.lock:  # type: ignore[attr-defined]
                jnl = self.server.journal  # type: ignore[attr-defined]
                if jnl is not None:
                    if self.path in self.server.store:  # type: ignore
                        jnl.record_delete(self.path)
                else:
                    self.server.store.pop(self.path, None)  # type: ignore
        except Exception as exc:  # noqa: BLE001 — report as 5xx
            self._server_error(exc)
            return
        self._respond(200)

    def log_message(self, *args):  # quiet
        pass


class RendezvousServer:
    """In-memory KV over HTTP; scope keys like /global/addr/0
    (reference scopes: global/local/cross).

    With ``journal_dir`` the store is durably journaled (replayed on
    construction) and the server participates in term-fenced
    leadership; ``follower=True`` starts it fenced-for-writes as a
    warm standby (see :class:`StandbyServer`) until :meth:`promote`."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 secret: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 follower: bool = False):
        from . import journal as _journal
        self._httpd = ThreadingHTTPServer((host, port), _KvHandler)
        jnl = (_journal.ControlJournal(journal_dir)
               if journal_dir else None)
        # With a journal the store IS the journal's replayed state
        # (one dict object): mutations flow through record_* appends,
        # which apply to it — the handler never double-writes.
        self._httpd.store = (jnl.state if jnl is not None  # type: ignore
                             else {})
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.secret = secret     # type: ignore[attr-defined]
        self._httpd.journal = jnl       # type: ignore[attr-defined]
        self._httpd.term = max(1, jnl.term if jnl else 1)  # type: ignore
        self._httpd.fenced = False      # type: ignore[attr-defined]
        self._httpd.follower = follower  # type: ignore[attr-defined]
        # /metrics renderer; None = this process's own registry.
        self._httpd.metrics_provider = None  # type: ignore[attr-defined]
        # POST /serve/<deployment> handler; None = endpoint disabled.
        self._httpd.serving_provider = None  # type: ignore[attr-defined]
        # GET /skew renderer; None = endpoint disabled (404).
        self._httpd.skew_provider = None  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        if not follower:
            metrics.gauge("control_leader_term").set(self.term)

    @property
    def metrics_provider(self):
        return self._httpd.metrics_provider  # type: ignore[attr-defined]

    @metrics_provider.setter
    def metrics_provider(self, fn):
        """Install a () -> str renderer for ``GET /metrics`` (the
        elastic driver's fleet-wide merge)."""
        self._httpd.metrics_provider = fn  # type: ignore[attr-defined]

    @property
    def serving_provider(self):
        return self._httpd.serving_provider  # type: ignore[attr-defined]

    @serving_provider.setter
    def serving_provider(self, fn):
        """Install a (deployment: str, body: bytes) -> bytes handler
        for ``POST /serve/<deployment>`` (the serving router's HTTP
        front door, serving/router.py ``install_http_frontend``)."""
        self._httpd.serving_provider = fn  # type: ignore[attr-defined]

    @property
    def skew_provider(self):
        return self._httpd.skew_provider  # type: ignore[attr-defined]

    @skew_provider.setter
    def skew_provider(self, fn):
        """Install a () -> str JSON renderer for ``GET /skew`` (the
        elastic driver's skew observatory, common/skew.py)."""
        self._httpd.skew_provider = fn  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def term(self) -> int:
        return self._httpd.term  # type: ignore[attr-defined]

    @property
    def fenced(self) -> bool:
        return self._httpd.fenced  # type: ignore[attr-defined]

    @property
    def follower(self) -> bool:
        return self._httpd.follower  # type: ignore[attr-defined]

    @property
    def seq(self) -> int:
        jnl = self._httpd.journal  # type: ignore[attr-defined]
        return jnl.seq if jnl is not None else 0

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        jnl = self._httpd.journal  # type: ignore[attr-defined]
        if jnl is not None:
            jnl.close()

    # -- HA control plane ------------------------------------------------

    def put_local(self, key: str, value: bytes):
        """Driver-side direct put (no HTTP round-trip to ourselves):
        how the elastic driver journals its control record."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            jnl = self._httpd.journal  # type: ignore[attr-defined]
            if jnl is not None:
                jnl.record_put(key, value)
            else:
                self._httpd.store[key] = value  # type: ignore

    def promote(self, new_term: int):
        """Take leadership at ``new_term``: unfence, stop following,
        journal the term bump so it survives OUR crash too."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.term = max(  # type: ignore[attr-defined]
                self._httpd.term, int(new_term))  # type: ignore
            self._httpd.fenced = False   # type: ignore[attr-defined]
            self._httpd.follower = False  # type: ignore[attr-defined]
            jnl = self._httpd.journal  # type: ignore[attr-defined]
            if jnl is not None:
                jnl.record_term(self._httpd.term)  # type: ignore
            term = self._httpd.term  # type: ignore[attr-defined]
        metrics.gauge("control_leader_term").set(term)
        LOG.warning("KV server promoted to leader at term %d", term)

    def apply_tail(self, blob: bytes, leader_term: int):
        """Follower path: journal + apply a leader's replication
        stream (store updates ride the shared state dict)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            jnl = self._httpd.journal  # type: ignore[attr-defined]
            if jnl is not None:
                jnl.apply_frames(blob)
                self._httpd.term = max(  # type: ignore[attr-defined]
                    self._httpd.term,  # type: ignore[attr-defined]
                    jnl.term, int(leader_term))

    def adopt_snapshot(self, kv: Dict[str, bytes], term: int, seq: int):
        """Follower bootstrap: adopt a leader's full dump (store,
        term, sequence) and durably snapshot it."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            jnl = self._httpd.journal  # type: ignore[attr-defined]
            if jnl is not None:
                jnl.adopt_snapshot(kv, term, seq)
            else:
                self._httpd.store.clear()  # type: ignore[attr-defined]
                self._httpd.store.update(kv)  # type: ignore
            self._httpd.term = max(  # type: ignore[attr-defined]
                self._httpd.term, int(term))  # type: ignore

    # Test/introspection access.
    def snapshot(self) -> Dict[str, bytes]:
        with self._httpd.lock:  # type: ignore[attr-defined]
            return dict(self._httpd.store)  # type: ignore[attr-defined]

    def reset(self):
        with self._httpd.lock:  # type: ignore[attr-defined]
            jnl = self._httpd.journal  # type: ignore[attr-defined]
            if jnl is not None:
                jnl.record_reset()
            else:
                self._httpd.store.clear()  # type: ignore[attr-defined]


class StandbyServer:
    """Warm standby for a rendezvous KV leader: a follower
    :class:`RendezvousServer` (journaled, write-fenced) plus a tail
    thread that bootstraps from the leader's ``/control/dump`` and
    then replicates its journal stream over the HMAC'd HTTP plane.
    When the leader stays unreachable past the lease
    (``HOROVOD_CONTROL_LEASE_SECS``) the standby promotes itself with
    a bumped term — from then on the old leader's writes are fenced
    (409) by term comparison wherever they land."""

    def __init__(self, leader_addr: str, journal_dir: str,
                 secret: Optional[str] = None,
                 host: str = "0.0.0.0", port: int = 0,
                 lease: Optional[float] = None):
        from . import journal as _journal
        self.leader_addr = leader_addr
        self.secret = secret
        self.server = RendezvousServer(host=host, port=port,
                                       secret=secret,
                                       journal_dir=journal_dir,
                                       follower=True)
        self._lease = (lease if lease is not None
                       else _journal.lease_secs())
        self._leader_term = 1
        self._bootstrapped = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def promoted(self) -> bool:
        return not self.server.follower

    def _leader_get(self, path: str) -> "tuple[bytes, Dict[str, str]]":
        """One unretried GET against the leader (the poll cadence is
        the retry policy); returns (body, headers)."""
        import urllib.request
        url = "http://" + self.leader_addr + path
        headers = {}
        if self.secret:
            headers[SECRET_HEADER] = compute_digest(
                self.secret, path.encode())
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.read(), dict(resp.headers)

    def _poll_once(self) -> bool:
        """One replication poll; True on success."""
        if faultline.site("kv.standby.partition"):
            LOG.warning("standby replication poll dropped (faultline "
                        "kv.standby.partition)")
            return False
        try:
            if not self._bootstrapped:
                body, _hdrs = self._leader_get("/control/dump")
                doc = json.loads(body.decode())
                self.server.adopt_snapshot(
                    {k: base64.b64decode(v.encode("ascii"))
                     for k, v in doc["kv"].items()},
                    int(doc["term"]), int(doc["seq"]))
                self._leader_term = max(self._leader_term,
                                        int(doc["term"]))
                self._bootstrapped = True
            tail, hdrs = self._leader_get(
                "/control/journal?since=%d" % self.server.seq)
            leader_term = int(hdrs.get(TERM_HEADER, "1"))
            self._leader_term = max(self._leader_term, leader_term)
            if tail:
                self.server.apply_tail(tail, leader_term)
            return True
        except Exception as exc:  # noqa: BLE001 — liveness signal
            LOG.debug("standby poll of leader %s failed: %s",
                      self.leader_addr, exc)
            return False

    def _run(self):
        from .http_client import jittered
        last_ok = time.monotonic()
        interval = max(0.05, self._lease / 4.0)
        while not self._stop.is_set():
            if self._poll_once():
                last_ok = time.monotonic()
            elif (time.monotonic() - last_ok > self._lease
                  and not self.promoted):
                new_term = max(self._leader_term,
                               self.server.term) + 1
                LOG.warning(
                    "leader %s unreachable for %.1fs (lease %.1fs): "
                    "standby taking over at term %d",
                    self.leader_addr,
                    time.monotonic() - last_ok, self._lease, new_term)
                self.server.promote(new_term)
                metrics.counter("control_failovers_total").inc()
                metrics.event("control_failover",
                              old_leader=self.leader_addr,
                              term=new_term)
            if self.promoted:
                return  # leaders do not tail anyone
            self._stop.wait(jittered(interval))

    def start(self) -> int:
        port = self.server.start()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return port

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.server.stop()
