"""Rendezvous key-value server over HTTP.

Equivalent of the reference's ``horovod/runner/http/http_server.py``
``RendezvousServer``: an in-memory KV store the launcher runs on the
driver host; workers PUT their address/topology and GET everyone else's —
the MPI-free bootstrap path (used by the TCP core the way Gloo used it),
and the re-rendezvous point for elastic mode.

Requests are authenticated with an HMAC of the body/path using the
launcher-distributed secret (reference: horovod/runner/common/util/secret.py).

``GET /metrics`` is the one unauthenticated path: it serves the metrics
plane's Prometheus exposition (read-only operational telemetry, no
payload data, and scrapers cannot compute the launcher HMAC).  By
default it renders this process's registry; the elastic driver installs
a provider that merges every worker's snapshot into a fleet-wide scrape
(``metrics_provider``).
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

LOG = logging.getLogger("horovod_tpu.runner.rendezvous")

SECRET_HEADER = "X-Hvd-Secret"


def compute_digest(secret: Optional[str], payload: bytes) -> str:
    if not secret:
        return ""
    return hmac.new(secret.encode(), payload, hashlib.sha256).hexdigest()


class _KvHandler(BaseHTTPRequestHandler):
    server_version = "HvdTpuRendezvous/1.0"

    def _authorized(self, payload: bytes) -> bool:
        secret = self.server.secret  # type: ignore[attr-defined]
        if not secret:
            return True
        given = self.headers.get(SECRET_HEADER, "")
        return hmac.compare_digest(given, compute_digest(secret, payload))

    def _server_error(self, exc: Exception):
        """A handler exception is OUR fault, not the client's: answer
        500 so the client's retry layer classifies it as transient and
        backs off, instead of a torn connection it cannot tell apart
        from an auth drop."""
        LOG.warning("rendezvous handler failed on %s %s: %s",
                    self.command, self.path, exc)
        try:
            self.send_response(500)
            self.end_headers()
        except Exception:  # noqa: BLE001 — socket already gone
            pass

    def do_POST(self):
        """``POST /serve/<deployment>`` — the serving plane's request
        endpoint (serving/router.py installs the provider).  Rides the
        same HMAC auth as the KV paths: in-harness synthetic load
        holds the launcher secret; a public front door would terminate
        auth upstream.  No provider installed = 404 (this server is a
        rendezvous KV first)."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if not self._authorized(body):
                self.send_response(403)
                self.end_headers()
                return
            provider = getattr(self.server, "serving_provider", None)
            if provider is None or not self.path.startswith("/serve/"):
                self.send_response(404)
                self.end_headers()
                return
            deployment = self.path[len("/serve/"):]
            out = provider(deployment, body)
        except Exception as exc:  # noqa: BLE001 — report as 5xx
            self._server_error(exc)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def do_PUT(self):
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if not self._authorized(body):
                self.send_response(403)
                self.end_headers()
                return
            with self.server.lock:  # type: ignore[attr-defined]
                self.server.store[self.path] = body  # type: ignore
        except Exception as exc:  # noqa: BLE001 — report as 5xx
            self._server_error(exc)
            return
        self.send_response(200)
        self.end_headers()

    def _serve_metrics(self):
        provider = getattr(self.server, "metrics_provider", None)
        from ..common import metrics as _metrics
        text = provider() if provider is not None \
            else _metrics.render_prometheus()
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _serve_skew(self):
        """``GET /skew`` — the skew observatory's fleet JSON (per-rank
        straggler scores, detections, plan-staleness classes).  Same
        auth stance as ``/metrics``: read-only operational telemetry
        with no payload data, served unauthenticated so fleet tooling
        that cannot compute the launcher HMAC can still watch it.  No
        provider installed (non-elastic servers) = 404."""
        provider = getattr(self.server, "skew_provider", None)
        if provider is None:
            self.send_response(404)
            self.end_headers()
            return
        body = provider().encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        try:
            if self.path == "/metrics":
                self._serve_metrics()
                return
            if self.path == "/skew":
                self._serve_skew()
                return
            if not self._authorized(self.path.encode()):
                self.send_response(403)
                self.end_headers()
                return
            with self.server.lock:  # type: ignore[attr-defined]
                value = self.server.store.get(self.path)  # type: ignore
        except Exception as exc:  # noqa: BLE001 — report as 5xx
            self._server_error(exc)
            return
        if value is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_DELETE(self):
        try:
            if not self._authorized(self.path.encode()):
                self.send_response(403)
                self.end_headers()
                return
            with self.server.lock:  # type: ignore[attr-defined]
                self.server.store.pop(self.path, None)  # type: ignore
        except Exception as exc:  # noqa: BLE001 — report as 5xx
            self._server_error(exc)
            return
        self.send_response(200)
        self.end_headers()

    def log_message(self, *args):  # quiet
        pass


class RendezvousServer:
    """In-memory KV over HTTP; scope keys like /global/addr/0
    (reference scopes: global/local/cross)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 secret: Optional[str] = None):
        self._httpd = ThreadingHTTPServer((host, port), _KvHandler)
        self._httpd.store = {}          # type: ignore[attr-defined]
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.secret = secret     # type: ignore[attr-defined]
        # /metrics renderer; None = this process's own registry.
        self._httpd.metrics_provider = None  # type: ignore[attr-defined]
        # POST /serve/<deployment> handler; None = endpoint disabled.
        self._httpd.serving_provider = None  # type: ignore[attr-defined]
        # GET /skew renderer; None = endpoint disabled (404).
        self._httpd.skew_provider = None  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def metrics_provider(self):
        return self._httpd.metrics_provider  # type: ignore[attr-defined]

    @metrics_provider.setter
    def metrics_provider(self, fn):
        """Install a () -> str renderer for ``GET /metrics`` (the
        elastic driver's fleet-wide merge)."""
        self._httpd.metrics_provider = fn  # type: ignore[attr-defined]

    @property
    def serving_provider(self):
        return self._httpd.serving_provider  # type: ignore[attr-defined]

    @serving_provider.setter
    def serving_provider(self, fn):
        """Install a (deployment: str, body: bytes) -> bytes handler
        for ``POST /serve/<deployment>`` (the serving router's HTTP
        front door, serving/router.py ``install_http_frontend``)."""
        self._httpd.serving_provider = fn  # type: ignore[attr-defined]

    @property
    def skew_provider(self):
        return self._httpd.skew_provider  # type: ignore[attr-defined]

    @skew_provider.setter
    def skew_provider(self, fn):
        """Install a () -> str JSON renderer for ``GET /skew`` (the
        elastic driver's skew observatory, common/skew.py)."""
        self._httpd.skew_provider = fn  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    # Test/introspection access.
    def snapshot(self) -> Dict[str, bytes]:
        with self._httpd.lock:  # type: ignore[attr-defined]
            return dict(self._httpd.store)  # type: ignore[attr-defined]

    def reset(self):
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.store.clear()  # type: ignore[attr-defined]
