"""Control-plane write-ahead journal: durable rendezvous KV + driver state.

The rendezvous KV server (runner/http_server.py) and the elastic
driver's slot bookkeeping were the last in-memory singletons in the
robustness story — a driver-host crash killed the world even though
every worker and spill blob outlived it.  With
``HOROVOD_CONTROL_JOURNAL_DIR`` set, every KV mutation is appended to a
write-ahead log before it is acknowledged, and the full store is
periodically snapshotted, both in the r10 spill wire format
(MAGIC + seq u64 + len u64 + crc32 + payload, shared framing from
common/atomicio.py with this plane's own MAGIC):

* ``wal-<first_seq>.walseg`` — append-only segments of framed JSON
  records, fsynced per append.  Record ops: ``put``/``del``/``reset``
  (store mutations, values base64), ``term`` (leadership changes).
* ``snap-<seq>.snap`` — atomic whole-store snapshots
  (``{"term": t, "kv": {key: b64}}``), written every
  ``SNAPSHOT_EVERY`` records; the newest ``KEEP_SNAPSHOTS`` are kept
  and fully-covered segments are deleted (keep-last-K compaction).

Replay loads the newest VALID snapshot (corrupt-newest falls back down
the chain, exactly like spill restore) and applies every journal
record with a newer sequence.  Torn or corrupt records — injectable
via the ``kv.journal.torn`` fault site — are skipped loudly
(``kv_journal_skipped_records_total``) with a resync to the next magic
boundary, never silently trusted.

The journal directory holds the launcher secret (inside the driver's
control record) and is created mode 0700 — treat it like a credential
store, not like scratch space.
"""

from __future__ import annotations

import base64
import json
import logging
import os
from typing import Callable, Dict, List, Optional, Tuple

from ..common import atomicio, faultline, metrics
from ..common.envutil import env_float

LOG = logging.getLogger("horovod_tpu.runner.journal")

MAGIC = b"HVDKVWAL1\n"
_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".walseg"
_SNAP_PREFIX = "snap-"
_SNAP_SUFFIX = ".snap"

# Snapshot cadence and retained history.  Keep-last-K is a constant,
# not an env: the chain only needs depth for corrupt-newest fallback,
# and the segments between snapshots bound disk growth regardless.
SNAPSHOT_EVERY = 256
KEEP_SNAPSHOTS = 3

# KV key under which the elastic driver journals its own bookkeeping
# (epoch, assignments, worker addresses, blacklist, secret) so a
# restarted driver can adopt the old world instead of re-forming it.
CONTROL_KEY = "/__control__/driver"


def control_journal_dir(tenant: Optional[str] = None) -> Optional[str]:
    """The control-plane journal directory
    (``HOROVOD_CONTROL_JOURNAL_DIR``); None disables journaling
    entirely.  Like spill_dir, a multi-tenant pod gives each tenant its
    own ``tenant-<id>`` subdirectory (explicit ``tenant`` argument wins
    over ``HOROVOD_TENANT_ID``) so one tenant's control history can
    never be adopted by another's driver."""
    base = os.environ.get("HOROVOD_CONTROL_JOURNAL_DIR") or None
    if base is None:
        return None
    if tenant is None:
        tenant = os.environ.get("HOROVOD_TENANT_ID")
    if tenant:
        return os.path.join(base, "tenant-%s" % tenant)
    return base


def lease_secs() -> float:
    """Leader lease (``HOROVOD_CONTROL_LEASE_SECS``, default 5 s,
    floor 0.1): a warm standby that cannot reach the active KV server
    for this long promotes itself with a bumped term."""
    return env_float("HOROVOD_CONTROL_LEASE_SECS", 5.0, minimum=0.1)


def recovery_deadline() -> float:
    """Driver-adoption budget (``HOROVOD_CONTROL_RECOVERY_DEADLINE``,
    default 60 s): how long a restarted driver waits for journaled
    workers to prove liveness (answer a ping / re-register) before
    giving up on adoption and falling back to ordinary world
    re-formation (where the r2 elastic deadline governs)."""
    return env_float("HOROVOD_CONTROL_RECOVERY_DEADLINE", 60.0,
                     minimum=0.0)


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode("ascii")


def _unb64(s: str) -> bytes:
    return base64.b64decode(s.encode("ascii"))


def _apply_op(op: Dict, kv: Dict[str, bytes], term: int) -> int:
    """Apply one journal record to (kv, term); returns the new term."""
    kind = op.get("op")
    if kind == "put":
        kv[op["k"]] = _unb64(op["v"])
    elif kind == "del":
        kv.pop(op["k"], None)
    elif kind == "reset":
        kv.clear()
    elif kind == "term":
        term = max(term, int(op["term"]))
    return term


def parse_frames(blob: bytes,
                 on_skip: Optional[Callable[[str], None]] = None
                 ) -> List[Tuple[int, bytes, Dict]]:
    """Parse a byte stream of concatenated journal frames into
    ``(seq, frame_bytes, op)`` triples.  A torn or corrupt record is
    skipped loudly (``on_skip`` + metrics) and parsing resyncs at the
    next MAGIC boundary — one bad record costs itself, not the tail of
    the segment."""
    out: List[Tuple[int, bytes, Dict]] = []
    head_len = len(MAGIC) + atomicio.HEADER.size
    pos = 0

    def skip(reason: str, resync_from: int):
        metrics.counter("kv_journal_skipped_records_total").inc()
        metrics.event("kv_journal_skip", reason=reason)
        if on_skip:
            on_skip(reason)
        return blob.find(MAGIC, resync_from)

    while 0 <= pos < len(blob):
        if not blob.startswith(MAGIC, pos):
            pos = skip("bad magic at offset %d" % pos, pos + 1)
            continue
        if pos + head_len > len(blob):
            pos = skip("truncated header at offset %d" % pos, pos + 1)
            continue
        seq, payload_len, _crc = atomicio.HEADER.unpack(
            blob[pos + len(MAGIC):pos + head_len])
        end = pos + head_len + payload_len
        frame_bytes = blob[pos:end]
        try:
            _seq, payload = atomicio.unframe(MAGIC, frame_bytes)
            op = json.loads(payload.decode())
        except (atomicio.RecordCorrupt, ValueError) as exc:
            pos = skip("record seq=%d at offset %d: %s"
                       % (seq, pos, exc), pos + 1)
            continue
        out.append((seq, frame_bytes, op))
        pos = end
    return out


def _list(d: str, prefix: str, suffix: str) -> List[Tuple[int, str]]:
    """(seq, path), ascending by seq, for journal files of one kind."""
    out = []
    if not os.path.isdir(d):
        return out
    for name in os.listdir(d):
        if not (name.startswith(prefix) and name.endswith(suffix)):
            continue
        try:
            seq = int(name[len(prefix):-len(suffix)])
        except ValueError:
            continue
        out.append((seq, os.path.join(d, name)))
    out.sort()
    return out


def replay(d: str) -> Tuple[Dict[str, bytes], int, int]:
    """Reconstruct ``(kv, term, last_seq)`` from a journal directory:
    newest valid snapshot (fallback chain on corruption) + every
    journal record with a newer sequence."""
    kv: Dict[str, bytes] = {}
    term, snap_seq = 0, 0
    for seq, path in reversed(_list(d, _SNAP_PREFIX, _SNAP_SUFFIX)):
        try:
            with open(path, "rb") as f:
                file_seq, payload = atomicio.unframe(MAGIC, f.read())
            doc = json.loads(payload.decode())
            kv = {k: _unb64(v) for k, v in doc["kv"].items()}
            term, snap_seq = int(doc["term"]), file_seq
            break
        except (OSError, atomicio.RecordCorrupt, ValueError, KeyError) as exc:
            metrics.counter("kv_journal_skipped_records_total").inc()
            LOG.warning("skipping corrupt control snapshot %s (%s); "
                        "falling back to the previous one", path, exc)
    last_seq = snap_seq
    for _first, path in _list(d, _SEG_PREFIX, _SEG_SUFFIX):
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as exc:
            LOG.warning("unreadable journal segment %s: %s", path, exc)
            continue
        for seq, _frame, op in parse_frames(
                blob, on_skip=lambda r, p=path: LOG.warning(
                    "skipping corrupt control-journal record in %s: %s",
                    p, r)):
            if seq <= snap_seq:
                continue
            term = _apply_op(op, kv, term)
            last_seq = max(last_seq, seq)
    return kv, term, last_seq


def peek_control_record(d: Optional[str]) -> Optional[Dict]:
    """The driver's journaled control record (parsed JSON), or None
    when there is no journal / no record — the restarted driver's
    adoption probe, read without taking ownership of the journal."""
    if not d or not os.path.isdir(d):
        return None
    kv, _term, _seq = replay(d)
    blob = kv.get(CONTROL_KEY)
    if blob is None:
        return None
    try:
        return json.loads(blob.decode())
    except ValueError as exc:
        LOG.warning("journaled control record is unparseable (%s); "
                    "ignoring it", exc)
        return None


class ControlJournal:
    """One process's handle on a journal directory: replays on open,
    appends framed records with per-record fsync, snapshots + compacts
    on cadence.  Not thread-safe by itself — the KV server serializes
    calls under its store lock."""

    def __init__(self, d: str):
        self.dir = d
        os.makedirs(d, mode=0o700, exist_ok=True)
        try:
            os.chmod(d, 0o700)  # pre-existing dir: tighten anyway
        except OSError:
            pass
        self.state, self.term, self.seq = replay(d)
        self._since_snapshot = 0
        self._seg_fd = None
        self._open_segment(self.seq + 1)

    def _open_segment(self, first_seq: int):
        if self._seg_fd is not None:
            try:
                self._seg_fd.close()
            except OSError:
                pass
        path = os.path.join(self.dir, "%s%020d%s"
                            % (_SEG_PREFIX, first_seq, _SEG_SUFFIX))
        self._seg_fd = open(path, "ab")

    def append(self, op: Dict) -> int:
        """Journal one record (fsync before return) and apply it to
        the in-memory replayed state; returns its sequence number."""
        seq = self.seq + 1
        blob = atomicio.frame(MAGIC, seq, json.dumps(
            op, sort_keys=True).encode())
        if faultline.site("kv.journal.torn"):
            # Injected torn append: the record lands truncated
            # mid-payload — the shape a power loss mid-fsync leaves.
            blob = blob[:len(MAGIC) + atomicio.HEADER.size
                        + max(1, (len(blob) - len(MAGIC)
                                  - atomicio.HEADER.size) // 2)]
            LOG.warning("control-journal record seq=%d torn "
                        "(faultline kv.journal.torn)", seq)
        self._seg_fd.write(blob)
        self._seg_fd.flush()
        os.fsync(self._seg_fd.fileno())
        metrics.counter("kv_journal_bytes_total").inc(len(blob))
        self.seq = seq
        self.term = _apply_op(op, self.state, self.term)
        self._since_snapshot += 1
        if self._since_snapshot >= SNAPSHOT_EVERY:
            self.snapshot()
        return seq

    def record_put(self, key: str, value: bytes) -> int:
        return self.append({"op": "put", "k": key, "v": _b64(value)})

    def record_delete(self, key: str) -> int:
        return self.append({"op": "del", "k": key})

    def record_reset(self) -> int:
        return self.append({"op": "reset"})

    def record_term(self, term: int) -> int:
        return self.append({"op": "term", "term": int(term)})

    def snapshot(self):
        """Atomic whole-store snapshot at the current sequence, then
        keep-last-K compaction: old snapshots beyond ``KEEP_SNAPSHOTS``
        and segments fully covered by the oldest retained snapshot are
        deleted, and appends roll into a fresh segment."""
        doc = {"term": self.term,
               "kv": {k: _b64(v) for k, v in self.state.items()}}
        blob = atomicio.frame(MAGIC, self.seq,
                              json.dumps(doc, sort_keys=True).encode())
        atomicio.write_atomic(
            self.dir, "%s%020d%s" % (_SNAP_PREFIX, self.seq,
                                     _SNAP_SUFFIX), blob)
        self._since_snapshot = 0
        snaps = _list(self.dir, _SNAP_PREFIX, _SNAP_SUFFIX)
        for _seq, path in snaps[:-KEEP_SNAPSHOTS]:
            try:
                os.unlink(path)
            except OSError:
                pass
        retained = snaps[-KEEP_SNAPSHOTS:]
        oldest_kept = retained[0][0] if retained else 0
        # A segment is droppable when every record in it is at or
        # below the oldest retained snapshot — i.e. the NEXT segment
        # starts at or below oldest_kept + 1.
        segs = _list(self.dir, _SEG_PREFIX, _SEG_SUFFIX)
        for i, (first, path) in enumerate(segs):
            nxt = segs[i + 1][0] if i + 1 < len(segs) else self.seq + 1
            if nxt <= oldest_kept + 1:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        atomicio.sweep_tmp(self.dir)
        self._open_segment(self.seq + 1)

    def adopt_snapshot(self, kv: Dict[str, bytes], term: int, seq: int):
        """Standby bootstrap: adopt a leader's full dump as our own
        durable snapshot, so the subsequent journal tail (whose
        records carry the leader's sequence numbers) lands on the same
        baseline.  ``max`` semantics keep anything newer we already
        hold (a restarted standby must not move backwards).  The
        in-place clear/update matters: the KV server's store IS this
        dict object."""
        self.state.clear()
        self.state.update(kv)
        self.term = max(self.term, int(term))
        self.seq = max(self.seq, int(seq))
        self.snapshot()

    def tail_since(self, since_seq: int) -> bytes:
        """Concatenated frames of every on-disk record newer than
        ``since_seq`` — the standby's replication feed (served over
        ``GET /control/journal?since=N``)."""
        out = []
        for _first, path in _list(self.dir, _SEG_PREFIX, _SEG_SUFFIX):
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                continue
            for seq, frame_bytes, _op in parse_frames(blob):
                if seq > since_seq:
                    out.append(frame_bytes)
        return b"".join(out)

    def apply_frames(self, blob: bytes) -> List[Dict]:
        """Apply a leader's tail stream: each record newer than our
        own sequence is journaled verbatim (preserving the leader's
        sequence numbers) and applied; already-seen records are
        skipped.  Returns the ops applied, in order."""
        applied = []
        for seq, frame_bytes, op in parse_frames(blob):
            if seq <= self.seq:
                continue
            self._seg_fd.write(frame_bytes)
            self._seg_fd.flush()
            os.fsync(self._seg_fd.fileno())
            metrics.counter("kv_journal_bytes_total").inc(
                len(frame_bytes))
            self.seq = seq
            self.term = _apply_op(op, self.state, self.term)
            self._since_snapshot += 1
            applied.append(op)
        if self._since_snapshot >= SNAPSHOT_EVERY:
            self.snapshot()
        return applied

    def close(self):
        if self._seg_fd is not None:
            try:
                self._seg_fd.close()
            except OSError:
                pass
            self._seg_fd = None
