"""Standalone rendezvous KV server / warm standby launcher.

The embedded driver KV (elastic/driver.py) dies with the driver
process; running the KV out-of-process with this module decouples the
control plane's lifetime from the driver's::

    # the active leader, journaled
    python -m horovod_tpu.runner.kv_server --port 18888 \
        --journal-dir /durable/kv-a

    # a warm standby tailing it (promotes on lease expiry)
    python -m horovod_tpu.runner.kv_server --port 18889 \
        --journal-dir /durable/kv-b --standby-of 127.0.0.1:18888

Workers and the driver reach whichever is alive via
``HOROVOD_RENDEZVOUS_ENDPOINTS=host:18888,host:18889`` — the client
rotates on transient-exhaustion and 409 fences (runner/http_client.py).

Auth: ``--secret-env`` names the env var holding the launcher secret
(default ``HOROVOD_SECRET_KEY``); unset/empty runs the plane
unauthenticated (harness-internal networks only).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from . import journal
from .http_server import RendezvousServer, StandbyServer


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.runner.kv_server",
        description="standalone rendezvous KV server / warm standby")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--journal-dir", default=None,
                        help="write-ahead journal directory (default: "
                             "HOROVOD_CONTROL_JOURNAL_DIR)")
    parser.add_argument("--standby-of", default=None, metavar="HOST:PORT",
                        help="run as a warm standby tailing this "
                             "leader; promotes on lease expiry")
    parser.add_argument("--secret-env", default="HOROVOD_SECRET_KEY",
                        help="env var holding the HMAC secret "
                             "(empty value = auth disabled)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    secret = os.environ.get(args.secret_env) or None
    journal_dir = args.journal_dir or journal.control_journal_dir()

    if args.standby_of:
        if not journal_dir:
            parser.error("--standby-of requires a journal dir "
                         "(--journal-dir or "
                         "HOROVOD_CONTROL_JOURNAL_DIR)")
        node = StandbyServer(args.standby_of, journal_dir,
                             secret=secret, host=args.host,
                             port=args.port)
        port = node.start()
        role = "standby"
        term = node.server.term
    else:
        node = RendezvousServer(host=args.host, port=args.port,
                                secret=secret, journal_dir=journal_dir)
        port = node.start()
        role = "leader"
        term = node.term

    # Parseable liveness line for launch tooling and the HA e2e.
    print("KV_SERVER LISTENING port=%d role=%s term=%d journal=%s"
          % (port, role, term, journal_dir or "-"), flush=True)

    done = threading.Event()

    def _stop(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    done.wait()
    node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
