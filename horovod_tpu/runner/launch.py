"""``horovodrun``-equivalent launcher.

Reference parity: ``horovod/runner/launch.py`` (+ ``gloo_run.py``): parse
CLI flags into worker env (``HOROVOD_*``), start the rendezvous KV server
on the driver, spawn one worker process per slot (locally, or over ssh
for remote hosts), multiplex their output with rank prefixes, and tear
everything down when the first worker fails.

Usage::

    python -m horovod_tpu.runner -np 4 python train.py
    python -m horovod_tpu.runner -np 8 -H a:4,b:4 python train.py
    python -m horovod_tpu.runner -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./disc.sh python train.py   # elastic
"""

from __future__ import annotations

import argparse
import os
import shlex
import sys
import time
from typing import Dict, List, Optional

from . import safe_shell_exec, util
from .http_server import RendezvousServer


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="horovod_tpu.runner",
        description="Launch a multi-process horovod_tpu job")
    p.add_argument("-np", "--num-proc", type=int, dest="np", default=None,
                   help="total number of worker processes")
    p.add_argument("-H", "--hosts", dest="hosts", default=None,
                   help="host1:slots,host2:slots (default: localhost)")
    p.add_argument("--hostfile", default=None,
                   help="mpirun-style hostfile")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--start-timeout", type=float, default=120.0)
    p.add_argument("--verbose", "-v", action="store_true")
    # Tuning flags -> env (reference: launch.py exports HOROVOD_*).
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--stall-check-time", type=float, default=None)
    p.add_argument("--stall-shutdown-time", type=float, default=None)
    # Multihost SPMD mode: workers join one global JAX runtime; the
    # native core carries only the control plane while payloads run as
    # XLA collectives over ICI/DCN (HOROVOD_CONTROLLER=multihost).
    p.add_argument("--multihost", action="store_true",
                   help="device-payload collectives over the global "
                        "jax.distributed mesh")
    # Elastic flags (reference: elastic launch surface).
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None)
    p.add_argument("--tpu-discovery", action="store_true",
                   help="built-in elastic discovery from the TPU VM "
                        "metadata server (slice membership + "
                        "preemption notices; HVD_TPU_METADATA_URL "
                        "overrides the endpoint)")
    p.add_argument("--tpu-discovery-slots", type=int, default=1,
                   help="worker slots per TPU host (default 1)")
    p.add_argument("--elastic-timeout", type=float, default=600.0)
    p.add_argument("--check-build", action="store_true",
                   help="print the build feature matrix and exit "
                        "(reference: horovodrun --check-build)")
    p.add_argument("--gloo", action="store_true",
                   help="accepted for reference-CLI parity: the TCP "
                        "controller IS the gloo-equivalent plane")
    p.add_argument("--mpi", action="store_true",
                   help="rejected: no MPI backend by design")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="worker command line")
    args = p.parse_args(argv)
    if args.mpi:
        p.error("--mpi is not supported: this framework has no MPI "
                "backend by design (drop the flag; --gloo/default is "
                "the TCP gloo-equivalent plane)")
    if not args.command and not args.check_build:
        p.error("no worker command given")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    return args


def build_common_env(args, base_env: Optional[Dict[str, str]] = None
                     ) -> Dict[str, str]:
    env = dict(base_env if base_env is not None else os.environ)
    def setif(key, value):
        if value is not None:
            env[key] = str(value)
    if args.fusion_threshold_mb is not None:
        env["HOROVOD_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024))
    setif("HOROVOD_CYCLE_TIME", args.cycle_time_ms)
    setif("HOROVOD_CACHE_CAPACITY", args.cache_capacity)
    setif("HOROVOD_TIMELINE", args.timeline_filename)
    if args.timeline_mark_cycles:
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if args.autotune:
        env["HOROVOD_AUTOTUNE"] = "1"
    setif("HOROVOD_AUTOTUNE_LOG", args.autotune_log_file)
    setif("HOROVOD_STALL_CHECK_TIME_SECONDS", args.stall_check_time)
    setif("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", args.stall_shutdown_time)
    # Always pin the controller: a stray HOROVOD_CONTROLLER inherited
    # from the launching shell must not silently detach the workers
    # from the multi-process world.
    env["HOROVOD_CONTROLLER"] = (
        "multihost" if getattr(args, "multihost", False) else "tcp")
    return env


def worker_env(common: Dict[str, str], rank: int, size: int,
               local_rank: int, local_size: int, cross_rank: int,
               cross_size: int, rendezvous_addr: str, secret: str,
               port_base: int) -> Dict[str, str]:
    env = dict(common)
    env.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(cross_rank),
        "HOROVOD_CROSS_SIZE": str(cross_size),
        "HOROVOD_RENDEZVOUS_ADDR": rendezvous_addr,
        "HOROVOD_SECRET_KEY": secret,
        "HOROVOD_PORT_BASE": str(port_base),
        "HOROVOD_CONTROLLER": common.get("HOROVOD_CONTROLLER", "tcp"),
    })
    return env


def _slot_assignments(hosts: List[util.HostInfo], np_: int):
    """(hostname, rank, local_rank, local_size, cross_rank) per slot."""
    out = []
    rank = 0
    for cross_rank, h in enumerate(hosts):
        local_size = min(h.slots, np_ - rank)
        for local_rank in range(local_size):
            out.append((h.hostname, rank, local_rank, local_size,
                        cross_rank))
            rank += 1
            if rank >= np_:
                return out, cross_rank + 1
    if rank < np_:
        raise ValueError(
            "requested -np %d but hosts provide only %d slots"
            % (np_, rank))
    return out, len(hosts)


def _ssh_wrap(host: str, ssh_port: int, env: Dict[str, str],
              command: List[str]) -> List[str]:
    """Build the ssh command carrying HOROVOD_* env to a remote host
    (reference: gloo_run.py get_remote_command)."""
    exports = " ".join("%s=%s" % (k, shlex.quote(v))
                       for k, v in env.items()
                       if k.startswith(("HOROVOD_", "PYTHON", "PATH")))
    remote = "cd %s && env %s %s" % (
        shlex.quote(os.getcwd()), exports,
        " ".join(shlex.quote(c) for c in command))
    return ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(ssh_port),
            host, remote]


def gloo_run(args, hosts: List[util.HostInfo],
             env: Optional[Dict[str, str]] = None) -> int:
    """Spawn the static (non-elastic) world; returns exit code."""
    np_ = args.np or util.total_slots(hosts)
    slots, cross_size = _slot_assignments(hosts, np_)
    secret = util.make_secret()
    server = RendezvousServer(secret=secret)
    port = server.start()
    rendezvous_addr = "127.0.0.1:%d" % port
    port_base = util.find_free_ports(1)[0]
    common = build_common_env(args, env)

    procs: List[safe_shell_exec.ManagedProcess] = []
    try:
        for hostname, rank, local_rank, local_size, cross_rank in slots:
            wenv = worker_env(common, rank, np_, local_rank, local_size,
                              cross_rank, cross_size, rendezvous_addr,
                              secret, port_base)
            is_local = hostname in ("localhost", "127.0.0.1",
                                    util.host_hash())
            cmd = (args.command if is_local
                   else _ssh_wrap(hostname, args.ssh_port, wenv,
                                  args.command))
            prefix = "[%d]<stdout>" % rank
            eprefix = "[%d]<stderr>" % rank
            procs.append(safe_shell_exec.ManagedProcess(
                cmd, wenv,
                stdout_sink=lambda l, p=prefix: sys.stdout.write(p + l),
                stderr_sink=lambda l, p=eprefix: sys.stderr.write(p + l)))
        # Wait; first failure tears down the world (reference behavior).
        deadline = (time.monotonic() + args.start_timeout
                    if args.start_timeout else None)
        rc = 0
        remaining = list(procs)
        while remaining:
            for mp in list(remaining):
                code = mp.poll()
                if code is not None:
                    remaining.remove(mp)
                    if code != 0:
                        rank_i = procs.index(mp)
                        if code < 0:
                            sys.stderr.write(
                                "[launcher] worker rank %d killed by "
                                "signal %d\n" % (rank_i, -code))
                        else:
                            sys.stderr.write(
                                "[launcher] worker rank %d exited with "
                                "code %d\n" % (rank_i, code))
                        rc = code
                        safe_shell_exec.terminate_all(remaining)
                        remaining = []
                        break
            time.sleep(0.05)
        for mp in procs:
            try:
                mp.wait(timeout=5)
            except Exception:
                mp.terminate()
        return rc
    finally:
        safe_shell_exec.terminate_all(procs)
        server.stop()


def check_build(out=None) -> int:
    """Print the build feature matrix (reference ``horovodrun
    --check-build``: frameworks / controllers / tensor operations,
    ``[X]`` present, ``[ ]`` absent-by-design)."""
    out = out if out is not None else sys.stdout
    from .. import __version__

    def probe(mod):
        try:
            __import__(mod)
            return True
        except Exception:  # noqa: BLE001 - any import failure = absent
            return False

    # basics imports jax-free (its jax uses are function-level), so the
    # probe stays the single source of truth with hvd.tcp_built().
    from ..common.basics import tcp_built
    tcp = tcp_built()
    have_jax = probe("jax")

    def row(flag, label):
        return "    [%s] %s" % ("X" if flag else " ", label)

    lines = ["horovod_tpu v%s:" % __version__, ""]
    lines.append("Available Frameworks:")
    lines.append(row(have_jax, "JAX"))
    lines.append(row(probe("tensorflow"), "TensorFlow"))
    lines.append(row(probe("torch"), "PyTorch"))
    lines.append(row(probe("mxnet"), "MXNet"))
    lines.append("")
    lines.append("Available Controllers:")
    lines.append(row(tcp, "TCP (gloo-equivalent negotiation plane)"))
    lines.append(row(have_jax, "SPMD (in-process single controller)"))
    lines.append(row(tcp and have_jax,
                     "Multihost (jax.distributed + TCP)"))
    lines.append(row(False, "MPI"))
    lines.append("")
    lines.append("Available Tensor Operations:")
    lines.append(row(have_jax, "XLA collectives (ICI/DCN)"))
    lines.append(row(tcp, "TCP host collectives"))
    lines.append(row(have_jax, "Pallas TPU kernels"))
    lines.append(row(False, "NCCL"))
    lines.append(row(False, "oneCCL"))
    lines.append(row(False, "DDL"))
    print("\n".join(lines), file=out)
    return 0


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.check_build:
        return check_build()
    if args.hostfile:
        hosts = util.parse_hostfile(args.hostfile)
    elif args.hosts:
        hosts = util.parse_hosts(args.hosts)
    else:
        # no explicit hosts: a batch scheduler allocation (LSF/Slurm)
        # supplies them.  An allocation too small for -np is a hard
        # error (reference launcher behavior): silently oversubscribing
        # the login/batch node would hide the misconfiguration in batch
        # logs.
        hosts = util.scheduler_hosts()
        if hosts and args.np and util.total_slots(hosts) < args.np:
            raise SystemExit(
                "[launcher] scheduler allocation has %d slots < -np %d; "
                "shrink -np or grow the allocation (or pass -H/"
                "--hostfile to override)"
                % (util.total_slots(hosts), args.np))
        hosts = hosts or [util.HostInfo("localhost", args.np or 1)]
    if args.host_discovery_script or getattr(args, "tpu_discovery",
                                             False) \
            or (args.min_np or args.max_np):
        from ..elastic.driver import elastic_run
        return elastic_run(args)
    return gloo_run(args, hosts)


def main():
    sys.exit(run_commandline())
