"""Programmatic launcher: ``horovod_tpu.runner.run(fn, args=...)``.

Reference parity: ``horovod.run(...)`` (``horovod/runner/__init__.py``):
run a python function across np worker processes and collect each rank's
return value.  The function is shipped pickled through an env payload
(top-level functions; same constraint family as the reference without
cloudpickle) and results come back through per-rank files.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
from typing import Any, List, Optional

from . import util
from .launch import parse_args, gloo_run

_STUB = r"""
import os, pickle, sys
from horovod_tpu.runner.util import loads_base64
payload = loads_base64(os.environ["HVD_TPU_RUN_PAYLOAD"])
fn, args, kwargs = payload
result = fn(*args, **kwargs)
out_dir = os.environ["HVD_TPU_RUN_OUT"]
rank = os.environ["HOROVOD_RANK"]
with open(os.path.join(out_dir, "result.%s.pkl" % rank), "wb") as fh:
    pickle.dump(result, fh)
"""


def run(fn, args=(), kwargs=None, np: int = 1,
        hosts: Optional[str] = None, verbose: bool = False,
        extra_cli: Optional[List[str]] = None,
        env: Optional[dict] = None) -> List[Any]:
    """Execute ``fn(*args, **kwargs)`` on np workers; returns the list of
    per-rank results (rank order).  ``env`` overlays extra variables on
    the workers' environment for this run only (the caller's environment
    is untouched)."""
    kwargs = kwargs or {}
    payload = util.dumps_base64((fn, tuple(args), kwargs))
    with tempfile.TemporaryDirectory() as out_dir:
        cli = ["-np", str(np)]
        if hosts:
            cli += ["-H", hosts]
        if verbose:
            cli.append("-v")
        cli += extra_cli or []
        cli += [sys.executable, "-c", _STUB]
        parsed = parse_args(cli)
        worker_env = dict(os.environ)
        worker_env.update(env or {})
        worker_env["HVD_TPU_RUN_PAYLOAD"] = payload
        worker_env["HVD_TPU_RUN_OUT"] = out_dir
        host_list = (util.parse_hosts(hosts) if hosts
                     else [util.HostInfo("localhost", np)])
        rc = gloo_run(parsed, host_list, env=worker_env)
        if rc != 0:
            raise RuntimeError("horovod_tpu.runner.run failed (rc=%d)" % rc)
        import pickle
        results = []
        for rank in range(np):
            with open(os.path.join(out_dir,
                                   "result.%d.pkl" % rank), "rb") as fh:
                results.append(pickle.load(fh))
        return results
