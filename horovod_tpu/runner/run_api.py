"""Programmatic launcher: ``horovod_tpu.runner.run(fn, args=...)``.

Reference parity: ``horovod.run(...)`` (``horovod/runner/__init__.py``):
run a python function across np worker processes and collect each rank's
return value.  The function is shipped pickled through an env payload
(top-level functions; same constraint family as the reference without
cloudpickle) and results come back through per-rank files.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
from typing import Any, List, Optional

from . import util
from .launch import parse_args, gloo_run

_STUB = r"""
import os, pickle, sys
from horovod_tpu.runner.util import loads_base64
payload = loads_base64(os.environ["HVD_TPU_RUN_PAYLOAD"])
fn, args, kwargs = payload
result = fn(*args, **kwargs)
out_dir = os.environ["HVD_TPU_RUN_OUT"]
if "HOROVOD_RANK" not in os.environ:
    # Elastic workers learn their rank from the driver rendezvous,
    # installed into the env by hvd.init(); without it there is no
    # rank to file the result under.
    sys.stderr.write(
        "horovod_tpu.runner.run: elastic runs require fn to call "
        "hvd.init() (rank is assigned at rendezvous)\n")
    sys.exit(3)
rank = os.environ["HOROVOD_RANK"]
size = os.environ["HOROVOD_SIZE"]
with open(os.path.join(out_dir, "result.%s.pkl" % rank), "wb") as fh:
    pickle.dump((int(size), result), fh)
"""


def run(fn, args=(), kwargs=None, np: int = 1,
        hosts: Optional[str] = None, verbose: bool = False,
        min_np: Optional[int] = None, max_np: Optional[int] = None,
        host_discovery_script: Optional[str] = None,
        elastic_timeout: Optional[float] = None,
        use_gloo: Optional[bool] = None, use_mpi: Optional[bool] = None,
        extra_cli: Optional[List[str]] = None,
        env: Optional[dict] = None) -> List[Any]:
    """Execute ``fn(*args, **kwargs)`` on np workers; returns the list of
    per-rank results (rank order).  ``env`` overlays extra variables on
    the workers' environment for this run only (the caller's environment
    is untouched).  Passing ``min_np``/``max_np``/
    ``host_discovery_script`` runs elastically (reference
    ``horovod.run`` elastic parameters): ``fn`` must call
    ``hvd.init()`` (rank assignment happens at the driver rendezvous),
    and results are the final world's per-rank values, whose length may
    differ from ``np``."""
    # Reference signature compatibility: the TCP controller IS the
    # gloo-equivalent plane; MPI is absent by design.
    if use_mpi:
        raise ValueError(
            "use_mpi is not supported: this framework has no MPI "
            "backend by design (the TCP controller is the "
            "gloo-equivalent plane; leave use_gloo/use_mpi unset)")
    del use_gloo  # accepted for signature parity; TCP is the only plane
    kwargs = kwargs or {}
    elastic = bool(min_np or max_np or host_discovery_script)
    payload = util.dumps_base64((fn, tuple(args), kwargs))
    with tempfile.TemporaryDirectory() as out_dir:
        cli = ["-np", str(np)]
        if hosts:
            cli += ["-H", hosts]
        if verbose:
            cli.append("-v")
        if min_np:
            cli += ["--min-np", str(min_np)]
        if max_np:
            cli += ["--max-np", str(max_np)]
        if host_discovery_script:
            cli += ["--host-discovery-script", host_discovery_script]
        if elastic_timeout is not None:
            cli += ["--elastic-timeout", str(elastic_timeout)]
        cli += extra_cli or []
        cli += [sys.executable, "-c", _STUB]
        parsed = parse_args(cli)
        worker_env = dict(os.environ)
        worker_env.update(env or {})
        worker_env["HVD_TPU_RUN_PAYLOAD"] = payload
        worker_env["HVD_TPU_RUN_OUT"] = out_dir
        if elastic:
            from ..elastic.driver import elastic_run
            rc = elastic_run(parsed, base_env=worker_env)
        else:
            host_list = (util.parse_hosts(hosts) if hosts
                         else [util.HostInfo("localhost", np)])
            rc = gloo_run(parsed, host_list, env=worker_env)
        if rc != 0:
            raise RuntimeError("horovod_tpu.runner.run failed (rc=%d)" % rc)
        return _collect_results(out_dir, None if elastic else np)


def _collect_results(out_dir: str, np: Optional[int]) -> List[Any]:
    """Per-rank results.  Static runs know the world size; elastic runs
    take it from the recorded (size, result) tuples — the final epoch's
    workers are exactly the ones that ran to completion, and stale files
    from larger earlier epochs are filtered by the recorded size."""
    import pickle
    found = {}
    for name in os.listdir(out_dir):
        if not (name.startswith("result.") and name.endswith(".pkl")):
            continue
        rank = int(name.split(".")[1])
        with open(os.path.join(out_dir, name), "rb") as fh:
            found[rank] = pickle.load(fh)
    elastic = np is None
    if elastic:
        if 0 not in found:
            raise RuntimeError("elastic run finished without a rank-0 "
                               "result")
        np = found[0][0]  # final world size recorded by rank 0
    results = []
    for rank in range(np):
        if rank not in found or (elastic and found[rank][0] != np):
            # A stale file from a larger earlier epoch records a
            # different size — surface it rather than return old data.
            raise RuntimeError("missing result for rank %d" % rank)
        results.append(found[rank][1])
    return results
