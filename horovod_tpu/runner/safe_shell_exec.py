"""Process spawning with clean teardown and output streaming.

Reference parity: ``horovod/runner/common/util/safe_shell_exec.py`` —
children run in their own process group so the whole tree can be
terminated (SIGTERM, then SIGKILL after a grace period), and their
stdout/stderr are streamed line-by-line through a prefixing callback
(the launcher multiplexes worker output as ``[rank]<line>``).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5.0


def _termination_grace() -> float:
    """SIGTERM→SIGKILL escalation window.  When the launcher exports
    ``HOROVOD_PREEMPT_GRACE_SECS`` (the drain protocol's grace), the
    driver's own terminate honors the same window — a drain-capable
    worker told to stop gets to finish its step, commit, and send its
    drain notice before the SIGKILL lands.  Unset keeps the historical
    5 s (non-elastic children have no drain work to protect)."""
    if os.environ.get("HOROVOD_PREEMPT_GRACE_SECS") is None:
        return GRACEFUL_TERMINATION_TIME_S
    from ..common.envutil import env_float
    return env_float("HOROVOD_PREEMPT_GRACE_SECS",
                     GRACEFUL_TERMINATION_TIME_S, minimum=0.0)


def _stream(pipe, sink: Callable[[str], None]):
    try:
        for line in iter(pipe.readline, b""):
            sink(line.decode(errors="replace"))
    finally:
        pipe.close()


class ManagedProcess:
    def __init__(self, command, env: Optional[Dict[str, str]] = None,
                 stdout_sink: Optional[Callable[[str], None]] = None,
                 stderr_sink: Optional[Callable[[str], None]] = None):
        self.proc = subprocess.Popen(
            command, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, preexec_fn=os.setsid)
        self._threads = [
            threading.Thread(
                target=_stream,
                args=(self.proc.stdout,
                      stdout_sink or (lambda l: sys.stdout.write(l))),
                daemon=True),
            threading.Thread(
                target=_stream,
                args=(self.proc.stderr,
                      stderr_sink or (lambda l: sys.stderr.write(l))),
                daemon=True),
        ]
        for t in self._threads:
            t.start()

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def wait(self, timeout: Optional[float] = None) -> int:
        rc = self.proc.wait(timeout)
        for t in self._threads:
            t.join(timeout=2.0)
        return rc

    def terminate(self, grace: Optional[float] = None):
        """SIGTERM the process group, wait out the grace window (the
        drain protocol's ``HOROVOD_PREEMPT_GRACE_SECS`` when exported,
        else 5 s), then SIGKILL stragglers — never an immediate kill:
        a preemption-aware child uses the window to commit and drain."""
        if self.proc.poll() is not None:
            return
        if grace is None:
            grace = _termination_grace()
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.05)
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        # Confirm the death: a caller that exits right after terminate()
        # (driver teardown) must not orphan a killed-but-not-yet-reaped
        # child on a loaded box — SIGKILL delivery is asynchronous.
        deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.05)


def terminate_all(procs, grace: Optional[float] = None):
    """Terminate many managed processes under ONE shared grace window:
    SIGTERM every process group first, wait out a single deadline,
    then SIGKILL the stragglers.  The serial ``for mp: mp.terminate()``
    shape multiplies the grace by the straggler count — with the drain
    window exported (``HOROVOD_PREEMPT_GRACE_SECS``, default 30 s)
    that turns an 8-worker teardown into minutes.

    Non-ManagedProcess entries (platform proc proxies: Spark agents,
    Ray actors) keep their own ``terminate()`` semantics — their
    teardown is an RPC, not a signal."""
    for mp in procs:
        if not isinstance(mp, ManagedProcess):
            try:
                mp.terminate()
            except Exception:  # noqa: BLE001 — proxy may already be gone
                pass
    procs = [mp for mp in procs
             if isinstance(mp, ManagedProcess) and mp.proc.poll() is None]
    if not procs:
        return
    if grace is None:
        grace = _termination_grace()
    for mp in procs:
        try:
            os.killpg(os.getpgid(mp.proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if all(mp.proc.poll() is not None for mp in procs):
            return
        time.sleep(0.05)
    for mp in procs:
        if mp.proc.poll() is None:
            try:
                os.killpg(os.getpgid(mp.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
    # Confirm the deaths (SIGKILL delivery is asynchronous) so a
    # caller exiting right after cannot orphan unreaped children.
    deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
    while time.monotonic() < deadline:
        if all(mp.proc.poll() is not None for mp in procs):
            return
        time.sleep(0.05)


def execute(command: List[str], env: Optional[Dict[str, str]] = None,
            stdout_sink=None, stderr_sink=None,
            timeout: Optional[float] = None) -> int:
    """Run one command to completion with tree teardown on timeout."""
    mp = ManagedProcess(command, env, stdout_sink, stderr_sink)
    try:
        return mp.wait(timeout)
    except subprocess.TimeoutExpired:
        mp.terminate()
        return -1
