"""Process spawning with clean teardown and output streaming.

Reference parity: ``horovod/runner/common/util/safe_shell_exec.py`` —
children run in their own process group so the whole tree can be
terminated (SIGTERM, then SIGKILL after a grace period), and their
stdout/stderr are streamed line-by-line through a prefixing callback
(the launcher multiplexes worker output as ``[rank]<line>``).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5.0


def _stream(pipe, sink: Callable[[str], None]):
    try:
        for line in iter(pipe.readline, b""):
            sink(line.decode(errors="replace"))
    finally:
        pipe.close()


class ManagedProcess:
    def __init__(self, command, env: Optional[Dict[str, str]] = None,
                 stdout_sink: Optional[Callable[[str], None]] = None,
                 stderr_sink: Optional[Callable[[str], None]] = None):
        self.proc = subprocess.Popen(
            command, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, preexec_fn=os.setsid)
        self._threads = [
            threading.Thread(
                target=_stream,
                args=(self.proc.stdout,
                      stdout_sink or (lambda l: sys.stdout.write(l))),
                daemon=True),
            threading.Thread(
                target=_stream,
                args=(self.proc.stderr,
                      stderr_sink or (lambda l: sys.stderr.write(l))),
                daemon=True),
        ]
        for t in self._threads:
            t.start()

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def wait(self, timeout: Optional[float] = None) -> int:
        rc = self.proc.wait(timeout)
        for t in self._threads:
            t.join(timeout=2.0)
        return rc

    def terminate(self):
        """SIGTERM the process group; SIGKILL stragglers after a grace
        period (reference teardown behavior)."""
        if self.proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.05)
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        # Confirm the death: a caller that exits right after terminate()
        # (driver teardown) must not orphan a killed-but-not-yet-reaped
        # child on a loaded box — SIGKILL delivery is asynchronous.
        deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.05)


def execute(command: List[str], env: Optional[Dict[str, str]] = None,
            stdout_sink=None, stderr_sink=None,
            timeout: Optional[float] = None) -> int:
    """Run one command to completion with tree teardown on timeout."""
    mp = ManagedProcess(command, env, stdout_sink, stderr_sink)
    try:
        return mp.wait(timeout)
    except subprocess.TimeoutExpired:
        mp.terminate()
        return -1
