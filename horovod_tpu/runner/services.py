"""Driver/task services: authenticated control-plane RPC.

Reference parity: ``horovod/runner/common/service/driver_service.py`` +
``task_service.py`` over ``network.py``: small pickled-message TCP
services authenticated with an HMAC of the payload using the launcher's
shared secret.  The driver probes each task service to confirm host
health and discover mutually-routable addresses before spawning the
world; elastic mode reuses the same machinery for worker notification.
"""

from __future__ import annotations

import hashlib
import hmac
import pickle
import socket
import socketserver
import struct
import sys
import threading
from typing import Any, Callable, Dict, Optional, Tuple

_MAC_LEN = 32


def _pack(secret: str, obj: Any) -> bytes:
    payload = pickle.dumps(obj)
    mac = hmac.new(secret.encode(), payload, hashlib.sha256).digest()
    return struct.pack("!I", len(payload) + _MAC_LEN) + mac + payload


def _unpack(secret: str, sock) -> Any:
    hdr = _recv_exact(sock, 4)
    (length,) = struct.unpack("!I", hdr)
    blob = _recv_exact(sock, length)
    mac, payload = blob[:_MAC_LEN], blob[_MAC_LEN:]
    want = hmac.new(secret.encode(), payload, hashlib.sha256).digest()
    if not hmac.compare_digest(mac, want):
        raise PermissionError("bad message authentication code")
    return pickle.loads(payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class _ReusableTCPServer(socketserver.ThreadingTCPServer):
    # A crash-restarted driver must be able to rebind its journaled
    # port while the dead process's sockets linger in TIME_WAIT —
    # without this, crash adoption (elastic/driver.py) could never
    # come back on the address its workers still hold.
    allow_reuse_address = True


class MessageServer:
    """Threaded TCP server dispatching pickled requests to a handler."""

    def __init__(self, handler: Callable[[Any], Any], secret: str,
                 host: str = "0.0.0.0", port: int = 0):
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    req = _unpack(outer.secret, self.request)
                    resp = outer.handler(req)
                    self.request.sendall(_pack(outer.secret, resp))
                except PermissionError:
                    pass  # unauthenticated: drop silently
                except Exception as exc:  # noqa: BLE001
                    try:
                        self.request.sendall(
                            _pack(outer.secret, {"error": str(exc)}))
                    except Exception:
                        pass

        self.handler = handler
        self.secret = secret
        self._server = _ReusableTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class AddressTable:
    """Generation-tracked endpoint table for the notification plane
    (keyed by slot): the fix for stale-entry shadowing after a
    failover.  A worker that reattaches re-registers from a NEW port;
    a live :meth:`register` always wins (it carries a fresh
    generation and evicts any other key still claiming the same
    address), while :meth:`restore` — the crash-adopted driver seeding
    journaled addresses — never overwrites an entry a live
    registration already refreshed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Any, Tuple[Tuple[str, int], int]] = {}
        self._gen = 0

    def register(self, key: Any, addr: Tuple[str, int]):
        """A live registration: newest always wins, and any OTHER key
        still mapped to this exact address is purged (the old owner's
        socket is gone — keeping it would misroute notifications)."""
        with self._lock:
            self._gen += 1
            stale = [k for k, (a, _g) in self._entries.items()
                     if a == addr and k != key]
            for k in stale:
                del self._entries[k]
            self._entries[key] = (addr, self._gen)

    def restore(self, key: Any, addr: Tuple[str, int]):
        """Seed a journaled address at generation 0: useful until the
        worker re-registers, at which point the live entry shadows it
        (never the other way around)."""
        with self._lock:
            self._entries.setdefault(key, (addr, 0))

    def get(self, key: Any) -> Optional[Tuple[str, int]]:
        with self._lock:
            entry = self._entries.get(key)
            return entry[0] if entry else None

    def purge(self, key: Any):
        with self._lock:
            self._entries.pop(key, None)

    def items(self):
        with self._lock:
            return [(k, a) for k, (a, _g) in self._entries.items()]

    def snapshot(self) -> Dict[Any, Tuple[str, int]]:
        with self._lock:
            return {k: a for k, (a, _g) in self._entries.items()}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: Any) -> bool:
        with self._lock:
            return key in self._entries


def send_message(addr: Tuple[str, int], secret: str, obj: Any,
                 timeout: float = 10.0,
                 retries: Optional[int] = 0,
                 deadline: Optional[float] = None) -> Any:
    """One authenticated request/response exchange, routed through the
    runner's shared retry/backoff layer (``http_client.
    request_with_retry``): transient transport failures — refused or
    reset connections, timeouts, a peer that died mid-reply — can be
    retried with exponential backoff + jitter; auth rejections
    (``PermissionError``) are fatal immediately.

    ``retries`` defaults to 0 (single attempt): most callers are
    liveness probes or teardown paths whose OWN failure counters are
    calibrated for one-attempt semantics — a dead peer must read as
    dead at the caller's cadence, not after a hidden in-call retry
    storm.  Callers that want the self-healing behavior opt in with an
    explicit count, or ``retries=None`` for the ``HOROVOD_RPC_*`` env
    defaults."""
    from .http_client import request_with_retry

    def attempt():
        with socket.create_connection(addr, timeout=timeout) as sock:
            sock.sendall(_pack(secret, obj))
            return _unpack(secret, sock)

    what = "message %r to %s:%d" % (
        obj.get("kind") if isinstance(obj, dict) else type(obj).__name__,
        addr[0], addr[1])
    return request_with_retry(attempt, what=what, max_retries=retries,
                              deadline=deadline)


class TaskService:
    """Per-worker-host agent (reference task_service.py): answers pings,
    reports addresses, runs commands for the driver, and (elastic)
    receives host-update notifications."""

    def __init__(self, index: int, secret: str):
        self.index = index
        self._notify_cb: Optional[Callable[[Any], None]] = None
        self._proc = None  # one managed worker process at a time
        self.server = MessageServer(self._handle, secret)

    def _handle(self, req: Any) -> Any:
        kind = req.get("kind")
        if kind == "ping":
            return {"ok": True, "index": self.index,
                    "host": socket.gethostname()}
        if kind == "addresses":
            return {"addresses": self._local_addresses()}
        if kind == "notify":
            if self._notify_cb:
                self._notify_cb(req.get("payload"))
            return {"ok": True}
        if kind == "run":
            # Execute a worker command for the driver (the reference
            # task service's run_command): one at a time, replacing a
            # finished predecessor.  The requested env OVERLAYS this
            # host's own environment (the driver's env does not apply
            # on a foreign executor), and "__PYTHON__" resolves to this
            # host's interpreter.
            import os
            from . import safe_shell_exec
            if self._proc is not None and self._proc.poll() is None:
                return {"error": "a command is already running"}
            env = dict(os.environ)
            env.update(dict(req.get("env") or {}))
            cmd = [sys.executable if c == "__PYTHON__" else c
                   for c in req["cmd"]]
            self._proc = safe_shell_exec.ManagedProcess(
                cmd, env,
                stdout_sink=sys.stdout.write,
                stderr_sink=sys.stderr.write)
            return {"ok": True}
        if kind == "proc_poll":
            # has_proc lets the caller tell "running" (rc=None with a
            # live proc) from "no proc at all" (agent restarted and lost
            # state) — the latter must read as a failed spawn upstream.
            if self._proc is None:
                return {"rc": None, "has_proc": False}
            return {"rc": self._proc.poll(), "has_proc": True}
        if kind == "proc_stop":
            if self._proc is not None and self._proc.poll() is None:
                self._proc.terminate()
            return {"ok": True}
        return {"error": "unknown request %r" % kind}

    @staticmethod
    def _local_addresses():
        """Candidate NIC addresses (reference: driver probes for mutually
        routable interfaces)."""
        addrs = {"127.0.0.1"}
        try:
            addrs.add(socket.gethostbyname(socket.gethostname()))
        except socket.gaierror:
            pass
        return sorted(addrs)

    def on_notify(self, cb: Callable[[Any], None]):
        self._notify_cb = cb

    def start(self) -> int:
        return self.server.start()

    def stop(self):
        self.server.stop()


class DriverService:
    """Launcher-side probe (reference driver_service.py): health-check
    every task service and collect its routable addresses."""

    def __init__(self, secret: str):
        self.secret = secret

    def probe(self, addr: Tuple[str, int], timeout: float = 10.0) -> Dict:
        pong = send_message(addr, self.secret, {"kind": "ping"},
                            timeout=timeout)
        if not pong.get("ok"):
            raise RuntimeError("task service at %s unhealthy: %r"
                               % (addr, pong))
        addresses = send_message(addr, self.secret,
                                 {"kind": "addresses"}, timeout=timeout)
        return {"index": pong["index"], "host": pong["host"],
                "addresses": addresses["addresses"]}

    def notify(self, addr: Tuple[str, int], payload: Any,
               timeout: float = 10.0):
        return send_message(addr, self.secret,
                            {"kind": "notify", "payload": payload},
                            timeout=timeout)
