"""Launcher utilities: host parsing, secrets, codecs, timeouts.

Reference parity: ``horovod/runner/common/util/{hosts.py, secret.py,
codec.py, timeout.py, host_hash.py}``.
"""

from __future__ import annotations

import base64
import dataclasses
import pickle
import secrets as _secrets
import socket
import time
from typing import List


@dataclasses.dataclass(frozen=True)
class HostInfo:
    hostname: str
    slots: int


def parse_hosts(hosts: str) -> List[HostInfo]:
    """Parse "host1:4,host2:2" (slots default 1)."""
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostInfo(name, int(slots)))
        else:
            out.append(HostInfo(part, 1))
    if not out:
        raise ValueError("no hosts parsed from %r" % hosts)
    return out


def parse_hostfile(path: str) -> List[HostInfo]:
    """Hostfile lines: "<host> slots=<n>" (mpirun style) or "host:n"."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.split("#")[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, rest = line.partition(" ")
                slots = int(rest.split("slots=")[1].split()[0])
                out.append(HostInfo(name.strip(), slots))
            else:
                out.extend(parse_hosts(line))
    if not out:
        raise ValueError("no hosts in hostfile %s" % path)
    return out


def total_slots(hosts: List[HostInfo]) -> int:
    return sum(h.slots for h in hosts)


def make_secret() -> str:
    """Shared HMAC secret distributed to workers (reference secret.py)."""
    return _secrets.token_hex(16)


def dumps_base64(obj) -> str:
    """Pickle+base64 codec for env-safe payloads (reference codec.py)."""
    return base64.b64encode(pickle.dumps(obj)).decode()


def loads_base64(s: str):
    return pickle.loads(base64.b64decode(s.encode()))


def host_hash() -> str:
    """Stable identifier for this host, used to group local ranks
    (reference host_hash.py)."""
    return socket.gethostname()


class Timeout:
    """Deadline helper with contextual error messages (reference
    timeout.py)."""

    def __init__(self, seconds: float, message: str = "operation"):
        self._deadline = time.monotonic() + seconds
        self._message = message

    def remaining(self) -> float:
        return max(0.0, self._deadline - time.monotonic())

    def expired(self) -> bool:
        return time.monotonic() >= self._deadline

    def check(self):
        if self.expired():
            raise TimeoutError("%s timed out" % self._message)


def routable_ip() -> str:
    """This host's address as peers would route to it (reference:
    driver-service NIC discovery): the source address of an outbound
    UDP connect, falling back to hostname resolution."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except socket.gaierror:
            return "127.0.0.1"


def find_free_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports
