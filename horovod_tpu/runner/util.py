"""Launcher utilities: host parsing, secrets, codecs, timeouts.

Reference parity: ``horovod/runner/common/util/{hosts.py, secret.py,
codec.py, timeout.py, host_hash.py}``.
"""

from __future__ import annotations

import base64
import dataclasses
import os
import pickle
import secrets as _secrets
import socket
import time
from typing import List


@dataclasses.dataclass(frozen=True)
class HostInfo:
    hostname: str
    slots: int


def parse_hosts(hosts: str) -> List[HostInfo]:
    """Parse "host1:4,host2:2" (slots default 1)."""
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostInfo(name, int(slots)))
        else:
            out.append(HostInfo(part, 1))
    if not out:
        raise ValueError("no hosts parsed from %r" % hosts)
    return out


def parse_hostfile(path: str) -> List[HostInfo]:
    """Hostfile lines: "<host> slots=<n>" (mpirun style) or "host:n"."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.split("#")[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, rest = line.partition(" ")
                slots = int(rest.split("slots=")[1].split()[0])
                out.append(HostInfo(name.strip(), slots))
            else:
                out.extend(parse_hosts(line))
    if not out:
        raise ValueError("no hosts in hostfile %s" % path)
    return out


def total_slots(hosts: List[HostInfo]) -> int:
    return sum(h.slots for h in hosts)


def make_secret() -> str:
    """Shared HMAC secret distributed to workers (reference secret.py)."""
    return _secrets.token_hex(16)


def dumps_base64(obj) -> str:
    """Pickle+base64 codec for env-safe payloads (reference codec.py)."""
    return base64.b64encode(pickle.dumps(obj)).decode()


def loads_base64(s: str):
    return pickle.loads(base64.b64decode(s.encode()))


def host_hash() -> str:
    """Stable identifier for this host, used to group local ranks
    (reference host_hash.py)."""
    return socket.gethostname()


class Timeout:
    """Deadline helper with contextual error messages (reference
    timeout.py)."""

    def __init__(self, seconds: float, message: str = "operation"):
        self._deadline = time.monotonic() + seconds
        self._message = message

    def remaining(self) -> float:
        return max(0.0, self._deadline - time.monotonic())

    def expired(self) -> bool:
        return time.monotonic() >= self._deadline

    def check(self):
        if self.expired():
            raise TimeoutError("%s timed out" % self._message)


def lsf_available() -> bool:
    """True under an LSF allocation (reference ``util/lsf.py``)."""
    return "LSB_MCPU_HOSTS" in os.environ or "LSB_HOSTS" in os.environ


def parse_lsf_hosts() -> List[HostInfo]:
    """Hosts/slots from the LSF environment (reference ``lsf.py``):
    ``LSB_MCPU_HOSTS`` = "host1 4 host2 4"; ``LSB_HOSTS`` = one token
    per slot."""
    mcpu = os.environ.get("LSB_MCPU_HOSTS")
    if mcpu:
        toks = mcpu.split()
        if len(toks) % 2:
            raise ValueError("malformed LSB_MCPU_HOSTS: %r" % mcpu)
        return [HostInfo(toks[i], int(toks[i + 1]))
                for i in range(0, len(toks), 2)]
    hosts = os.environ.get("LSB_HOSTS", "").split()
    if not hosts:
        raise ValueError("no LSF host environment found")
    # one token per slot, possibly interleaved: count ALL occurrences
    # per host, first-seen order (adjacent-only runs would split a
    # host into duplicate entries and collide local_ranks)
    counts: dict = {}
    for h in hosts:
        counts[h] = counts.get(h, 0) + 1
    return [HostInfo(h, c) for h, c in counts.items()]


def slurm_available() -> bool:
    """True under a Slurm allocation."""
    return "SLURM_JOB_NODELIST" in os.environ or \
        "SLURM_NODELIST" in os.environ


def _expand_slurm_nodelist(nodelist: str) -> List[str]:
    """Expand "node[1-3,7],gpu01" into explicit hostnames (the subset
    of Slurm's syntax schedulers actually emit: comma lists and one
    [a-b,c] range block per name, zero-padded)."""
    hosts: List[str] = []
    i, n = 0, len(nodelist)
    while i < n:
        j = i
        while j < n and nodelist[j] not in ",[":
            j += 1
        prefix = nodelist[i:j]
        if j < n and nodelist[j] == "[":
            k = nodelist.index("]", j)
            for part in nodelist[j + 1:k].split(","):
                if "-" in part:
                    lo, hi = part.split("-")
                    width = len(lo)
                    for v in range(int(lo), int(hi) + 1):
                        hosts.append(prefix + str(v).zfill(width))
                else:
                    hosts.append(prefix + part)
            i = k + 2  # skip "]," if present
        else:
            if prefix:
                hosts.append(prefix)
            i = j + 1
    return hosts


def _expand_slurm_tasks(spec: str, num_hosts: int) -> List[int]:
    """Expand SLURM_TASKS_PER_NODE "4(x2),2" into per-host counts."""
    counts: List[int] = []
    for part in spec.split(","):
        if "(x" in part:
            base, times = part.split("(x")
            counts.extend([int(base)] * int(times.rstrip(")")))
        else:
            counts.append(int(part))
    if len(counts) < num_hosts:  # pad with last
        counts.extend([counts[-1]] * (num_hosts - len(counts)))
    return counts[:num_hosts]


def parse_slurm_hosts() -> List[HostInfo]:
    """Hosts/slots from the Slurm environment."""
    nodelist = os.environ.get("SLURM_JOB_NODELIST") or \
        os.environ.get("SLURM_NODELIST")
    if not nodelist:
        raise ValueError("no Slurm host environment found")
    hosts = _expand_slurm_nodelist(nodelist)
    tasks = os.environ.get("SLURM_TASKS_PER_NODE") or \
        os.environ.get("SLURM_NTASKS_PER_NODE") or "1"
    counts = _expand_slurm_tasks(tasks, len(hosts))
    return [HostInfo(h, c) for h, c in zip(hosts, counts)]


def scheduler_hosts() -> List[HostInfo]:
    """Hosts from a detected batch scheduler (LSF, then Slurm), or an
    empty list when not running under one — the launcher's fallback
    when no -H/--hostfile is given (reference: lsf/slurm detection in
    ``horovod/runner/launch.py``).  A malformed scheduler environment
    is reported loudly (then the next source is tried) rather than
    silently degrading to single-host."""
    import sys
    if lsf_available():
        try:
            return parse_lsf_hosts()
        except ValueError as exc:
            print("[launcher] WARNING: LSF detected but unusable: %s"
                  % exc, file=sys.stderr)
    if slurm_available():
        try:
            return parse_slurm_hosts()
        except ValueError as exc:
            print("[launcher] WARNING: Slurm detected but unusable: %s"
                  % exc, file=sys.stderr)
    return []


def routable_ip() -> str:
    """This host's address as peers would route to it (reference:
    driver-service NIC discovery): the source address of an outbound
    UDP connect, falling back to hostname resolution."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except socket.gaierror:
            return "127.0.0.1"


def find_free_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports
