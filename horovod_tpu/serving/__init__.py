"""Continuous-batching serving plane (ISSUE 11).

The training stack's coordination core re-aimed at inference traffic:
requests coalesce into batches the way tensors fuse into buckets
(``router``), replica groups are process sets under the pod scheduler
with traffic-driven autoscaling (``replica``), published weights roll
across replicas with zero dropped requests via the survivor election
generalized to newest-version-wins, and process-mode replicas pull
from a durable claim-based work queue (``workqueue``).  docs/serving.md
has the lifecycle; ``benchmarks/serving_bw.py`` is the headline
harness.
"""

from .router import (InferenceRequest, Router, install_http_frontend,
                     serve_http)
from .replica import (Autoscaler, DeploymentSpec, ReplicaSet,
                      VersionStore, admit_deployment, autoscale_decision,
                      serve_from_queue, swap_to, tenant_autoscaler)
from .workqueue import Claim, FileWorkQueue

__all__ = [
    "InferenceRequest", "Router", "install_http_frontend", "serve_http",
    "Autoscaler", "DeploymentSpec", "ReplicaSet", "VersionStore",
    "admit_deployment", "autoscale_decision", "serve_from_queue",
    "swap_to", "tenant_autoscaler",
    "Claim", "FileWorkQueue",
]
