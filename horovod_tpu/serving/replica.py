"""Replica groups, weight hot-swap and traffic-driven autoscaling.

Three pieces, each a deliberate reuse of an existing plane:

* **Model version store** (:class:`VersionStore`) — published weights
  ride the r10 durable-spill format (MAGIC + version-as-commit-id +
  CRC32, atomic rename, keep-last-K; elastic/spill.py) in their own
  directory, so a replica "loads a model" through the exact
  crash-hardened restore path training states use, and a torn publish
  is skipped loudly instead of half-loading weights.

* **Hot swap** — a new version rolls across replicas with zero
  dropped requests: each replica swaps BETWEEN batches (queued
  requests keep queueing; the other replicas keep serving), and
  the version to converge on is ELECTED, not assumed —
  ``jax.functions.elect_newest(records, keys=("version",))``:
  newest model version wins, the r10 survivor election generalized.
  In a multi-process replica group the same rule rides the elastic
  sync itself (each swap commits, so the max-commit survivor carries
  the newest version through ``elect_state_root`` after a death).

* **Autoscaler** — the r13 ``PodScheduler`` becomes the traffic-driven
  autoscaler: deployments are tenants (SLO class = priority), and a
  queue-depth series from the r11 metrics plane drives
  :func:`autoscale_decision` (grow when the per-replica backlog
  crosses ``HOROVOD_SERVING_AUTOSCALE_UP_QDEPTH``, shrink below
  ``..._DOWN_QDEPTH`` after a cooldown).  Scale orders land through
  ``scheduler.resize`` + ``poke`` — applied on the NEXT tick, not a
  full cadence later — and the order→converged gap is the cold-start
  window the serving SLO measures (a fresh replica adopts the fleet's
  r14 tuned plan at init, before taking traffic).

Process-mode replicas (deployment-as-tenant) pull from the durable
:class:`~.workqueue.FileWorkQueue` via :func:`serve_from_queue`; the
in-process :class:`ReplicaSet` (threads) is the latency path
``benchmarks/serving_bw.py`` measures.
"""

from __future__ import annotations

import logging
import math
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..common import faultline, metrics
from ..common.envutil import env_float
from ..elastic import spill
from .router import Router, max_batch

LOG = logging.getLogger("horovod_tpu.serving.replica")


# -- autoscale knobs (one read point each; graftlint env-drift covers
#    this module via bootstrap_env_files) -----------------------------------

def autoscale_up_qdepth() -> float:
    """Per-replica queue depth that triggers a scale-UP
    (``HOROVOD_SERVING_AUTOSCALE_UP_QDEPTH``, default 4.0, floor
    0.1): backlog above this means the current replicas are not
    keeping up."""
    return env_float("HOROVOD_SERVING_AUTOSCALE_UP_QDEPTH", 4.0,
                     minimum=0.1)


def autoscale_down_qdepth() -> float:
    """Per-replica queue depth below which one replica is released
    (``HOROVOD_SERVING_AUTOSCALE_DOWN_QDEPTH``, default 0.5, floor
    0.0), one step per cooldown window."""
    return env_float("HOROVOD_SERVING_AUTOSCALE_DOWN_QDEPTH", 0.5,
                     minimum=0.0)


def autoscale_interval_secs() -> float:
    """Autoscaler evaluation cadence
    (``HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECS``, default 1.0, floor
    0.05)."""
    return env_float("HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECS", 1.0,
                     minimum=0.05)


def autoscale_cooldown_secs() -> float:
    """Minimum quiet time after any scale change before a SHRINK is
    allowed (``HOROVOD_SERVING_AUTOSCALE_COOLDOWN_SECS``, default 5.0,
    floor 0.0).  Scale-UPs are never cooldown-gated: under-provisioning
    burns the latency SLO immediately, over-provisioning only burns
    slots."""
    return env_float("HOROVOD_SERVING_AUTOSCALE_COOLDOWN_SECS", 5.0,
                     minimum=0.0)


# -- fault seams ------------------------------------------------------------
#
# Each site has exactly ONE plant (the graftlint fault-site rule);
# these helpers are that plant, shared by the two execution modes.


def _replica_die_seam():
    """The batch-execution seam: a claimed batch, not yet served —
    ``die``/``wedge`` here takes a replica down mid-service (the
    hot-swap e2e's no-request-lost certification).  Fired by the
    in-process replica loop AND the process-mode ``serve_from_queue``
    loop."""
    faultline.site("serving.replica.die")


def _swap_stall_seam():
    """The weight hot-swap seam: inside the swap window, before the
    new version loads — ``delay``/``wedge`` stalls one replica's load
    while the others must keep serving.  Fired by :func:`swap_to`
    (process mode) and the in-process replica's between-batch swap
    check."""
    faultline.site("serving.swap.stall")


# -- model version store ----------------------------------------------------


class VersionStore:
    """Published model versions as durable spill blobs in ``d``
    (version = the blob's commit id; monotonically increasing by
    convention).  ``publish`` is what a deployment pipeline calls;
    replicas poll :meth:`version` cheaply (filename scan) and
    :meth:`newest` re-validates CRC at load time."""

    def __init__(self, d: str):
        self.dir = d
        # (head_version, min_version) for which load found NO valid
        # newer blob: a persistently corrupt head would otherwise be
        # fully re-read + CRC-failed + WARNING-logged on EVERY swap
        # check (each batch and each ~50 ms idle beat) until a good
        # version lands.  Reset the moment the head moves.
        self._exhausted = None

    def publish(self, version: int, weights: Any) -> Optional[str]:
        if version <= 0:
            raise ValueError("model versions start at 1 (got %d)"
                             % version)
        return spill.write(version, pickle.dumps(weights), tag="model",
                           d=self.dir)

    def version(self) -> int:
        """Newest published version by filename (0 = none yet); the
        load path re-validates the header before trusting it."""
        scanned = spill.scan(self.dir)
        return scanned[0][0] if scanned else 0

    def newest(self, min_version: int = 0):
        """(version, weights) strictly newer than ``min_version``, or
        None; corrupt blobs are skipped loudly with CRC-failure
        metrics (the spill restore path) — once, per head version:
        an exhausted (head, floor) is remembered so a corrupt head is
        not re-read on every poll."""
        head = self.version()
        if self._exhausted is not None:
            ex_head, ex_min = self._exhausted
            if head == ex_head and min_version >= ex_min:
                return None
        loaded = spill.load_newest(min_commit_id=min_version, d=self.dir)
        if loaded is None:
            if head > min_version:
                self._exhausted = (head, min_version)
            return None
        self._exhausted = None
        return loaded[0], pickle.loads(loaded[1])


def swap_to(store: VersionStore, state,
            version_attr: str = "version") -> bool:
    """Process-mode hot swap: when the store holds a version newer
    than ``state.<version_attr>``, load it through the spill restore
    path into ``state.weights`` (+ bump the version attr) and COMMIT —
    the commit is what carries the new version into the elastic
    election evidence, so after a replica death the survivors'
    max-commit root IS the newest-version root.  Returns True when a
    swap happened.  The ``serving.swap.stall`` site fires inside the
    swap window (a stalled replica must not stall the deployment)."""
    current = int(getattr(state, version_attr, 0) or 0)
    if store.version() <= current:
        return False
    _swap_stall_seam()
    loaded = store.newest(min_version=current)
    if loaded is None:
        return False  # newest blob was corrupt; keep serving current
    version, weights = loaded
    setattr(state, version_attr, version)
    state.weights = weights
    metrics.event("serving_swap", version=version)
    LOG.warning("hot-swapped to model version %d", version)
    state.commit()
    return True


# -- in-process replica set -------------------------------------------------


class ReplicaKilled(RuntimeError):
    """Test-injected abrupt replica death (``ReplicaSet.kill``)."""


class _Replica:
    """One in-process replica: a thread pulling batches from the
    router, swapping weights between batches."""

    def __init__(self, rset: "ReplicaSet", index: int):
        self.rset = rset
        self.index = index
        self.version = 0
        self.weights: Any = None
        self.alive = True
        self.ready = False
        self.started_at = time.monotonic()
        self.first_batch_s: Optional[float] = None
        self._killed = False
        self._stop = threading.Event()
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name="replica-%s-%d" % (rset.deployment, index))

    def _run(self):
        try:
            # Take the fleet's tuned plan (adopted process-wide at
            # hvd.init via the r14 plan cache) BEFORE taking traffic;
            # what was adopted is recorded for the bench's levers.
            self.rset._note_plan()
            self._load_initial()
            self.ready = True
            while not self._stop.is_set():
                if self._killed:
                    raise ReplicaKilled("replica %d killed" % self.index)
                self._maybe_swap()
                batch = self.rset.router.next_batch(
                    self.rset.deployment, timeout=0.02)
                if not batch:
                    continue
                _replica_die_seam()
                if self._killed:
                    # Abrupt death with a claimed batch: hand it back
                    # (the no-request-lost seam the units certify).
                    self.rset.router.requeue(batch)
                    raise ReplicaKilled("replica %d killed" % self.index)
                try:
                    results = self.rset.model_fn(
                        self.weights, [r.payload for r in batch])
                except BaseException:
                    self.rset.router.requeue(batch)
                    raise
                self.rset.router.complete(batch, results)
                if self.first_batch_s is None:
                    self.first_batch_s = (time.monotonic()
                                          - self.started_at)
                self.rset._note_first_token()
        except ReplicaKilled:
            LOG.warning("replica %s/%d died", self.rset.deployment,
                        self.index)
        except Exception:  # noqa: BLE001 — a replica must die contained
            LOG.exception("replica %s/%d crashed", self.rset.deployment,
                          self.index)
        finally:
            self.alive = False
            self.rset._on_death(self)

    def _load_initial(self):
        store = self.rset.store
        if store is not None:
            loaded = store.newest()
            if loaded is not None:
                self.version, self.weights = loaded
                return
        self.weights = self.rset.initial_weights
        self.version = self.rset.initial_version

    def _maybe_swap(self):
        target = self.rset.target_version()
        if target <= self.version:
            return
        _swap_stall_seam()
        loaded = (self.rset.store.newest(min_version=self.version)
                  if self.rset.store is not None else None)
        if loaded is None:
            return
        self.version, self.weights = loaded
        metrics.event("serving_swap", deployment=self.rset.deployment,
                      replica=self.index, version=self.version)
        LOG.info("replica %s/%d hot-swapped to version %d",
                 self.rset.deployment, self.index, self.version)

    def stop(self):
        self._stop.set()

    def kill(self):
        self._killed = True


class ReplicaSet:
    """In-process replica group for one deployment: N replica threads
    pulling coalesced batches from ``router``.  ``model_fn(weights,
    payloads) -> results`` is the whole model contract.  Grow/shrink
    via :meth:`scale` (shrinking replicas finish their in-flight batch
    first — zero-downtime by construction)."""

    def __init__(self, deployment: str,
                 model_fn: Callable[[Any, List[Any]], List[Any]],
                 router: Router,
                 store: Optional[VersionStore] = None,
                 initial_weights: Any = None,
                 initial_version: int = 0,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None):
        self.deployment = deployment
        self.model_fn = model_fn
        self.router = router
        self.store = store
        self.initial_weights = initial_weights
        self.initial_version = initial_version
        self.min_replicas = max(1, min_replicas)
        self.max_replicas = max_replicas
        self._lock = threading.Lock()
        self._replicas: List[_Replica] = []
        self._next_index = 0
        self._stopping = False
        self._started_at: Optional[float] = None
        self._first_token_s: Optional[float] = None
        self.plan: Dict[str, Any] = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self, replicas: Optional[int] = None):
        with self._lock:
            if self._started_at is None:
                self._started_at = time.monotonic()
        self.scale(replicas if replicas is not None
                   else self.min_replicas)
        return self

    def scale(self, n: int):
        """Converge on ``n`` live replicas (clamped to
        [min_replicas, max_replicas])."""
        n = max(self.min_replicas, n)
        if self.max_replicas is not None:
            n = min(self.max_replicas, n)
        to_start: List[_Replica] = []
        with self._lock:
            live = [r for r in self._replicas if r.alive]
            for r in live[n:]:
                r.stop()  # finishes its in-flight batch, then exits
            while len(live) + len(to_start) < n:
                rep = _Replica(self, self._next_index)
                self._next_index += 1
                self._replicas.append(rep)
                to_start.append(rep)
        for rep in to_start:
            rep.thread.start()
        if to_start:
            metrics.event("serving_scale", deployment=self.deployment,
                          replicas=n)

    def stop(self, timeout: float = 10.0):
        # NOT router.close(): the router is shared across deployments
        # (one HTTP front door mounts one router), so decommissioning
        # THIS deployment must not wedge the others' next_batch
        # waiters.  Replicas poll with a short timeout and exit on
        # their own stop flag.
        with self._lock:
            self._stopping = True
            replicas = list(self._replicas)
        for r in replicas:
            r.stop()
        deadline = time.monotonic() + timeout
        for r in replicas:
            r.thread.join(max(0.1, deadline - time.monotonic()))

    def kill(self, index: int):
        """Abruptly kill one replica (tests/chaos): its claimed batch
        is requeued and served by survivors."""
        with self._lock:
            for r in self._replicas:
                if r.index == index and r.alive:
                    r.kill()
                    return
        raise KeyError("no live replica %d" % index)

    # -- introspection -----------------------------------------------------

    def live_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if r.alive)

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas
                       if r.alive and r.ready)

    def versions(self) -> List[int]:
        with self._lock:
            return [r.version for r in self._replicas if r.alive]

    def target_version(self) -> int:
        """The version this set converges on: ELECTED over every live
        replica's evidence plus the store's newest — newest version
        wins (``elect_newest`` with version evidence), so a replica
        that already swapped pulls the others forward even if the
        store momentarily vanishes."""
        from ..jax.functions import elect_newest
        with self._lock:
            records = [{"rank": r.index, "version": r.version}
                       for r in self._replicas if r.alive]
        if self.store is not None:
            # The store is the lowest-authority tiebreak: any live
            # replica already AT a version outranks it on ties.
            records.append({"rank": 1 << 20,
                            "version": self.store.version()})
        if not records:
            return 0
        return int(elect_newest(records, keys=("version",))["version"])

    def cold_start_seconds(self) -> Optional[float]:
        """start() → first completed request, the cold-start-to-first-
        token SLO ``serving_bw.py`` reports."""
        return self._first_token_s

    # -- internal ----------------------------------------------------------

    def _note_first_token(self):
        if self._first_token_s is None and self._started_at is not None:
            self._first_token_s = time.monotonic() - self._started_at

    def _note_plan(self):
        if self.plan:
            return
        try:
            from ..utils import plancache
            d = plancache.describe()
            self.plan = {"enabled": d.get("enabled"),
                         "source": d.get("source"),
                         "hits": d.get("hits")}
        except Exception:  # noqa: BLE001 — attribution only
            self.plan = {}

    def _on_death(self, replica: _Replica):
        live = self.live_count()
        metrics.event("serving_replica_death",
                      deployment=self.deployment, replica=replica.index,
                      live=live)
        with self._lock:
            stopping = self._stopping
        if not stopping and live < self.min_replicas:
            # Hold the floor: a deployment must never silently drop
            # below min_replicas — the sole replica crashing on a bad
            # batch would otherwise strand the queue forever (the
            # autoscaler only converges worlds that still serve).
            # Runs on the dying replica's thread; scale() itself only
            # spawns, so no recursion.
            LOG.warning("replica %s/%d died below the floor; "
                        "respawning to min_replicas=%d",
                        self.deployment, replica.index,
                        self.min_replicas)
            self.scale(self.min_replicas)


# -- autoscaler -------------------------------------------------------------


def autoscale_decision(queue_depth: float, replicas: int,
                       min_replicas: int,
                       max_replicas: Optional[int],
                       up_qdepth: Optional[float] = None,
                       down_qdepth: Optional[float] = None) -> int:
    """Pure scale policy (the unit-tested decision table): returns the
    DESIRED replica count.  Backlog per replica >= up_qdepth → grow to
    ceil(depth / up_qdepth) (enough replicas that the backlog would sit
    at the threshold), bounded by max_replicas; backlog per replica <=
    down_qdepth → release exactly one replica (shrink is deliberately
    one-step — a drained queue says little about the NEXT second's
    traffic); otherwise hold."""
    up = up_qdepth if up_qdepth is not None else autoscale_up_qdepth()
    down = (down_qdepth if down_qdepth is not None
            else autoscale_down_qdepth())
    replicas = max(1, int(replicas))
    want = replicas
    per_replica = queue_depth / replicas
    if per_replica >= up:
        want = max(replicas, int(math.ceil(queue_depth / up)))
    elif per_replica <= down:
        want = replicas - 1
    want = max(min_replicas, want)
    if max_replicas is not None:
        want = min(max_replicas, want)
    return want


class Autoscaler:
    """Traffic-driven replica autoscaling over the r11 metrics plane:
    every interval, read the deployment's queue depth (``depth_fn``),
    run :func:`autoscale_decision` against the live replica count
    (``current_fn``), and apply changes (``apply_fn(desired)``) —
    scale-ups immediately, scale-downs only after
    ``HOROVOD_SERVING_AUTOSCALE_COOLDOWN_SECS`` of quiet.

    ``deployment`` republishes the observed depth into the
    ``serving_queue_depth`` gauge so process-mode deployments (whose
    depth lives in the work queue, not this process's registry) still
    feed the fleet /metrics scrape.  Cold-start accounting: the gap
    between a scale-up order and ``current_fn`` reaching it is
    recorded as a ``serving_scale_converged`` event and
    :attr:`last_scale_up_secs`."""

    def __init__(self, depth_fn: Callable[[], float],
                 current_fn: Callable[[], int],
                 apply_fn: Callable[[int], None],
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 deployment: Optional[str] = None,
                 interval: Optional[float] = None,
                 cooldown: Optional[float] = None,
                 up_qdepth: Optional[float] = None,
                 down_qdepth: Optional[float] = None):
        self.depth_fn = depth_fn
        self.current_fn = current_fn
        self.apply_fn = apply_fn
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.deployment = deployment
        self.interval = (interval if interval is not None
                         else autoscale_interval_secs())
        self.cooldown = (cooldown if cooldown is not None
                         else autoscale_cooldown_secs())
        self.up_qdepth = up_qdepth
        self.down_qdepth = down_qdepth
        self.decisions: List[Dict[str, Any]] = []
        self.last_scale_up_secs: Optional[float] = None
        self._pending_up: Optional[Dict[str, Any]] = None
        self._last_change = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self):
        depth = float(self.depth_fn())
        current = int(self.current_fn())
        if self.deployment is not None:
            metrics.gauge("serving_queue_depth",
                          deployment=self.deployment).set(depth)
        now = time.monotonic()
        if self._pending_up is not None \
                and current >= self._pending_up["to"]:
            secs = now - self._pending_up["at"]
            self.last_scale_up_secs = secs
            metrics.event("serving_scale_converged",
                          deployment=self.deployment,
                          replicas=current, secs=round(secs, 3))
            self._pending_up = None
        desired = autoscale_decision(
            depth, current, self.min_replicas, self.max_replicas,
            self.up_qdepth, self.down_qdepth)
        if desired == current:
            return
        if desired < current and now - self._last_change < self.cooldown:
            return  # shrink waits out the cooldown; growth never does
        self.decisions.append({"from": current, "to": desired,
                               "depth": depth})
        metrics.event("serving_scale_decision",
                      deployment=self.deployment, depth=depth,
                      replicas=current, desired=desired)
        LOG.info("autoscale %s: %d -> %d replicas (queue depth %.0f)",
                 self.deployment or "?", current, desired, depth)
        if desired > current:
            self._pending_up = {"to": desired, "at": now}
        self._last_change = now
        self.apply_fn(desired)

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-autoscaler")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                LOG.exception("autoscale tick failed; retrying")
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


# -- deployment-as-tenant (process-mode replicas) ---------------------------


class DeploymentSpec:
    """One model deployment for the pod scheduler: ``command`` runs a
    replica process (typically an elastic worker calling
    :func:`serve_from_queue`), ``slo_class`` maps to scheduler
    priority (higher = preempts lower SLO classes under contention),
    replicas scale within [min_replicas, max_replicas]."""

    def __init__(self, name: str, command: List[str],
                 slo_class: int = 0, min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None):
        if not name:
            raise ValueError("deployment name must be non-empty")
        self.name = name
        self.command = list(command)
        self.slo_class = int(slo_class)
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = (None if max_replicas is None
                             else int(max_replicas))
        self.env = dict(env or {})


def admit_deployment(scheduler, spec: DeploymentSpec) -> str:
    """Admit ``spec`` as a tenant (replica group = process set under
    its own elastic driver): tenant id ``serve-<name>``, priority =
    SLO class.  Starts at ``min_replicas`` (``max_np`` pinned there
    too — growth is the AUTOSCALER's call via ``scheduler.resize``,
    not free slack absorption).  Returns the tenant id."""
    from ..elastic.scheduler import TenantSpec
    tenant_id = "serve-%s" % spec.name
    env = dict(spec.env)
    env.setdefault("HOROVOD_SERVING_DEPLOYMENT", spec.name)
    scheduler.admit(TenantSpec(
        tenant_id, spec.command, priority=spec.slo_class,
        min_np=spec.min_replicas, max_np=spec.min_replicas, env=env))
    return tenant_id


def tenant_autoscaler(scheduler, tenant_id: str, spec: DeploymentSpec,
                      depth_fn: Callable[[], float],
                      **kwargs) -> Autoscaler:
    """Wire an :class:`Autoscaler` to a deployment tenant: desired
    replica counts land as ``scheduler.resize(max_np=desired)`` +
    ``poke()`` (applied on the next tick — the satellite fix), and the
    live count comes from the tenant driver's worker census."""

    def current() -> int:
        driver = scheduler.tenant_driver(tenant_id)
        return driver.live_worker_count() if driver is not None else 0

    def apply(desired: int):
        try:
            scheduler.resize(tenant_id, max_np=desired)
        except KeyError:
            # The deployment finished (or was evicted) under us: a
            # scale order for a gone tenant is a no-op, not an error —
            # the operator stops the autoscaler, not the other way
            # around.
            LOG.info("autoscale order for finished tenant %s skipped",
                     tenant_id)
            return
        scheduler.poke()

    return Autoscaler(depth_fn, current, apply,
                      min_replicas=spec.min_replicas,
                      max_replicas=spec.max_replicas,
                      deployment=spec.name, **kwargs)


# -- process-mode replica serve loop ---------------------------------------


def serve_from_queue(queue, handler: Callable[[str, Dict], Dict],
                     state=None, store: Optional[VersionStore] = None,
                     deployment: str = "default",
                     total: Optional[int] = None,
                     batch_n: Optional[int] = None,
                     idle_sleep: float = 0.05):
    """One process-mode replica's serve loop over a durable
    :class:`~.workqueue.FileWorkQueue`: sweep dead claimants' work
    back to pending, claim up to a batch, serve each request through
    ``handler(req_id, payload) -> result``, commit.  With ``state`` +
    ``store`` the loop hot-swaps between batches (:func:`swap_to`:
    version bump + commit — the election evidence).  Runs until the
    deployment's done-count reaches ``total`` (None = until the
    elastic plane stops the worker).  The ``serving.replica.die`` site
    fires per claimed batch — the e2e kills one replica mid-service
    and asserts no request is lost."""
    n = batch_n if batch_n is not None else max_batch()
    while True:
        if total is not None and queue.done_count() >= total:
            return
        if state is not None and store is not None:
            swap_to(store, state)
        queue.sweep_dead_claimants()
        metrics.gauge("serving_queue_depth",
                      deployment=deployment).set(queue.depth())
        batch = queue.claim(n)
        if not batch:
            time.sleep(idle_sleep)
            if state is not None:
                # Idle beats still commit: host updates and drain
                # notices are consumed at the commit seam.
                state.commit()
            continue
        _replica_die_seam()
        metrics.histogram("serving_batch_size").observe(len(batch))
        for claim in batch:
            result = handler(claim.req_id, claim.payload)
            queue.complete(claim, result)
            metrics.counter("serving_requests_total",
                            deployment=deployment, outcome="ok").inc()
        if state is not None:
            state.commit()
