"""Continuous-batching request router: the serving front door.

The serving analog of ``ops/engine.py``'s tensor fusion: individual
inference requests are worth little alone (a one-request forward pass
wastes the accelerator exactly the way a lone small allreduce wastes
the wire), so the router coalesces them into batches under a
two-knob admission policy — close a batch when it reaches
``HOROVOD_SERVING_MAX_BATCH`` requests OR when the OLDEST queued
request has waited ``HOROVOD_SERVING_MAX_WAIT_MICROS`` (the fusion
buffer-size / cycle-time pair, renamed for the request plane; Orca,
OSDI '22 calls the same lever iteration-level batching).

Data flow is **pull-based**: replicas (serving/replica.py) call
:meth:`Router.next_batch` when they free up, so a slow replica never
backs up the queue for the others, and a dying replica's in-flight
batch is handed back via :meth:`Router.requeue` — requests are only
ever terminal as ``ok``, ``deadline`` (expired waiting) or ``dropped``
(admission refused / injected ``serving.request.drop``).  A requeued
batch re-enters AT THE FRONT, preserving arrival order, so "no request
lost" across a replica death is a router invariant, not a client
retry.

Exposure: :func:`install_http_frontend` mounts the router at
``POST /serve/<deployment>`` on the rendezvous KV server
(runner/http_server.py) — the same plumbing workers already bootstrap
through, HMAC-authed with the launcher secret.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from ..common import faultline, metrics
from ..common.envutil import env_int

LOG = logging.getLogger("horovod_tpu.serving.router")


def max_batch() -> int:
    """Requests coalesced into one dispatched batch at most
    (``HOROVOD_SERVING_MAX_BATCH``, default 8, floor 1) — the serving
    twin of the fusion buffer threshold."""
    return env_int("HOROVOD_SERVING_MAX_BATCH", 8, minimum=1)


def max_wait_micros() -> int:
    """Longest the oldest queued request waits for companions before
    its batch closes anyway (``HOROVOD_SERVING_MAX_WAIT_MICROS``,
    default 2000, floor 0) — the serving twin of the fusion cycle
    time.  0 = dispatch immediately, batch only what is already
    queued."""
    return env_int("HOROVOD_SERVING_MAX_WAIT_MICROS", 2000, minimum=0)


class InferenceRequest:
    """One queued inference request.  ``payload`` is opaque to the
    router; ``deadline`` (monotonic, absolute) bounds queue wait —
    an expired request resolves ``deadline`` without ever dispatching.
    ``wait()`` blocks the submitting client until a terminal outcome.
    """

    __slots__ = ("id", "deployment", "payload", "arrival", "deadline",
                 "result", "outcome", "attempts", "_done")
    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, deployment: str, payload: Any,
                 timeout_s: Optional[float] = None):
        with InferenceRequest._seq_lock:
            InferenceRequest._seq += 1
            self.id = InferenceRequest._seq
        self.deployment = deployment
        self.payload = payload
        self.arrival = time.monotonic()
        self.deadline = (self.arrival + timeout_s
                         if timeout_s is not None else None)
        self.result: Any = None
        self.outcome: Optional[str] = None  # ok | deadline | dropped
        self.attempts = 0
        self._done = threading.Event()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()


class _Queue:
    """One deployment's pending-request queue + its condition var."""

    __slots__ = ("cond", "items")

    def __init__(self):
        self.cond = threading.Condition()
        self.items: Deque[InferenceRequest] = deque()


class Router:
    """Per-deployment continuous-batching queues (module docstring has
    the policy).  ``max_batch``/``max_wait_micros`` default to the env
    knobs; explicit arguments win (benches A/B them)."""

    def __init__(self, max_batch_size: Optional[int] = None,
                 max_wait_us: Optional[int] = None):
        self.max_batch = (max_batch_size if max_batch_size is not None
                          else max_batch())
        self.max_wait_s = (max_wait_us if max_wait_us is not None
                           else max_wait_micros()) / 1e6
        self._queues: Dict[str, _Queue] = {}
        self._queues_lock = threading.Lock()
        self._closed = False

    def _queue(self, deployment: str) -> _Queue:
        with self._queues_lock:
            q = self._queues.get(deployment)
            if q is None:
                q = self._queues[deployment] = _Queue()
            return q

    # -- client side -------------------------------------------------------

    def submit(self, deployment: str, payload: Any,
               timeout_s: Optional[float] = None) -> InferenceRequest:
        """Enqueue one request; returns immediately (``wait()`` for the
        outcome).  The ``serving.request.drop`` site fires here: a
        dropped request resolves terminally as ``dropped`` and never
        queues."""
        req = InferenceRequest(deployment, payload, timeout_s)
        if faultline.site("serving.request.drop"):
            self._finish(req, "dropped", None)
            LOG.warning("request %d for %s dropped at admission "
                        "(faultline serving.request.drop)",
                        req.id, deployment)
            return req
        q = self._queue(deployment)
        with q.cond:
            q.items.append(req)
            metrics.gauge("serving_queue_depth",
                          deployment=deployment).set(len(q.items))
            q.cond.notify_all()
        return req

    def serve(self, deployment: str, payload: Any,
              timeout_s: Optional[float] = None) -> InferenceRequest:
        """Blocking submit: returns the request after it resolved (or
        after ``timeout_s`` of waiting; the request may still resolve
        later — check ``done``)."""
        req = self.submit(deployment, payload, timeout_s)
        req.wait(timeout_s)
        return req

    def depth(self, deployment: str) -> int:
        q = self._queue(deployment)
        with q.cond:
            return len(q.items)

    def close(self):
        """Unblock every ``next_batch`` waiter (replica shutdown)."""
        self._closed = True
        with self._queues_lock:
            queues = list(self._queues.values())
        for q in queues:
            with q.cond:
                q.cond.notify_all()

    # -- replica side ------------------------------------------------------

    def _expire_locked(self, deployment: str, q: _Queue,
                       now: float) -> Optional[float]:
        """Resolve expired requests (caller holds ``q.cond``); returns
        the nearest future deadline among survivors, or None."""
        nearest: Optional[float] = None
        keep: Deque[InferenceRequest] = deque()
        changed = False
        for req in q.items:
            if req.deadline is not None and now >= req.deadline:
                changed = True
                self._finish(req, "deadline", None)
            else:
                if req.deadline is not None:
                    nearest = (req.deadline if nearest is None
                               else min(nearest, req.deadline))
                keep.append(req)
        if changed:
            q.items = keep
            metrics.gauge("serving_queue_depth",
                          deployment=deployment).set(len(keep))
        return nearest

    def next_batch(self, deployment: str,
                   timeout: Optional[float] = None
                   ) -> List[InferenceRequest]:
        """Block until a batch is ready under the admission policy
        (full, or the oldest request aged past max-wait), then claim
        it.  Returns [] on ``timeout`` (replicas use the idle beat for
        swap checks) or when the router is closed."""
        q = self._queue(deployment)
        give_up = (time.monotonic() + timeout
                   if timeout is not None else None)
        with q.cond:
            while not self._closed:
                now = time.monotonic()
                nearest_deadline = self._expire_locked(deployment, q, now)
                if q.items:
                    close_at = q.items[0].arrival + self.max_wait_s
                    if len(q.items) >= self.max_batch or now >= close_at:
                        batch = [q.items.popleft() for _ in
                                 range(min(self.max_batch,
                                           len(q.items)))]
                        metrics.gauge(
                            "serving_queue_depth",
                            deployment=deployment).set(len(q.items))
                        metrics.histogram("serving_batch_size").observe(
                            len(batch))
                        for req in batch:
                            req.attempts += 1
                        return batch
                    wake_at = close_at
                else:
                    wake_at = None
                if give_up is not None and now >= give_up:
                    return []
                for t in (give_up, nearest_deadline):
                    if t is not None:
                        wake_at = t if wake_at is None \
                            else min(wake_at, t)
                q.cond.wait(None if wake_at is None
                            else max(0.0, wake_at - now))
        return []

    def complete(self, batch: List[InferenceRequest],
                 results: List[Any]):
        """Resolve a dispatched batch ``ok`` with its results
        (positional)."""
        for req, result in zip(batch, results):
            self._finish(req, "ok", result)

    def requeue(self, batch: List[InferenceRequest]):
        """Hand a failed dispatch back (replica died / backend raised):
        surviving requests re-enter AT THE FRONT in arrival order;
        already-expired ones resolve ``deadline``.  This is the
        no-request-lost seam the hot-swap certification leans on."""
        if not batch:
            return
        deployment = batch[0].deployment
        q = self._queue(deployment)
        now = time.monotonic()
        with q.cond:
            for req in reversed(batch):
                if req.deadline is not None and now >= req.deadline:
                    self._finish(req, "deadline", None)
                else:
                    q.items.appendleft(req)
            metrics.gauge("serving_queue_depth",
                          deployment=deployment).set(len(q.items))
            q.cond.notify_all()
        metrics.event("serving_requeue", deployment=deployment,
                      requests=len(batch))
        LOG.warning("requeued %d request(s) for %s after a failed "
                    "dispatch", len(batch), deployment)

    def _finish(self, req: InferenceRequest, outcome: str, result: Any):
        req.outcome = outcome
        req.result = result
        metrics.counter("serving_requests_total",
                        deployment=req.deployment, outcome=outcome).inc()
        if outcome == "ok":
            metrics.histogram(
                "serving_request_seconds",
                deployment=req.deployment).observe(
                    time.monotonic() - req.arrival)
        req._done.set()


# -- HTTP front door --------------------------------------------------------


def serve_http(router: Router, deployment: str, body: bytes,
               timeout_s: float = 30.0) -> bytes:
    """One ``POST /serve/<deployment>`` request through the router:
    JSON body in, JSON ``{id, outcome, result}`` out.  A non-ok
    outcome travels IN the JSON (the HTTP layer reserves 5xx for
    handler crashes, which clients classify as transient)."""
    payload = json.loads(body.decode()) if body else {}
    timeout = float(payload.get("timeout_s", timeout_s))
    req = router.serve(deployment, payload, timeout_s=timeout)
    return json.dumps({
        "id": req.id,
        "outcome": req.outcome if req.done else "deadline",
        "result": req.result,
    }).encode()


def install_http_frontend(server, router: Router,
                          timeout_s: float = 30.0):
    """Mount ``router`` at ``POST /serve/<deployment>`` on a
    :class:`~..runner.http_server.RendezvousServer`."""
    server.serving_provider = (
        lambda deployment, body: serve_http(router, deployment, body,
                                            timeout_s))
