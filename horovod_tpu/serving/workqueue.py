"""Durable request queue for process-mode replica groups.

The in-process :class:`~.router.Router` holds requests in memory — the
right latency path for one serving frontend, the wrong durability
story for replicas that are REAL processes the pod scheduler can
spawn, preempt and lose.  This queue is the process-mode transport:
one directory tree on shared storage, with atomic-rename claim
semantics so every request is served despite replica death —

    <root>/pending/req-<id>.json      submitted, unclaimed
    <root>/claimed/req-<id>.<pid>.json  claimed by live process <pid>
    <root>/done/<id>.json             result (atomic tmp+replace)

* **Claim** — ``os.rename`` of the pending file into ``claimed/``
  stamped with the claimant's pid: atomic on POSIX, so two replicas
  racing the same request resolve to exactly one winner (the loser's
  rename raises and it moves on).
* **Requeue on death** — ``sweep_dead_claimants`` renames claims whose
  pid is no longer alive back to ``pending/`` (pid liveness via
  ``os.kill(pid, 0)`` — HOST-LOCAL by design; multi-host deployments
  back this with a lease age, see ``stale_claim_secs``).  A replica
  that died mid-batch therefore loses its claim, not the request.
* **At-least-once, idempotent** — a replica that died after writing
  ``done/`` but before releasing its claim gets its work re-done by a
  survivor; ``done/<id>.json`` is keyed by request id and atomically
  replaced, so duplicates collapse and ``done_count`` never
  double-counts.

No request is EVER deleted from the tree before its result exists —
"no request lost" is a filesystem invariant here, certified by the
hot-swap e2e under ``serving.replica.die`` injection.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
import time
import uuid
from typing import Dict, List, Optional

LOG = logging.getLogger("horovod_tpu.serving.workqueue")

_PENDING, _CLAIMED, _DONE = "pending", "claimed", "done"

# Generated request ids must sort in ARRIVAL order (claim() walks the
# pending dir lexicographically): fixed-width nanosecond timestamp,
# a per-process sequence for same-tick ties, a random suffix for
# cross-process uniqueness.  A bare uuid here would make claim order
# random and let a high-hex request starve under sustained load.
_id_seq = itertools.count()


def _generated_id() -> str:
    return "%016x-%08x-%s" % (time.time_ns(), next(_id_seq),
                              uuid.uuid4().hex[:8])


class Claim:
    """One claimed request: serve it, then ``complete(claim, result)``."""

    __slots__ = ("req_id", "payload", "path")

    def __init__(self, req_id: str, payload: Dict, path: str):
        self.req_id = req_id
        self.payload = payload
        self.path = path


def _atomic_write(path: str, data: bytes):
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(prefix=".tmp-req-", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class FileWorkQueue:
    """See module docstring.  ``stale_claim_secs`` (default 120)
    additionally requeues claims older than the window even when their
    pid LOOKS alive — the wedged-replica backstop, and the correctness
    net when claimants run on another host (where pid liveness is
    meaningless and every claim looks alive)."""

    def __init__(self, root: str, stale_claim_secs: float = 120.0):
        self.root = root
        self.stale_claim_secs = stale_claim_secs
        for sub in (_PENDING, _CLAIMED, _DONE):
            os.makedirs(os.path.join(root, sub), exist_ok=True)

    def _dir(self, sub: str) -> str:
        return os.path.join(self.root, sub)

    # -- producer side -----------------------------------------------------

    def submit(self, payload: Dict,
               req_id: Optional[str] = None) -> str:
        """Enqueue one request; ids must not contain ``.`` (the claim
        filename separator).  Generated ids sort in arrival order;
        caller-provided ids are claimed in THEIR lexicographic order.
        """
        req_id = req_id if req_id is not None else _generated_id()
        if "." in req_id or "/" in req_id:
            raise ValueError("request id %r may not contain '.' or '/'"
                             % req_id)
        _atomic_write(os.path.join(self._dir(_PENDING),
                                   "req-%s.json" % req_id),
                      json.dumps(payload).encode())
        return req_id

    def result(self, req_id: str) -> Optional[Dict]:
        try:
            with open(os.path.join(self._dir(_DONE),
                                   "%s.json" % req_id), "rb") as f:
                return json.loads(f.read().decode())
        except (OSError, ValueError):
            return None

    def depth(self) -> int:
        return len([n for n in os.listdir(self._dir(_PENDING))
                    if n.startswith("req-")])

    def done_count(self) -> int:
        return len([n for n in os.listdir(self._dir(_DONE))
                    if n.endswith(".json")])

    # -- replica side ------------------------------------------------------

    def claim(self, n: int) -> List[Claim]:
        """Claim up to ``n`` pending requests (oldest id first); a
        rename lost to a racing replica is simply skipped."""
        out: List[Claim] = []
        for name in sorted(os.listdir(self._dir(_PENDING))):
            if len(out) >= n:
                break
            if not (name.startswith("req-") and name.endswith(".json")):
                continue
            req_id = name[len("req-"):-len(".json")]
            src = os.path.join(self._dir(_PENDING), name)
            dst = os.path.join(self._dir(_CLAIMED),
                               "req-%s.%d.json" % (req_id, os.getpid()))
            try:
                os.rename(src, dst)
            except OSError:
                continue  # another replica won the claim race
            try:
                # rename preserves the SUBMIT-time mtime; the stale
                # window must run from CLAIM time, or any backlog older
                # than the window would be instantly "stale" and
                # double-served the moment it was claimed.
                os.utime(dst)
            except OSError:
                pass  # worst case: the submit-age heuristic applies
            try:
                with open(dst, "rb") as f:
                    payload = json.loads(f.read().decode())
            except (OSError, ValueError) as exc:
                LOG.warning("claimed request %s is unreadable (%s); "
                            "leaving the claim for the sweeper", req_id,
                            exc)
                continue
            out.append(Claim(req_id, payload, dst))
        return out

    def complete(self, claim: Claim, result: Dict):
        """Write the result atomically, THEN release the claim — a
        crash between the two re-serves the request, never loses it."""
        _atomic_write(os.path.join(self._dir(_DONE),
                                   "%s.json" % claim.req_id),
                      json.dumps(result).encode())
        try:
            os.unlink(claim.path)
        except OSError:
            pass  # sweeper may have requeued a slow serve; done wins

    def sweep_dead_claimants(self) -> int:
        """Requeue claims held by dead pids (or older than the stale
        window); returns how many were handed back.  Already-completed
        requests are released instead of requeued."""
        requeued = 0
        now = time.time()
        for name in list(os.listdir(self._dir(_CLAIMED))):
            if not (name.startswith("req-") and name.endswith(".json")):
                continue
            stem = name[len("req-"):-len(".json")]
            req_id, _, pid_text = stem.rpartition(".")
            path = os.path.join(self._dir(_CLAIMED), name)
            try:
                pid = int(pid_text)
            except ValueError:
                continue
            alive = True
            if pid != os.getpid():
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    alive = False
                except OSError:
                    pass  # EPERM etc: alive but not ours
            if alive:
                try:
                    stale = now - os.path.getmtime(path) \
                        > self.stale_claim_secs
                except OSError:
                    continue  # completed/requeued under us
                if not stale:
                    continue
            if self.result(req_id) is not None:
                # Served before the claimant died: release, don't redo.
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            dst = os.path.join(self._dir(_PENDING),
                               "req-%s.json" % req_id)
            try:
                os.rename(path, dst)
                requeued += 1
                LOG.warning("requeued request %s from dead claimant "
                            "pid %d", req_id, pid)
            except OSError:
                continue  # raced another sweeper
        return requeued
