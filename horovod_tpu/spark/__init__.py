"""Spark platform integration.

Reference parity: ``horovod/spark/__init__.py`` (``horovod.spark.run``)
— run a distributed training function on Spark executors.  The
reference orchestrates task services + mpirun into executors; here the
natural carrier is Spark's **barrier execution mode**: one barrier task
per rank, rank = partition id, bootstrap through the driver's
rendezvous KV server, collectives over the native TCP core (exactly the
world the launcher would build, with Spark doing the process placement).

pyspark is not bundled in this environment; everything imports lazily
so the module is importable (and unit-testable) without it.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

from ..runner import util
from ..runner.http_server import RendezvousServer

from .elastic import run_elastic  # noqa: E402,F401  (pyspark-free import)

__all__ = ["run", "run_elastic", "default_num_proc"]


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as exc:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.spark requires pyspark (pip install pyspark)"
        ) from exc


def default_num_proc() -> int:
    pyspark = _require_pyspark()
    sc = pyspark.SparkContext._active_spark_context
    return sc.defaultParallelism if sc else 1


_driver_ip = util.routable_ip


def _make_mapper(fn: Callable, args: tuple, kwargs: Dict,
                 num_proc: int, rendezvous_addr: str, secret: str,
                 extra_env: Dict[str, str]):
    """The barrier-task body (runs on executors; must be picklable)."""

    def mapper(_):
        from pyspark import BarrierTaskContext
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        infos = ctx.getTaskInfos()
        hosts = [t.address.split(":")[0] for t in infos]
        my_host = hosts[rank]
        local_ranks = [i for i, h in enumerate(hosts) if h == my_host]
        unique_hosts: List[str] = []
        for h in hosts:
            if h not in unique_hosts:
                unique_hosts.append(h)
        os.environ.update(extra_env)
        os.environ.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(num_proc),
            "HOROVOD_LOCAL_RANK": str(local_ranks.index(rank)),
            "HOROVOD_LOCAL_SIZE": str(len(local_ranks)),
            "HOROVOD_CROSS_RANK": str(unique_hosts.index(my_host)),
            "HOROVOD_CROSS_SIZE": str(len(unique_hosts)),
            "HOROVOD_RENDEZVOUS_ADDR": rendezvous_addr,
            "HOROVOD_SECRET_KEY": secret,
            "HOROVOD_HOSTNAME": my_host,
            "HOROVOD_CONTROLLER": "tcp",
        })
        result = fn(*args, **(kwargs or {}))
        ctx.barrier()
        yield rank, result

    return mapper


def run(fn: Callable, args: tuple = (), kwargs: Optional[Dict] = None,
        num_proc: Optional[int] = None,
        extra_env: Optional[Dict[str, str]] = None,
        verbose: int = 1) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark executors as one Horovod world
    (reference ``horovod.spark.run``); returns per-rank results ordered
    by rank."""
    pyspark = _require_pyspark()
    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create a "
                           "SparkSession before horovod_tpu.spark.run")
    num_proc = num_proc or sc.defaultParallelism
    secret = util.make_secret()
    server = RendezvousServer(secret=secret)
    port = server.start()
    addr = "%s:%d" % (_driver_ip(), port)
    if verbose:
        print("horovod_tpu.spark: %d ranks, rendezvous at %s"
              % (num_proc, addr))
    mapper = _make_mapper(fn, args, kwargs or {}, num_proc, addr,
                          secret, extra_env or {})
    try:
        rdd = sc.parallelize(range(num_proc), num_proc)
        results = rdd.barrier().mapPartitions(mapper).collect()
        return [r for _, r in sorted(results)]
    finally:
        server.stop()
