"""Shared Spark-estimator machinery (reference ``horovod/spark/common/``):
``store`` (artifact/dataset storage), ``params`` (estimator params),
``backend`` (how the distributed training fn is executed),
``serialization`` (model <-> bytes).
"""

from .backend import Backend, LocalBackend, SparkBackend  # noqa: F401
from .params import EstimatorParams  # noqa: F401
from .store import (ArrowFsStore, DBFSLocalStore,  # noqa: F401
                    FilesystemStore, HDFSStore,
                    LocalStore, Store)
