"""Estimator execution backends.

Reference parity: ``horovod/spark/common/backend.py`` — a ``Backend``
abstracts *how* the distributed training function runs:
``SparkBackend`` submits it through ``horovod.spark.run`` (barrier
tasks on executors); the reference's ``LocalBackend`` runs it in
plain local processes for testing.  Here ``LocalBackend`` launches a
real multi-process world through this framework's launcher
(``horovod_tpu.runner.run``) — the same strategy the reference tests
use (local-mode Spark / localhost Gloo).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

__all__ = ["Backend", "SparkBackend", "LocalBackend",
           "has_active_spark"]


def has_active_spark() -> bool:
    """True when a SparkContext is live in this process (drives the
    estimators' default backend choice)."""
    try:
        import pyspark
        return pyspark.SparkContext._active_spark_context is not None
    except ImportError:
        return False


class Backend:
    """Executes ``fn`` on every rank of a fresh world and returns the
    per-rank results in rank order (reference ``Backend.run``)."""

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict] = None,
            env: Optional[Dict[str, str]] = None) -> List[Any]:
        raise NotImplementedError

    def num_processes(self) -> int:
        raise NotImplementedError


class SparkBackend(Backend):
    """Runs the training fn as Spark barrier tasks (reference
    ``SparkBackend``): one task per rank on the executors, rendezvous
    through the driver."""

    def __init__(self, num_proc: Optional[int] = None, verbose: int = 1):
        self._num_proc = num_proc
        self._verbose = verbose

    def run(self, fn, args=(), kwargs=None, env=None):
        from .. import run as spark_run
        return spark_run(fn, args=args, kwargs=kwargs or {},
                         num_proc=self._num_proc,
                         extra_env=env or {}, verbose=self._verbose)

    def num_processes(self) -> int:
        from .. import default_num_proc
        return self._num_proc or default_num_proc()


class LocalBackend(Backend):
    """Runs the training fn on a real local multi-process world via the
    launcher — no Spark required.  This is both the test backend and a
    single-host convenience (reference ``LocalBackend``)."""

    def __init__(self, num_proc: int = 1, verbose: bool = False):
        self._num_proc = num_proc
        self._verbose = verbose

    def run(self, fn, args=(), kwargs=None, env=None):
        from ...runner.run_api import run as launcher_run
        # env rides the launcher's per-run overlay — the driver process
        # environment is never mutated, so overlays cannot leak into
        # later runs.
        return launcher_run(fn, args=args, kwargs=kwargs or {},
                            np=self._num_proc, verbose=self._verbose,
                            env=env)

    def num_processes(self) -> int:
        return self._num_proc
