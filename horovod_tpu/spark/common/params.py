"""Estimator hyper-parameters.

Reference parity: ``horovod/spark/common/params.py`` —
``EstimatorParams`` defines the shared param surface (num_proc, model,
store, feature/label columns, batch size, epochs, validation split,
shuffle, verbose, callbacks, custom objects) with getter/setter pairs
in the Spark ML ``Params`` style.  The reference builds on
``pyspark.ml.param``; this build keeps the same ``setX``/``getX``
surface over plain attributes so the estimators work (and are
testable) with or without pyspark.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["EstimatorParams"]


class EstimatorParams:
    """Shared estimator params with reference-style accessors:
    ``est.setEpochs(3).setBatchSize(32)`` chains, ``est.getEpochs()``
    reads, and keyword construction works too."""

    _param_names = [
        "num_proc", "model", "store", "backend", "loss", "metrics",
        "optimizer", "feature_cols", "label_cols", "validation",
        "batch_size", "epochs", "verbose", "shuffle", "callbacks",
        "custom_objects", "run_id", "train_steps_per_epoch",
        "validation_steps_per_epoch", "sample_weight_col",
    ]

    _defaults: Dict[str, Any] = {
        "num_proc": None, "model": None, "store": None, "backend": None,
        "loss": None, "metrics": [], "optimizer": None,
        "feature_cols": ["features"], "label_cols": ["label"],
        "validation": None, "batch_size": 32, "epochs": 1,
        "verbose": 1, "shuffle": True, "callbacks": [],
        "custom_objects": None, "run_id": None,
        "train_steps_per_epoch": None,
        "validation_steps_per_epoch": None, "sample_weight_col": None,
    }

    def __init__(self, **kwargs):
        for name in self._param_names:
            default = self._defaults[name]
            setattr(self, name,
                    list(default) if isinstance(default, list)
                    else default)
        unknown = set(kwargs) - set(self._param_names)
        if unknown:
            raise ValueError("unknown estimator params: %s"
                             % sorted(unknown))
        for k, v in kwargs.items():
            setattr(self, k, v)

    # Reference-style accessors: setNumProc/getNumProc for every param.
    @staticmethod
    def _camel(name: str) -> str:
        return "".join(p.capitalize() for p in name.split("_"))

    def __getattr__(self, item):
        # only called for missing attributes: resolve setX/getX
        if item.startswith("set") or item.startswith("get"):
            kind, camel = item[:3], item[3:]
            for name in object.__getattribute__(self, "_param_names"):
                if self._camel(name) == camel:
                    if kind == "get":
                        return lambda: getattr(self, name)

                    def setter(value, _name=name):
                        setattr(self, _name, value)
                        return self
                    return setter
        raise AttributeError(item)

    def _check_params(self):
        if self.model is None:
            raise ValueError("model is required")
        if self.store is None:
            raise ValueError("store is required (e.g. "
                             "Store.create('/tmp/hvd_store'))")
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if self.epochs <= 0:
            raise ValueError("epochs must be positive")
        if not self.feature_cols or not self.label_cols:
            raise ValueError("feature_cols and label_cols are required")

    def _params_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name)
                for name in self._param_names}
