"""Model (de)serialization for the estimators.

Reference parity: ``horovod/spark/common/serialization.py`` /
``horovod/spark/keras/util.py`` — models cross the driver→worker and
worker→store boundaries as bytes.  Keras models ride the ``.keras``
saved format; torch models ride ``torch.save`` of the module (and
``state_dict`` for checkpoints); generic payloads ride pickle.
"""

from __future__ import annotations

import io
import os
import pickle
import tempfile
from typing import Any

__all__ = ["serialize_keras_model", "deserialize_keras_model",
           "serialize_torch_model", "deserialize_torch_model",
           "serialize_generic", "deserialize_generic"]


def serialize_keras_model(model) -> bytes:
    import keras  # noqa: F401
    fd, path = tempfile.mkstemp(suffix=".keras")
    os.close(fd)
    try:
        model.save(path)
        with open(path, "rb") as f:
            return f.read()
    finally:
        os.remove(path)


def deserialize_keras_model(data: bytes, custom_objects=None):
    import keras
    fd, path = tempfile.mkstemp(suffix=".keras")
    os.close(fd)
    try:
        with open(path, "wb") as f:
            f.write(data)
        return keras.models.load_model(path,
                                       custom_objects=custom_objects)
    finally:
        os.remove(path)


def serialize_torch_model(model) -> bytes:
    import torch
    buf = io.BytesIO()
    torch.save(model, buf)
    return buf.getvalue()


def deserialize_torch_model(data: bytes):
    import torch
    return torch.load(io.BytesIO(data), weights_only=False)


def serialize_generic(obj: Any) -> bytes:
    return pickle.dumps(obj)


def deserialize_generic(data: bytes) -> Any:
    return pickle.loads(data)
