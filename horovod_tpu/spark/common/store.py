"""Estimator storage abstraction.

Reference parity: ``horovod/spark/common/store.py`` — a ``Store`` knows
where intermediate training data, run artifacts, and checkpoints live
(``get_train_data_path``/``get_val_data_path``/``get_run_path``/
``get_checkpoint_path``, ``exists``/``read``/``write_text``,
``sync_fn``), with concrete stores for the local filesystem
(``LocalStore``), HDFS (``HDFSStore``), and Databricks DBFS
(``DBFSLocalStore``).  The reference materializes DataFrames through
Petastorm; this build's native dataset format is **parquet via
pyarrow** (read sharded by row on the workers), which needs no extra
dependency and feeds numpy/JAX directly.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

__all__ = ["Store", "FilesystemStore", "LocalStore", "ArrowFsStore",
           "HDFSStore", "DBFSLocalStore"]


class Store:
    """Base class (reference ``Store``): path layout +  IO primitives.

    Layout under ``prefix_path``:
      ``intermediate_train_data/`` — materialized training parquet
      ``intermediate_val_data/``   — materialized validation parquet
      ``runs/<run_id>/``           — per-run artifacts (checkpoints,
                                     logs, final model)
    """

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path

    # -- path layout (reference get_*_path methods) --------------------

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        p = os.path.join(self.prefix_path, "intermediate_train_data")
        return p if idx is None else "%s.%d" % (p, idx)

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        p = os.path.join(self.prefix_path, "intermediate_val_data")
        return p if idx is None else "%s.%d" % (p, idx)

    def get_test_data_path(self, idx: Optional[int] = None) -> str:
        p = os.path.join(self.prefix_path, "intermediate_test_data")
        return p if idx is None else "%s.%d" % (p, idx)

    def get_runs_path(self) -> str:
        return os.path.join(self.prefix_path, "runs")

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self.get_runs_path(), run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id),
                            self.checkpoint_filename())

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def checkpoint_filename(self) -> str:
        return "checkpoint.bin"

    def is_parquet_dataset(self, path: str) -> bool:
        try:
            return any(n.endswith(".parquet")
                       for n in self.listdir(path))
        except OSError:
            return False

    # -- IO primitives (implemented by concrete stores) ----------------

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes):
        raise NotImplementedError

    def listdir(self, path: str):
        raise NotImplementedError

    def makedirs(self, path: str):
        raise NotImplementedError

    def delete(self, path: str):
        raise NotImplementedError

    def sync_fn(self, run_id: str):
        """Return a callable(local_dir) that publishes a worker's local
        artifacts into the store's run dir (reference ``sync_fn``)."""
        raise NotImplementedError

    # -- factory (reference Store.create) ------------------------------

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith("dbfs:/") or \
                prefix_path.startswith("/dbfs"):
            return DBFSLocalStore(prefix_path, *args, **kwargs)
        return LocalStore(prefix_path, *args, **kwargs)


class FilesystemStore(Store):
    """Store over a mounted POSIX filesystem (reference
    ``FilesystemStore``): plain ``os``/``shutil`` IO."""

    def __init__(self, prefix_path: str):
        super().__init__(os.path.abspath(
            prefix_path[len("file://"):] if
            prefix_path.startswith("file://") else prefix_path))
        os.makedirs(self.prefix_path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def listdir(self, path: str):
        return sorted(os.listdir(path))

    def makedirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def sync_fn(self, run_id: str):
        run_path = self.get_run_path(run_id)

        def fn(local_dir: str):
            os.makedirs(run_path, exist_ok=True)
            for root, _, files in os.walk(local_dir):
                rel = os.path.relpath(root, local_dir)
                dst_root = (run_path if rel == "." else
                            os.path.join(run_path, rel))
                os.makedirs(dst_root, exist_ok=True)
                for name in files:
                    shutil.copy2(os.path.join(root, name),
                                 os.path.join(dst_root, name))

        return fn


class LocalStore(FilesystemStore):
    """Local-FS store (reference ``LocalStore``)."""


class DBFSLocalStore(FilesystemStore):
    """Databricks DBFS mounted under ``/dbfs`` (reference
    ``DBFSLocalStore``): same POSIX IO, normalized prefix."""

    def __init__(self, prefix_path: str):
        if prefix_path.startswith("dbfs:/"):
            prefix_path = "/dbfs/" + prefix_path[len("dbfs:/"):].lstrip("/")
        super().__init__(prefix_path)


class ArrowFsStore(Store):
    """Store over any ``pyarrow.fs.FileSystem``.

    The filesystem is injected, so the exact logic HDFS runs is
    executed in tests against ``pyarrow.fs.LocalFileSystem`` — the
    reference tests its ``HDFSStore`` the same way (a local filesystem
    standing in for the cluster).
    """

    def __init__(self, prefix_path: str, filesystem):
        super().__init__(prefix_path)
        self._fs = filesystem
        self._made_dirs: set = set()

    def exists(self, path: str) -> bool:
        from pyarrow import fs as pafs
        info = self._fs.get_file_info([path])[0]
        return info.type != pafs.FileType.NotFound

    def read(self, path: str) -> bytes:
        with self._fs.open_input_stream(path) as f:
            return f.read()

    def write(self, path: str, data: bytes):
        # One create_dir round trip per DIRECTORY, not per file: on a
        # remote namenode, sync_fn writes many files into few dirs.
        parent = os.path.dirname(path)
        if parent not in self._made_dirs:
            self.makedirs(parent)
            self._made_dirs.add(parent)
        with self._fs.open_output_stream(path) as f:
            f.write(data)

    def listdir(self, path: str):
        from pyarrow import fs as pafs
        sel = pafs.FileSelector(path)
        return sorted(i.path for i in self._fs.get_file_info(sel))

    def makedirs(self, path: str):
        self._fs.create_dir(path, recursive=True)

    def delete(self, path: str):
        from pyarrow import fs as pafs
        # Deleted dirs must fall out of the write() memo.
        self._made_dirs = {d for d in self._made_dirs
                           if not d.startswith(path)}
        info = self._fs.get_file_info([path])[0]
        if info.type == pafs.FileType.NotFound:
            return
        if info.type == pafs.FileType.Directory:
            self._fs.delete_dir(path)
        else:
            self._fs.delete_file(path)

    def sync_fn(self, run_id: str):
        run_path = self.get_run_path(run_id)

        def fn(local_dir: str):
            for root, _, files in os.walk(local_dir):
                rel = os.path.relpath(root, local_dir)
                dst_root = (run_path if rel == "." else
                            os.path.join(run_path, rel))
                for name in files:
                    with open(os.path.join(root, name), "rb") as f:
                        self.write(os.path.join(dst_root, name),
                                   f.read())

        return fn


class HDFSStore(ArrowFsStore):
    """HDFS store (reference ``HDFSStore``), via ``pyarrow.fs``.

    Requires a reachable HDFS (libhdfs); constructing one without it
    raises with instructions, keeping the rest of the package usable.
    """

    def __init__(self, prefix_path: str, host: Optional[str] = None,
                 port: Optional[int] = None, user: Optional[str] = None):
        try:
            from pyarrow import fs as pafs
        except ImportError as exc:  # pragma: no cover
            raise ImportError("HDFSStore requires pyarrow") from exc
        try:
            filesystem = pafs.HadoopFileSystem(
                host or "default", port or 0, user=user)
        except Exception as exc:  # pragma: no cover - needs a cluster
            raise RuntimeError(
                "HDFSStore could not connect to HDFS (is libhdfs / a "
                "cluster available?): %s" % exc) from exc
        super().__init__(prefix_path, filesystem)
