"""Dataset materialization + sharded reading for the estimators.

Reference parity: ``horovod/spark/common/util.py`` — the reference
materializes a Spark DataFrame to Parquet (via Petastorm) and each
worker reads its shard.  Here the writer accepts a Spark **or** pandas
DataFrame (parquet via Spark's writer or pyarrow respectively) and
workers read a row-sharded numpy view with pyarrow — the natural feed
into numpy/JAX/keras/torch.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["materialize_dataframe", "read_parquet_shard",
           "check_validation"]


def _is_spark_df(df) -> bool:
    mod = type(df).__module__
    return mod.startswith("pyspark.")


def materialize_dataframe(df, path: str, store,
                          partitions: Optional[int] = None):
    """Write ``df`` (Spark or pandas) as a parquet dataset at ``path``
    inside ``store``; skips rewrite if already materialized there."""
    if store.is_parquet_dataset(path):
        store.delete(path)
    if _is_spark_df(df):
        writer = df.repartition(partitions) if partitions else df
        writer.write.mode("overwrite").parquet(path)
        return
    # pandas path (LocalBackend / tests)
    import pyarrow as pa
    import pyarrow.parquet as pq
    store.makedirs(path)
    table = pa.Table.from_pandas(df, preserve_index=False)
    pq.write_table(table, os.path.join(path, "part-00000.parquet"))


def read_parquet_shard(path: str, rank: int, size: int,
                       feature_cols: Sequence[str],
                       label_cols: Sequence[str],
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Read rows ``rank::size`` of the parquet dataset into
    ``(features, labels)`` float32 arrays.  Multiple feature columns
    are stacked along the last axis; a single column holding
    fixed-length lists becomes a 2-D array."""
    import pyarrow.parquet as pq
    files = sorted(os.path.join(path, n) for n in os.listdir(path)
                   if n.endswith(".parquet"))
    if not files:
        raise FileNotFoundError("no parquet files under %s" % path)
    tables = [pq.read_table(f, columns=list(feature_cols) +
                            list(label_cols)) for f in files]
    import pyarrow as pa
    table = pa.concat_tables(tables)

    def cols_to_array(cols: Sequence[str]) -> np.ndarray:
        arrays: List[np.ndarray] = []
        for c in cols:
            col = table.column(c).to_numpy(zero_copy_only=False)
            if col.dtype == object:  # list column → 2-D
                col = np.stack([np.asarray(v, np.float32) for v in col])
            arrays.append(col.astype(np.float32))
        if len(arrays) == 1:
            return arrays[0]
        return np.stack(arrays, axis=-1)

    x = cols_to_array(feature_cols)[rank::size]
    y = cols_to_array(label_cols)[rank::size]
    return x, y


def check_validation(validation) -> float:
    """Normalize the ``validation`` param (reference semantics: a float
    in (0,1) = split fraction; None = no validation)."""
    if validation is None:
        return 0.0
    v = float(validation)
    if not 0.0 < v < 1.0:
        raise ValueError("validation must be a fraction in (0, 1)")
    return v
