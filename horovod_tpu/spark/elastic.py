"""Elastic training on Spark executors.

Reference parity: ``horovod.spark.run_elastic`` and the task-service
architecture in ``horovod/spark/driver/`` + ``horovod/spark/task/``:
the driver cannot place Spark tasks on chosen hosts, so placement is
inverted — Spark schedules AGENT tasks wherever it likes, each agent
registers its (host, slot) with the elastic driver, and the driver
discovers its world from the live agents and starts/stops worker
processes THROUGH them (``TaskService`` "run"/"proc_poll"/"proc_stop").

Worker results ride the driver's rendezvous KV (executors share no
filesystem with the driver), keyed ``result/<rank>`` with the epoch's
world size, so the final world's values are collected exactly like the
programmatic ``run`` API's file protocol.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..elastic.discovery import HostDiscovery
from ..elastic.driver import ElasticDriver, Slot
from ..runner import util
from ..runner.services import TaskService, send_message

__all__ = ["run_elastic"]


class _AgentRegistry:
    """Live agent task services: per-host ordered lists, compacted when
    an agent dies, so (host, i) always resolves to the i-th LIVE agent
    — matching ``ordered_slots``' 0-based renumbering.  Already-running
    workers are unaffected by compaction: their ``_AgentProc`` captured
    the agent address at spawn time."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_host: Dict[str, List[Tuple[str, int]]] = {}

    def register(self, host: str, port: int) -> int:
        with self._lock:
            lst = self._by_host.setdefault(host, [])
            lst.append((host, port))
            return len(lst) - 1

    def addr(self, slot: Slot) -> Optional[Tuple[str, int]]:
        host, idx = slot
        with self._lock:
            lst = self._by_host.get(host, [])
            return lst[idx] if idx < len(lst) else None

    def drop_addr(self, addr: Tuple[str, int]):
        with self._lock:
            lst = self._by_host.get(addr[0], [])
            if addr in lst:
                lst.remove(addr)

    def addrs(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [a for lst in self._by_host.values() for a in lst]


class AgentDiscovery(HostDiscovery):
    """Hosts = wherever live agents registered from (ping-checked)."""

    _MAX_PING_FAILURES = 3

    def __init__(self, registry: _AgentRegistry,
                 secret: Optional[str] = None):
        self._registry = registry
        self._secret = secret  # installed from the driver's after build
        self._ping_failures: Dict[Tuple[str, int], int] = {}

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        hosts: Dict[str, int] = {}
        for addr in self._registry.addrs():
            try:
                send_message(addr, self._secret, {"kind": "ping"},
                             timeout=5.0)
                self._ping_failures.pop(addr, None)
            except Exception:  # noqa: BLE001 - transient or task lost
                # One blip must not kill a live agent (its healthy
                # worker would be renumbered away); drop only after
                # consecutive failures.
                n = self._ping_failures.get(addr, 0) + 1
                self._ping_failures[addr] = n
                if n >= self._MAX_PING_FAILURES:
                    self._registry.drop_addr(addr)
                    self._ping_failures.pop(addr, None)
                    continue
            hosts[addr[0]] = hosts.get(addr[0], 0) + 1
        return hosts


class _AgentProc:
    """Proc-like proxy for a worker process running under an agent.
    Polls are rate-limited (the driver's reap loop runs at 10 Hz) and a
    single failed RPC is retried before the agent is declared dead."""

    _POLL_INTERVAL = 1.0
    _MAX_FAILURES = 3

    def __init__(self, addr: Tuple[str, int], secret: str):
        self._addr = addr
        self._secret = secret
        self._failures = 0
        self._last_poll = 0.0
        self._last_rc = None

    def poll(self):
        if self._last_rc is not None:
            return self._last_rc  # terminal
        now = time.monotonic()
        if now - self._last_poll < self._POLL_INTERVAL:
            return None
        self._last_poll = now
        try:
            resp = send_message(self._addr, self._secret,
                                {"kind": "proc_poll"}, timeout=5.0)
            self._failures = 0
            # An agent with NO process (restarted, lost state) must not
            # read as "running" forever: treat it as a failed spawn so
            # the driver's reap loop retries the slot.  Older agents
            # without the has_proc field keep the lenient reading.
            if resp.get("has_proc") is False:
                self._last_rc = 1
                return 1
            self._last_rc = resp["rc"]
            return self._last_rc
        except Exception:  # noqa: BLE001 - transient or dead agent
            self._failures += 1
            if self._failures >= self._MAX_FAILURES:
                self._last_rc = 1  # agent gone = worker failed
                return 1
            return None

    def terminate(self):
        try:
            send_message(self._addr, self._secret,
                         {"kind": "proc_stop"}, timeout=5.0)
        except Exception:  # noqa: BLE001 - already gone
            pass


class SparkElasticDriver(ElasticDriver):
    """ElasticDriver whose workers run under Spark agent tasks."""

    def __init__(self, *args, registry: _AgentRegistry, **kwargs):
        super().__init__(*args, **kwargs)
        self._agents = registry
        self._extra_handler = self._handle_agent

    def _handle_agent(self, req: Dict) -> Dict:
        if req.get("kind") == "agent_register":
            idx = self._agents.register(req["host"], int(req["port"]))
            return {"ok": True, "slot": idx}
        return {"error": "unknown request %r" % req.get("kind")}

    def _make_worker_proc(self, slot: Slot, env: Dict[str, str]):
        addr = self._agents.addr(slot)
        if addr is None:
            return None  # agent not registered yet; reap loop retries
        # Agents run in foreign interpreters: only string env crosses.
        try:
            resp = send_message(addr, self._secret, {
                "kind": "run", "cmd": list(self.command),
                "env": {k: str(v) for k, v in env.items()}}, timeout=10.0)
        except Exception:  # noqa: BLE001 - agent died between ping+run
            self._agents.drop_addr(addr)
            return None
        if resp.get("error"):
            # Agent refused (e.g. a previous epoch's worker is still
            # being stopped): decline so the reap loop retries rather
            # than attaching to the wrong process.
            return None
        return _AgentProc(addr, self._secret)

    def shutdown_agents(self):
        for addr in self._agents.addrs():
            try:
                send_message(addr, self._secret,
                             {"kind": "notify",
                              "payload": {"type": "agent_exit"}},
                             timeout=5.0)
            except Exception:  # noqa: BLE001 - already gone
                pass


def _agent_mapper(driver_addr: Tuple[str, int], secret: str,
                  extra_env: Dict[str, str]):
    """Body of one Spark agent task (must be picklable)."""

    def mapper(it):
        import socket as _socket
        os.environ.update(extra_env)
        pid = next(iter(it), 0)
        # Test hook: stagger agent registration per partition so scale-
        # up (an agent appearing mid-run) is exercisable; unset in
        # production.
        stagger = float(extra_env.get("HVD_TPU_TEST_AGENT_STAGGER", 0))
        if stagger and pid:
            time.sleep(stagger * pid)
        try:
            host = _socket.gethostbyname(_socket.gethostname())
        except _socket.gaierror:
            host = "127.0.0.1"
        if driver_addr[0].startswith("127."):
            host = "127.0.0.1"  # single-machine worlds stay on loopback
        done = threading.Event()
        agent = TaskService(index=0, secret=secret)
        agent.on_notify(lambda payload: done.set()
                        if (payload or {}).get("type") == "agent_exit"
                        else None)
        port = agent.server.start()
        # The driver's message server comes up inside driver.run();
        # agents may be scheduled first, so registration retries.
        slot = None
        deadline = time.monotonic() + 120.0
        while True:
            try:
                resp = send_message(driver_addr, secret, {
                    "kind": "agent_register", "host": host,
                    "port": port}, timeout=10.0)
                slot = resp.get("slot")
                break
            except Exception:  # noqa: BLE001 - driver not serving yet
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        # Wait for agent_exit, but don't leak the Spark task forever if
        # the single best-effort notify is lost: when the driver itself
        # stops answering pings, exit.
        misses = 0
        while not done.wait(10.0):
            try:
                send_message(driver_addr, secret, {"kind": "ping"},
                             timeout=5.0)
                misses = 0
            except Exception:  # noqa: BLE001 - driver gone?
                misses += 1
                if misses >= 3:
                    break
        agent.server.stop()
        yield ("agent", host, slot)

    return mapper


def _worker_body(fn: Callable, args: tuple, kwargs: Dict):
    """Runs on the worker process under an agent: execute fn, then PUT
    the result to the driver's rendezvous KV (no shared filesystem)."""
    result = fn(*args, **(kwargs or {}))
    from ..runner.http_client import RendezvousClient
    from ..runner.util import dumps_base64
    addr = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
    rank = os.environ["HOROVOD_RANK"]
    size = os.environ["HOROVOD_SIZE"]
    client = RendezvousClient(addr,
                              secret=os.environ.get("HOROVOD_SECRET_KEY"))
    client.put("result/%s" % rank, dumps_base64((int(size), result)))
    return result


_WORKER_STUB = r"""
import os
from horovod_tpu.runner.util import loads_base64
from horovod_tpu.spark.elastic import _worker_body
fn, args, kwargs = loads_base64(os.environ["HVD_TPU_RUN_PAYLOAD"])
_worker_body(fn, args, kwargs)
"""


def run_elastic(fn: Callable, args: tuple = (),
                kwargs: Optional[Dict] = None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None,
                max_np: Optional[int] = None,
                elastic_timeout: float = 600.0,
                start_timeout: float = 120.0,
                failure_threshold: Optional[int] = None,
                extra_env: Optional[Dict[str, str]] = None,
                verbose: int = 1) -> List[Any]:
    """Run ``fn`` elastically on Spark executors (reference
    ``horovod.spark.run_elastic``); returns the final world's per-rank
    results.  ``fn`` must call ``hvd.init()`` (elastic rendezvous
    assigns ranks) and should use the ``hvd.elastic`` state pattern to
    survive resizes."""
    import pyspark
    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create a "
                           "SparkSession before run_elastic")
    num_proc = num_proc or sc.defaultParallelism
    min_np = min_np or num_proc
    max_np = max_np or num_proc

    registry = _AgentRegistry()
    payload = util.dumps_base64((fn, tuple(args), kwargs or {}))
    # Workers run on foreign executors: ship only the overlay (the
    # agent merges it over ITS OWN environment) and resolve the
    # interpreter agent-side ("__PYTHON__" → the executor's python).
    env = dict(extra_env or {})
    env["HVD_TPU_RUN_PAYLOAD"] = payload
    discovery = AgentDiscovery(registry)
    driver = SparkElasticDriver(
        ["__PYTHON__", "-c", _WORKER_STUB], discovery,
        min_np, max_np, env=env, elastic_timeout=elastic_timeout,
        start_timeout=start_timeout,
        failure_threshold=failure_threshold, registry=registry)
    secret = driver._secret  # one shared HMAC key for every channel
    discovery._secret = secret

    # MessageServer binds in its constructor, so the port is known
    # before driver.run() starts serving; agents retry until it does.
    driver_addr = (util.routable_ip(), driver._server.port)
    if verbose:
        print("horovod_tpu.spark.run_elastic: agents=%d np=[%d,%d] "
              "driver at %s:%d" % (max_np, min_np, max_np,
                                   driver_addr[0], driver_addr[1]))

    # Spark schedules the agents wherever it likes; they call home.
    agent_rdd = sc.parallelize(range(max_np), max_np)
    mapper = _agent_mapper(driver_addr, secret, extra_env or {})
    agent_job = threading.Thread(
        target=lambda: agent_rdd.mapPartitions(mapper).collect(),
        daemon=True)
    agent_job.start()

    try:
        rc = driver.run()
    finally:
        driver.shutdown_agents()
        agent_job.join(timeout=30)
    if rc != 0:
        raise RuntimeError("run_elastic failed (rc=%d)" % rc)

    # Final world's results from the KV (reset happens per epoch, so
    # only the last epoch's PUTs survive).
    # run() already stopped the HTTP server; the in-memory store
    # outlives it.
    store = driver._kv._httpd.store
    found: Dict[int, Tuple[int, Any]] = {}
    for key, value in list(store.items()):
        parts = key.strip("/").split("/")
        if len(parts) == 2 and parts[0] == "result":
            found[int(parts[1])] = util.loads_base64(
                value if isinstance(value, str) else value.decode())
    if 0 not in found:
        raise RuntimeError("elastic run finished without a rank-0 "
                           "result")
    size = found[0][0]
    results = []
    for rank in range(size):
        if rank not in found or found[rank][0] != size:
            raise RuntimeError("missing result for rank %d" % rank)
        results.append(found[rank][1])
    return results
