"""Spark Keras estimator.

Reference parity: ``horovod/spark/keras/__init__.py``
(``KerasEstimator`` / ``KerasModel``): ``est.fit(df)`` materializes the
DataFrame into the store, trains the Keras model data-parallel — one
world rank per task, gradients averaged through the framework's
``DistributedOptimizer`` — and returns a ``KerasModel`` whose
``transform(df)`` appends predictions.

Works through any ``Backend``: ``SparkBackend`` (barrier tasks) or
``LocalBackend`` (the launcher's local multi-process world — also the
test path, mirroring the reference's local-mode-Spark tests).
"""

from __future__ import annotations

import os
import uuid
from typing import Optional

import numpy as np

from ..common.backend import (LocalBackend, SparkBackend,
                              has_active_spark)
from ..common.params import EstimatorParams
from ..common.serialization import (deserialize_keras_model,
                                    serialize_keras_model)
from ..common.util import (check_validation, materialize_dataframe,
                           read_parquet_shard)

__all__ = ["KerasEstimator", "KerasModel"]


def _keras_train_fn(payload):
    """Per-rank training body (top-level: must be picklable)."""
    import horovod_tpu.keras as hvd
    hvd.init()
    try:
        import keras
        model = deserialize_keras_model(
            payload["model"], custom_objects=payload["custom_objects"])
        optimizer = (keras.optimizers.get(payload["optimizer"])
                     if payload["optimizer"] is not None
                     else model.optimizer)
        if optimizer is None:
            raise ValueError("model is not compiled and no optimizer "
                             "was given to KerasEstimator")
        loss = payload["loss"] if payload["loss"] is not None \
            else model.loss
        dist = hvd.DistributedOptimizer(optimizer)
        model.compile(optimizer=dist, loss=loss,
                      metrics=payload["metrics"])

        x, y = read_parquet_shard(
            payload["train_path"], hvd.rank(), hvd.size(),
            payload["feature_cols"], payload["label_cols"])
        val_frac = payload["validation"]
        fit_kwargs = dict(batch_size=payload["batch_size"],
                          epochs=payload["epochs"],
                          verbose=payload["verbose"]
                          if hvd.rank() == 0 else 0,
                          shuffle=payload["shuffle"])
        if val_frac:
            fit_kwargs["validation_split"] = val_frac
        callbacks = [
            hvd.callbacks.BroadcastGlobalVariablesCallback(0),
            hvd.callbacks.MetricAverageCallback(),
        ] + list(payload["callbacks"])
        history = model.fit(x, y, callbacks=callbacks, **fit_kwargs)
        out = {"history": {k: [float(v) for v in vs] for k, vs in
                           history.history.items()},
               "model": None}
        if hvd.rank() == 0:
            # the wrapped optimizer class is process-local (built by
            # subclassing at runtime) — swap the plain class back in,
            # carrying slot state, so the artifact deserializes anywhere
            base_cls = type(dist).__mro__[1]
            plain = base_cls.from_config(dist.get_config())
            if getattr(dist, "built", False):
                plain.build(model.trainable_variables)
                for src, dst in zip(dist.variables, plain.variables):
                    dst.assign(src)
            # keras serializes the compile-time config, so a recompile
            # (not attribute swap) is what changes the artifact
            model.compile(optimizer=plain, loss=loss,
                          metrics=payload["metrics"])
            out["model"] = serialize_keras_model(model)
        return out
    finally:
        hvd.shutdown()


class KerasEstimator(EstimatorParams):
    """Trains a Keras model over a DataFrame (reference
    ``KerasEstimator``).  Params via keywords or reference-style
    setters (``setEpochs`` …)."""

    def fit(self, df=None) -> "KerasModel":
        self._check_params()
        check_validation(self.validation)
        backend = self.backend or (
            SparkBackend(self.num_proc) if has_active_spark()
            else LocalBackend(self.num_proc or 1))
        run_id = self.run_id or ("keras_" + uuid.uuid4().hex[:8])
        train_path = self.store.get_train_data_path()
        if df is not None:
            materialize_dataframe(df, train_path, self.store)
        payload = {
            "model": serialize_keras_model(self.model),
            "optimizer": self.optimizer,
            "loss": self.loss,
            "metrics": list(self.metrics),
            "custom_objects": self.custom_objects,
            "train_path": train_path,
            "feature_cols": list(self.feature_cols),
            "label_cols": list(self.label_cols),
            "validation": check_validation(self.validation),
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "verbose": self.verbose,
            "shuffle": self.shuffle,
            "callbacks": list(self.callbacks),
        }
        results = backend.run(_keras_train_fn, args=(payload,))
        rank0 = results[0]
        model = deserialize_keras_model(rank0["model"],
                                        custom_objects=self.custom_objects)
        # publish the final model into the store's run dir
        ckpt = self.store.get_checkpoint_path(run_id)
        self.store.write(ckpt, rank0["model"])
        return KerasModel(model=model,
                          feature_cols=list(self.feature_cols),
                          label_cols=list(self.label_cols),
                          history=rank0["history"], run_id=run_id,
                          custom_objects=self.custom_objects)


class KerasModel:
    """Fitted transformer (reference ``KerasModel``): ``transform(df)``
    appends prediction columns; ``predict`` serves numpy/pandas."""

    def __init__(self, model=None, feature_cols=None, label_cols=None,
                 history=None, run_id: Optional[str] = None,
                 custom_objects=None):
        self.model = model
        self.feature_cols = feature_cols or ["features"]
        self.label_cols = label_cols or ["label"]
        self.history = history or {}
        self.run_id = run_id
        self.custom_objects = custom_objects

    def getModel(self):
        return self.model

    def _features_of(self, pdf) -> np.ndarray:
        cols = [np.asarray(pdf[c].tolist(), np.float32)
                for c in self.feature_cols]
        if len(cols) == 1:
            return cols[0]
        return np.stack(cols, axis=-1)

    def predict(self, data) -> np.ndarray:
        if hasattr(data, "columns"):  # pandas
            data = self._features_of(data)
        return np.asarray(self.model.predict(
            np.asarray(data, np.float32), verbose=0))

    def transform(self, df):
        if type(df).__module__.startswith("pyspark."):
            model_bytes = serialize_keras_model(self.model)
            feature_cols = self.feature_cols
            label_cols = self.label_cols
            custom_objects = self.custom_objects

            def map_fn(iterator):
                m = deserialize_keras_model(model_bytes,
                                            custom_objects)
                for pdf in iterator:
                    cols = [np.asarray(pdf[c].tolist(), np.float32)
                            for c in feature_cols]
                    x = cols[0] if len(cols) == 1 \
                        else np.stack(cols, axis=-1)
                    pred = np.asarray(m.predict(x, verbose=0))
                    for i, lc in enumerate(label_cols):
                        p = pred if pred.ndim == 1 else pred[..., i]
                        pdf[lc + "__output"] = list(p)
                    yield pdf
            import pyspark.sql.types as T  # noqa: F401
            schema = df.schema
            for lc in self.label_cols:
                import pyspark.sql.types as T
                schema = schema.add(lc + "__output", T.FloatType())
            return df.mapInPandas(map_fn, schema=schema)
        # pandas path
        out = df.copy()
        pred = self.predict(df)
        for i, lc in enumerate(self.label_cols):
            p = pred if pred.ndim == 1 else pred[..., i]
            out[lc + "__output"] = list(np.asarray(p, np.float32))
        return out
