"""Spark Lightning estimator.

Reference parity: ``horovod/spark/lightning/__init__.py``
(``TorchEstimator`` over PyTorch Lightning modules).  The estimator is
duck-typed: it drives anything exposing the LightningModule training
contract — ``configure_optimizers()`` supplies the optimizer(s) and
``training_step(batch, batch_idx)`` the loss — so it works both with
real ``lightning``/``pytorch_lightning`` modules and, in environments
without the package, with any ``torch.nn.Module`` implementing those
two methods (the pattern this repo uses for optional frameworks).

Everything except optimizer sourcing and the per-batch step is shared
with the plain torch estimator (``..torch.run_training``).
"""

from __future__ import annotations

from ..common.serialization import deserialize_torch_model
from ..torch import TorchEstimator as _TorchEstimator
from ..torch import TorchModel, run_training

__all__ = ["TorchEstimator", "LightningModel"]

_CONTRACT_ERR = (
    "lightning TorchEstimator needs a module with "
    "training_step(batch, batch_idx) and configure_optimizers(); "
    "got %r — use horovod_tpu.spark.torch.TorchEstimator for plain "
    "modules")


def _has_contract(module) -> bool:
    return (callable(getattr(module, "training_step", None))
            and callable(getattr(module, "configure_optimizers", None)))


def _first_optimizer(configured):
    """``configure_optimizers`` may return one optimizer, a list, the
    ``(optimizers, schedulers)`` tuple, a ``{"optimizer": ...}`` dict,
    or a list of such dicts (all documented lightning contracts); DP
    training drives the first optimizer (the reference lightning
    estimator's single-optimizer path does the same)."""
    if isinstance(configured, tuple) and len(configured) == 2 and \
            isinstance(configured[0], (list, tuple)):
        opts = list(configured[0])
    elif isinstance(configured, (list, tuple)):
        opts = list(configured)
    else:
        opts = [configured]
    if not opts:
        raise ValueError("configure_optimizers() returned no optimizer")
    first = opts[0]
    if isinstance(first, dict):
        try:
            return first["optimizer"]
        except KeyError:
            raise ValueError(
                "configure_optimizers() returned a dict without an "
                "'optimizer' entry: %r" % sorted(first)) from None
    return first


def _step_loss(result):
    """``training_step`` may return the loss tensor or a dict with a
    'loss' entry (both are lightning contracts)."""
    if isinstance(result, dict):
        result = result["loss"]
    return result


def _lightning_train_fn(payload):
    """Per-rank training body (top-level: must be picklable)."""
    import horovod_tpu.torch as hvd
    hvd.init()
    try:
        module = deserialize_torch_model(payload["model"])
        if not _has_contract(module):
            # Defense for deserialization drift; fit() checks first.
            raise TypeError(_CONTRACT_ERR % type(module).__name__)

        def make_optimizer(m):
            return _first_optimizer(m.configure_optimizers())

        def step_fn(m, xb, yb, batch_idx):
            return _step_loss(m.training_step((xb, yb), batch_idx))

        return run_training(payload, module, make_optimizer, step_fn,
                            "LightningEstimator")
    finally:
        hvd.shutdown()


class LightningModel(TorchModel):
    """Fitted transformer (reference lightning ``TorchModel``);
    inherits ``transform``/``predict``/``getModel``."""


class TorchEstimator(_TorchEstimator):
    """Trains a LightningModule-style module over a DataFrame
    (reference ``horovod.spark.lightning.TorchEstimator``): the
    module's ``configure_optimizers`` supplies the optimizer and
    ``training_step`` the loss; gradients ride the framework's
    ``DistributedOptimizer`` hooks."""

    _run_prefix = "lightning_"

    @staticmethod
    def _train_fn(payload):
        return _lightning_train_fn(payload)

    def _model_cls(self):
        return LightningModel

    def _extra_payload(self):
        return {}

    def fit(self, df=None) -> "LightningModel":
        if not _has_contract(self.model):
            # Fail on the driver, before any workers launch
            # (super().fit validates the common params).
            raise TypeError(_CONTRACT_ERR % type(self.model).__name__)
        return super().fit(df)
