"""Spark Lightning estimator.

Reference parity: ``horovod/spark/lightning/__init__.py``
(``TorchEstimator`` over PyTorch Lightning modules).  Lightning is not
installed in this environment; the estimator accepts a
``LightningModule``-style object (anything exposing
``training_step``/``configure_optimizers``) and falls back to an
informative ImportError when the lightning runtime itself is required.
"""

from __future__ import annotations

__all__ = ["TorchEstimator"]

try:  # optional dependency
    import lightning  # type: ignore # noqa: F401
    _HAVE_LIGHTNING = True
except ImportError:
    try:
        import pytorch_lightning  # type: ignore # noqa: F401
        _HAVE_LIGHTNING = True
    except ImportError:
        _HAVE_LIGHTNING = False


if _HAVE_LIGHTNING:  # pragma: no cover - lightning not in this env
    from ..torch import TorchEstimator as _Base

    class TorchEstimator(_Base):
        """Lightning-module estimator: the module's
        ``configure_optimizers`` supplies the optimizer and
        ``training_step`` the loss (reference
        ``horovod/spark/lightning``)."""

else:

    class TorchEstimator:  # type: ignore[no-redef]
        def __init__(self, *args, **kwargs):
            raise ImportError(
                "horovod_tpu.spark.lightning requires lightning / "
                "pytorch_lightning, which is not installed; use "
                "horovod_tpu.spark.torch.TorchEstimator instead.")
