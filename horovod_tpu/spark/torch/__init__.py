"""Spark Torch estimator.

Reference parity: ``horovod/spark/torch/__init__.py``
(``TorchEstimator`` / ``TorchModel``): ``est.fit(df)`` trains a torch
module data-parallel across backend ranks with the framework's
``DistributedOptimizer`` (per-parameter async allreduce hooks) and
returns a ``TorchModel`` transformer.
"""

from __future__ import annotations

import uuid
from typing import Callable, Optional

import numpy as np

from ..common.backend import (LocalBackend, SparkBackend,
                              has_active_spark)
from ..common.params import EstimatorParams
from ..common.serialization import (deserialize_torch_model,
                                    serialize_torch_model)
from ..common.util import (check_validation, materialize_dataframe,
                           read_parquet_shard)
__all__ = ["TorchEstimator", "TorchModel"]


def run_training(payload, model, make_optimizer, step_fn, loss_prefix):
    """The shared per-rank DP training loop: DistributedOptimizer
    hooks, parameter/optimizer broadcast, parquet shard read, epoch
    loop, cross-rank loss averaging, rank-0 model serialization.
    ``make_optimizer(model)`` sources the optimizer; ``step_fn(model,
    xb, yb, batch_idx)`` returns the batch loss.  Used by the torch
    and lightning estimators (only those two hooks differ)."""
    import torch
    import horovod_tpu.torch as hvd
    optimizer = hvd.DistributedOptimizer(
        make_optimizer(model), named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    x, y = read_parquet_shard(
        payload["train_path"], hvd.rank(), hvd.size(),
        payload["feature_cols"], payload["label_cols"])
    x = torch.from_numpy(np.ascontiguousarray(x))
    y = torch.from_numpy(np.ascontiguousarray(y))
    bs = payload["batch_size"]
    history = []
    for epoch in range(payload["epochs"]):
        perm = (torch.randperm(len(x)) if payload["shuffle"]
                else torch.arange(len(x)))
        epoch_loss, batches = 0.0, 0
        for batch_idx, i in enumerate(range(0, len(x), bs)):
            idx = perm[i:i + bs]
            optimizer.zero_grad()
            loss = step_fn(model, x[idx], y[idx], batch_idx)
            loss.backward()
            optimizer.step()
            epoch_loss += float(loss.detach())
            batches += 1
        avg = epoch_loss / max(1, batches)
        avg = float(hvd.allreduce(
            torch.tensor(avg), op=hvd.Average,
            name="%s.epoch_loss.%d" % (loss_prefix, epoch)))
        history.append({"epoch": epoch, "loss": avg})
        if payload["verbose"] and hvd.rank() == 0:
            print("epoch %d loss %.6f" % (epoch, avg))
    out = {"history": history, "model": None}
    if hvd.rank() == 0:
        out["model"] = serialize_torch_model(model)
    return out


def _torch_train_fn(payload):
    """Per-rank training body (top-level: must be picklable)."""
    import torch
    import horovod_tpu.torch as hvd
    hvd.init()
    try:
        model = deserialize_torch_model(payload["model"])
        loss_fn = payload["loss"] or torch.nn.functional.mse_loss
        opt_factory = payload["optimizer"]

        def make_optimizer(m):
            return (opt_factory(m.parameters()) if opt_factory
                    else torch.optim.SGD(m.parameters(), lr=0.01))

        def step_fn(m, xb, yb, batch_idx):
            return loss_fn(m(xb).squeeze(-1), yb.squeeze(-1))

        return run_training(payload, model, make_optimizer, step_fn,
                            "TorchEstimator")
    finally:
        hvd.shutdown()


class TorchEstimator(EstimatorParams):
    """Trains a torch module over a DataFrame (reference
    ``TorchEstimator``).  ``optimizer`` is a factory
    ``params -> torch.optim.Optimizer`` (picklable, e.g. a top-level
    function or ``functools.partial``); ``loss`` a picklable callable.
    """

    # Subclass hooks (the lightning estimator overrides these).
    _run_prefix = "torch_"

    @staticmethod
    def _train_fn(payload):
        return _torch_train_fn(payload)

    def _model_cls(self):
        return TorchModel

    def _extra_payload(self):
        return {"optimizer": self.optimizer, "loss": self.loss}

    def fit(self, df=None) -> "TorchModel":
        self._check_params()
        check_validation(self.validation)
        backend = self.backend or (
            SparkBackend(self.num_proc) if has_active_spark()
            else LocalBackend(self.num_proc or 1))
        run_id = self.run_id or (self._run_prefix + uuid.uuid4().hex[:8])
        train_path = self.store.get_train_data_path()
        if df is not None:
            materialize_dataframe(df, train_path, self.store)
        payload = {
            "model": serialize_torch_model(self.model),
            "train_path": train_path,
            "feature_cols": list(self.feature_cols),
            "label_cols": list(self.label_cols),
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "verbose": self.verbose,
            "shuffle": self.shuffle,
        }
        payload.update(self._extra_payload())
        results = backend.run(type(self)._train_fn, args=(payload,))
        rank0 = results[0]
        model = deserialize_torch_model(rank0["model"])
        ckpt = self.store.get_checkpoint_path(run_id)
        self.store.write(ckpt, rank0["model"])
        return self._model_cls()(
            model=model, feature_cols=list(self.feature_cols),
            label_cols=list(self.label_cols),
            history=rank0["history"], run_id=run_id)


class TorchModel:
    """Fitted transformer (reference ``TorchModel``)."""

    def __init__(self, model=None, feature_cols=None, label_cols=None,
                 history=None, run_id: Optional[str] = None):
        self.model = model
        self.feature_cols = feature_cols or ["features"]
        self.label_cols = label_cols or ["label"]
        self.history = history or []
        self.run_id = run_id

    def getModel(self):
        return self.model

    def predict(self, data) -> np.ndarray:
        import torch
        if hasattr(data, "columns"):
            cols = [np.asarray(data[c].tolist(), np.float32)
                    for c in self.feature_cols]
            data = cols[0] if len(cols) == 1 \
                else np.stack(cols, axis=-1)
        with torch.no_grad():
            out = self.model(torch.from_numpy(
                np.asarray(data, np.float32)))
        return out.numpy()

    def transform(self, df):
        if type(df).__module__.startswith("pyspark."):
            model_bytes = serialize_torch_model(self.model)
            feature_cols = self.feature_cols
            label_cols = self.label_cols

            def map_fn(iterator):
                import torch
                m = deserialize_torch_model(model_bytes)
                for pdf in iterator:
                    cols = [np.asarray(pdf[c].tolist(), np.float32)
                            for c in feature_cols]
                    x = cols[0] if len(cols) == 1 \
                        else np.stack(cols, axis=-1)
                    with torch.no_grad():
                        pred = m(torch.from_numpy(x)).numpy()
                    for i, lc in enumerate(label_cols):
                        p = pred if pred.ndim == 1 else pred[..., i]
                        pdf[lc + "__output"] = list(p)
                    yield pdf
            import pyspark.sql.types as T
            schema = df.schema
            for lc in self.label_cols:
                schema = schema.add(lc + "__output", T.FloatType())
            return df.mapInPandas(map_fn, schema=schema)
        out = df.copy()
        pred = self.predict(df)
        for i, lc in enumerate(self.label_cols):
            p = pred if pred.ndim == 1 else pred[..., i]
            out[lc + "__output"] = list(np.asarray(p, np.float32))
        return out
