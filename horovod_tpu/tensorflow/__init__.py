"""TensorFlow adapter: ``import horovod_tpu.tensorflow as hvd``.

Reference parity: ``horovod/tensorflow/__init__.py`` — the same surface
(init/rank/size, the eight collectives with registered gradients,
``DistributedGradientTape``, ``DistributedOptimizer``,
``broadcast_variables`` / ``broadcast_object``, ``Compression``, local
gradient aggregation via ``backward_passes_per_step``, elastic
``TensorFlowKerasState``) routed through this framework's native core
instead of the reference's custom TF C++ kernels
(``horovod/tensorflow/mpi_ops.cc``).

As with the torch adapter, TF tensors here are host tensors — the TPU
compute path is the JAX adapter; this adapter gives TF training scripts
the reference's CPU (MPI/Gloo-path) semantics over the native TCP core.
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

from ..common.basics import (shutdown, is_initialized, rank, size,
                             local_rank, local_size, cross_rank,
                             cross_size, is_homogeneous, topology,
                             start_timeline, stop_timeline, xla_built,
                             tcp_built, gloo_built, mpi_built,
                             nccl_built, ccl_built, ddl_built,
                             cuda_built, rocm_built, mpi_enabled,
                             mpi_threads_supported)
from ..common.basics import init as _base_init
from ..common.process_sets import (ProcessSet, global_process_set,
                                   add_process_set, remove_process_set)
from ..ops.engine import HorovodInternalError
from ..ops.xla_ops import ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM
from .compression import Compression
from .functions import (allgather_object, broadcast_object,
                        broadcast_variables)
from .gradient_aggregation import LocalGradientAggregationHelper
from .mpi_ops import (allgather, allgather_async, allreduce,
                      allreduce_async, alltoall, barrier, broadcast,
                      broadcast_async, grouped_allreduce, join,
                      local_rank_op, local_size_op, poll,
                      process_set_included_op, rank_op, reducescatter,
                      size_op, synchronize)

Sum = SUM
Average = AVERAGE
Min = MIN
Max = MAX
Product = PRODUCT
Adasum = ADASUM


def init(*args, **kwargs):
    """``hvd.init()`` — defaults to the multi-process (tcp) controller,
    matching the torch adapter: per-process tensors need a real world
    even when unlaunched (size-1)."""
    kwargs.setdefault("controller", "tcp")
    return _base_init(*args, **kwargs)


def _densify(grad):
    if isinstance(grad, tf.IndexedSlices):
        return tf.convert_to_tensor(grad)
    return grad


def _make_allreduce_grads_fn(name_prefix: str, op, compression,
                             process_set):
    def allreduce_grads(grads):
        grads = [None if g is None else _densify(g) for g in grads]
        if any(g is not None and tf.is_symbolic_tensor(g)
               for g in grads):
            # traced inside tf.function: stage per-tensor through the
            # differentiable py_function path
            out = []
            for i, g in enumerate(grads):
                if g is None:
                    out.append(None)
                    continue
                c, ctx = compression.compress(g)
                r = allreduce(c, op=op, process_set=process_set,
                              name="%s.grad_%d" % (name_prefix, i))
                out.append(compression.decompress(r, ctx))
            return out
        # eager: submit every allreduce before waiting on any, so
        # negotiation/transfer of all gradients overlap (the reference's
        # async enqueue + single synchronize pattern)
        pending = []
        for i, g in enumerate(grads):
            if g is None:
                pending.append((None, None))
                continue
            c, ctx = compression.compress(g)
            h = allreduce_async(c, op=op, process_set=process_set,
                                name="%s.grad_%d" % (name_prefix, i))
            pending.append((h, ctx))
        return [None if h is None else compression.decompress(h.wait(),
                                                              ctx)
                for h, ctx in pending]
    return allreduce_grads


class _DistributedGradientTape:
    """Wraps a ``tf.GradientTape`` so ``gradient()`` returns globally
    reduced gradients (reference ``DistributedGradientTape``)."""

    def __init__(self, tape: tf.GradientTape, device_dense="",
                 device_sparse="", compression=Compression.none,
                 sparse_as_dense=True, op=AVERAGE, process_set=None):
        # No backward_passes_per_step here: the tape API has no way to
        # tell the caller to skip an optimizer update on non-boundary
        # passes, so local aggregation lives on DistributedOptimizer
        # only — same split as the reference.
        self._tape = tape
        self._allreduce_grads = _make_allreduce_grads_fn(
            "DistributedGradientTape", op, compression, process_set)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        single = not isinstance(grads, (list, tuple))
        glist = [grads] if single else list(grads)
        glist = self._allreduce_grads(glist)
        return glist[0] if single else glist


def DistributedGradientTape(gradtape: tf.GradientTape, *args, **kwargs):
    return _DistributedGradientTape(gradtape, *args, **kwargs)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         compression=Compression.none,
                         sparse_as_dense: bool = True, op=AVERAGE,
                         process_set=None,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = True):
    """Wrap a Keras optimizer so every ``apply``/``apply_gradients``
    first averages gradients across ranks (reference
    ``hvd.DistributedOptimizer`` for tf.keras).

    Built by subclassing the optimizer's own class and rebuilding it
    from config — the reference's construction — so the result is a
    genuine Keras optimizer usable in ``model.compile``.
    """
    allreduce_grads = _make_allreduce_grads_fn(
        name or "DistributedOptimizer", op, compression, process_set)
    agg = LocalGradientAggregationHelper(
        backward_passes_per_step, allreduce_grads,
        average_aggregated_gradients) \
        if backward_passes_per_step > 1 else None

    cls = optimizer.__class__

    class _DistributedKerasOptimizer(cls):
        _hvd_distributed = True

        def apply(self, grads, trainable_variables=None, **kw):
            grads = [_densify(g) for g in grads]
            if agg is not None:
                should, grads = agg.apply(grads)
                if not should:
                    return
            else:
                grads = allreduce_grads(grads)
            return super().apply(grads, trainable_variables, **kw)

    _DistributedKerasOptimizer.__name__ = "Distributed" + cls.__name__
    return _DistributedKerasOptimizer.from_config(optimizer.get_config())


class elastic:
    """Elastic namespace: ``hvd.elastic.TensorFlowKerasState`` etc.
    (reference ``horovod/tensorflow/elastic.py``)."""

    from ..elastic import run  # noqa: F401  (retry decorator)
    from ..elastic.state import ObjectState, State  # noqa: F401
    from ..elastic.worker import HostsUpdatedInterrupt  # noqa: F401

    class TensorFlowKerasState(ObjectState):
        """Keras model + optimizer elastic state: weights snapshotted on
        commit, broadcast from rank 0 on sync (reference
        ``TensorFlowKerasState`` in horovod/tensorflow/elastic.py)."""

        def __init__(self, model, optimizer=None, **kwargs):
            self._model = model
            self._optimizer = optimizer
            super().__init__(**kwargs)

        def _weights(self):
            w = {"model": [v.numpy() for v in self._model.weights]}
            if self._optimizer is not None:
                w["optimizer"] = [v.numpy()
                                  for v in self._optimizer.variables]
            return w

        def _set_weights(self, w):
            for v, val in zip(self._model.weights, w["model"]):
                v.assign(val)
            if self._optimizer is not None and "optimizer" in w:
                for v, val in zip(self._optimizer.variables,
                                  w["optimizer"]):
                    v.assign(val)

        def save(self):
            super().save()
            self._saved_weights = self._weights()

        def restore(self):
            super().restore()
            self._set_weights(self._saved_weights)

        def sync(self):
            super().sync()
            from ..common import basics
            if basics.is_initialized() and basics.size() > 1:
                synced = broadcast_object(
                    self._weights(), root_rank=0,
                    name="elastic.TensorFlowKerasState")
                self._set_weights(synced)
            self.save()
