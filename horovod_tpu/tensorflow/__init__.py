"""TensorFlow adapter: ``import horovod_tpu.tensorflow as hvd``.

Reference parity: ``horovod/tensorflow/__init__.py`` — the same surface
(init/rank/size, the eight collectives with registered gradients,
``DistributedGradientTape``, ``DistributedOptimizer``,
``broadcast_variables`` / ``broadcast_object``, ``Compression``, local
gradient aggregation via ``backward_passes_per_step``, elastic
``TensorFlowKerasState``) routed through this framework's native core
instead of the reference's custom TF C++ kernels
(``horovod/tensorflow/mpi_ops.cc``).

As with the torch adapter, TF tensors here are host tensors — the TPU
compute path is the JAX adapter; this adapter gives TF training scripts
the reference's CPU (MPI/Gloo-path) semantics over the native TCP core.
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

from ..common.basics import (shutdown, is_initialized, rank, size,
                             local_rank, local_size, cross_rank,
                             cross_size, is_homogeneous, topology,
                             start_timeline, stop_timeline, xla_built,
                             tcp_built, gloo_built, mpi_built,
                             nccl_built, ccl_built, ddl_built,
                             cuda_built, rocm_built, mpi_enabled,
                             mpi_threads_supported)
from ..common.basics import init as _base_init
from ..common.process_sets import (ProcessSet, global_process_set,
                                   add_process_set, remove_process_set,
                                   process_set_by_id, process_set_ids)
from ..ops.engine import HorovodInternalError
from ..ops.xla_ops import ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM
from .compression import Compression
from .sync_batch_norm import SyncBatchNormalization
from .functions import (allgather_object, broadcast_object,
                        broadcast_variables)
from .gradient_aggregation import LocalGradientAggregationHelper
from .mpi_ops import (allgather, allgather_async, allreduce,
                      allreduce_async, alltoall, barrier, broadcast,
                      broadcast_async, grouped_allgather,
                      grouped_allgather_async, grouped_allreduce,
                      grouped_allreduce_async, grouped_reducescatter,
                      grouped_reducescatter_async, join,
                      local_rank_op, local_size_op, poll,
                      process_set_included_op, rank_op, reducescatter,
                      size_op, synchronize)

Sum = SUM
Average = AVERAGE
Min = MIN
Max = MAX
Product = PRODUCT
Adasum = ADASUM


def init(*args, **kwargs):
    """``hvd.init()`` — defaults to the multi-process (tcp) controller,
    matching the torch adapter: per-process tensors need a real world
    even when unlaunched (size-1)."""
    kwargs.setdefault("controller", "tcp")
    return _base_init(*args, **kwargs)


def _densify(grad):
    if isinstance(grad, tf.IndexedSlices):
        return tf.convert_to_tensor(grad)
    return grad


def _make_allreduce_grads_fn(name_prefix: str, op, compression,
                             process_set, num_groups: int = 0,
                             groups=None):
    """Build the per-gradient reduce function.

    ``num_groups``/``groups`` mirror the reference TF surface: an int
    buckets the gradients into that many atomic ``grouped_allreduce``
    calls; a list of variable lists groups explicitly (matched against
    the ``variables`` the caller passes), with leftovers reduced
    individually.
    """
    if isinstance(groups, int):
        num_groups, groups = groups, None
    elif groups is not None and not isinstance(groups, (list, tuple)):
        raise ValueError("groups must be an int or a list of variable "
                         "lists")
    explicit_gid = None
    if groups is not None:
        explicit_gid = {}
        for gid, members in enumerate(groups):
            for v in members:
                key = v.ref() if hasattr(v, "ref") else id(v)
                if key in explicit_gid:
                    raise ValueError(
                        "variable appears in more than one group")
                explicit_gid[key] = gid

    def allreduce_grads(grads, variables=None):
        grads = [None if g is None else _densify(g) for g in grads]
        live = [i for i, g in enumerate(grads) if g is not None]
        buckets = {}
        grouped = set()
        for pos, i in enumerate(live):
            if explicit_gid is not None:
                gid = None
                if variables is not None and i < len(variables) and \
                        variables[i] is not None:
                    v = variables[i]
                    gid = explicit_gid.get(v.ref() if hasattr(v, "ref")
                                           else id(v))
            elif num_groups > 0:
                n = min(num_groups, len(live)) or 1
                gid = pos * n // len(live)
            else:
                gid = None
            if gid is not None:
                buckets.setdefault(gid, []).append(i)
                grouped.add(i)
        if explicit_gid is not None and live and not grouped:
            # Mirror the aggregation-boundary ValueError: a requested
            # explicit grouping that matches nothing must not silently
            # degrade to per-tensor reduces.
            raise ValueError(
                "none of the explicit groups' variables matched this "
                "call's sources/trainable_variables; pass the same "
                "variable objects, or use an integer num_groups")
        out = [None] * len(grads)
        singles = [i for i in live if i not in grouped]
        symbolic = any(g is not None and tf.is_symbolic_tensor(g)
                       for g in grads)

        def compress_bucket(idxs):
            wires, ctxs = [], []
            for i in idxs:
                c, ctx = compression.compress(grads[i])
                wires.append(c)
                ctxs.append(ctx)
            return wires, ctxs

        if symbolic:
            # traced inside tf.function: stage buckets and singles
            # through the py_function paths
            for gid in sorted(buckets):
                idxs = buckets[gid]
                wires, ctxs = compress_bucket(idxs)
                rs = grouped_allreduce(
                    wires, op=op, process_set=process_set,
                    name="%s.group_%d" % (name_prefix, gid))
                for i, r, ctx in zip(idxs, rs, ctxs):
                    out[i] = compression.decompress(r, ctx)
            for i in singles:
                c, ctx = compression.compress(grads[i])
                r = allreduce(c, op=op, process_set=process_set,
                              name="%s.grad_%d" % (name_prefix, i))
                out[i] = compression.decompress(r, ctx)
            return out
        # eager: submit every bucket and single allreduce before waiting
        # on any, so negotiation/transfer of all gradients overlap (the
        # reference's async enqueue + single synchronize pattern)
        pending = []
        for gid in sorted(buckets):
            idxs = buckets[gid]
            wires, ctxs = compress_bucket(idxs)
            hs = grouped_allreduce_async(
                wires, op=op, process_set=process_set,
                name="%s.group_%d" % (name_prefix, gid))
            pending.extend(zip(idxs, hs, ctxs))
        for i in singles:
            c, ctx = compression.compress(grads[i])
            h = allreduce_async(c, op=op, process_set=process_set,
                                name="%s.grad_%d" % (name_prefix, i))
            pending.append((i, h, ctx))
        for i, h, ctx in pending:
            out[i] = compression.decompress(h.wait(), ctx)
        return out
    return allreduce_grads


class _DistributedGradientTape:
    """Wraps a ``tf.GradientTape`` so ``gradient()`` returns globally
    reduced gradients (reference ``DistributedGradientTape``)."""

    def __init__(self, tape: tf.GradientTape, device_dense="",
                 device_sparse="", compression=Compression.none,
                 sparse_as_dense=True, op=AVERAGE, process_set=None,
                 num_groups: int = 0, groups=None):
        # No backward_passes_per_step here: the tape API has no way to
        # tell the caller to skip an optimizer update on non-boundary
        # passes, so local aggregation lives on DistributedOptimizer
        # only — same split as the reference.
        self._tape = tape
        self._allreduce_grads = _make_allreduce_grads_fn(
            "DistributedGradientTape", op, compression, process_set,
            num_groups, groups)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        single = not isinstance(grads, (list, tuple))
        glist = [grads] if single else list(grads)
        vlist = [sources] if single else (
            list(sources) if isinstance(sources, (list, tuple)) else None)
        glist = self._allreduce_grads(glist, vlist)
        return glist[0] if single else glist


def DistributedGradientTape(gradtape: tf.GradientTape, *args, **kwargs):
    return _DistributedGradientTape(gradtape, *args, **kwargs)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         compression=Compression.none,
                         sparse_as_dense: bool = True, op=AVERAGE,
                         process_set=None,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = True,
                         num_groups: int = 0, groups=None):
    """Wrap a Keras optimizer so every ``apply``/``apply_gradients``
    first averages gradients across ranks (reference
    ``hvd.DistributedOptimizer`` for tf.keras).

    Built by subclassing the optimizer's own class and rebuilding it
    from config — the reference's construction — so the result is a
    genuine Keras optimizer usable in ``model.compile``.
    """
    allreduce_grads = _make_allreduce_grads_fn(
        name or "DistributedOptimizer", op, compression, process_set,
        num_groups, groups)
    if isinstance(groups, (list, tuple)) and backward_passes_per_step > 1:
        # The aggregation helper reduces without variable identities, so
        # explicit variable groups cannot be matched on its boundary.
        raise ValueError(
            "explicit variable groups cannot be combined with "
            "backward_passes_per_step > 1; use an integer num_groups")
    agg = LocalGradientAggregationHelper(
        backward_passes_per_step, allreduce_grads,
        average_aggregated_gradients) \
        if backward_passes_per_step > 1 else None

    cls = optimizer.__class__

    class _DistributedKerasOptimizer(cls):
        _hvd_distributed = True

        def apply(self, grads, trainable_variables=None, **kw):
            grads = [_densify(g) for g in grads]
            if agg is not None:
                should, grads = agg.apply(grads)
                if not should:
                    return
            else:
                # Keras 3 allows apply(grads) after build(); explicit
                # groups then match against the optimizer's own built
                # variable list.
                tv = trainable_variables if trainable_variables \
                    is not None else getattr(
                        self, "_trainable_variables", None)
                grads = allreduce_grads(grads, tv)
            return super().apply(grads, trainable_variables, **kw)

    _DistributedKerasOptimizer.__name__ = "Distributed" + cls.__name__
    return _DistributedKerasOptimizer.from_config(optimizer.get_config())


class elastic:
    """Elastic namespace: ``hvd.elastic.TensorFlowKerasState`` etc.
    (reference ``horovod/tensorflow/elastic.py``)."""

    from ..elastic import run  # noqa: F401  (retry decorator)
    from ..elastic.state import ObjectState, State  # noqa: F401
    from ..elastic.worker import HostsUpdatedInterrupt  # noqa: F401

    class TensorFlowState(ObjectState):
        """Plain tf.Variable elastic state (reference
        ``TensorFlowState``): values snapshotted on commit, broadcast
        from rank 0 on sync."""

        def __init__(self, variables=None, **kwargs):
            self._variables = list(variables or [])
            super().__init__(**kwargs)

        def save(self):
            super().save()
            self._saved_values = [v.numpy() for v in self._variables]

        def restore(self):
            super().restore()
            for v, val in zip(self._variables, self._saved_values):
                v.assign(val)

        def sync(self):
            super().sync()
            from ..common import basics
            if basics.is_initialized() and basics.size() > 1:
                synced = broadcast_object(
                    [v.numpy() for v in self._variables], root_rank=0,
                    name="elastic.TensorFlowState")
                for v, val in zip(self._variables, synced):
                    v.assign(val)

    class TensorFlowKerasState(ObjectState):
        """Keras model + optimizer elastic state: weights snapshotted on
        commit, broadcast from rank 0 on sync (reference
        ``TensorFlowKerasState`` in horovod/tensorflow/elastic.py)."""

        def __init__(self, model, optimizer=None, **kwargs):
            self._model = model
            self._optimizer = optimizer
            super().__init__(**kwargs)

        def _weights(self):
            w = {"model": [v.numpy() for v in self._model.weights]}
            if self._optimizer is not None:
                w["optimizer"] = [v.numpy()
                                  for v in self._optimizer.variables]
            return w

        def _set_weights(self, w):
            for v, val in zip(self._model.weights, w["model"]):
                v.assign(val)
            if self._optimizer is not None and "optimizer" in w:
                for v, val in zip(self._optimizer.variables,
                                  w["optimizer"]):
                    v.assign(val)

        def save(self):
            super().save()
            self._saved_weights = self._weights()

        def restore(self):
            super().restore()
            self._set_weights(self._saved_weights)

        def sync(self):
            super().sync()
            from ..common import basics
            if basics.is_initialized() and basics.size() > 1:
                synced = broadcast_object(
                    self._weights(), root_rank=0,
                    name="elastic.TensorFlowKerasState")
                self._set_weights(synced)
            self.save()
