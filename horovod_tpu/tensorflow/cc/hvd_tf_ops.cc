// TensorFlow custom op + XLA custom-call lowering for hvd allreduce.
//
// Reference parity: horovod/tensorflow/xla_mpi_ops.cc — the piece that
// lets hvd.allreduce live INSIDE tf.function(jit_compile=True): a
// registered XLA kernel lowers the op to a host custom-call whose
// callback enqueues into the native core (negotiation + wire move) and
// blocks until the result lands, exactly like the reference's
// HVDAllreduceOp custom call enqueues to the Horovod background thread.
// The reference only implements allreduce in its XLA path; so do we.
//
// The core is the SAME singleton the Python runtime initialized: this
// library dlopens libhvdtpu_core.so, which the dynamic loader resolves
// to the already-loaded instance.
//
// Scope: CPU JIT (XLA_CPU_JIT). On TPU the compiled path is JAX/XLA
// collectives over ICI (ops/xla_ops.py); a "Host" custom-call target
// does not exist inside a TPU executable, so the XLA_TPU_JIT kernel
// below fails AT TRACE TIME with a clear redirect to the JAX adapter
// instead of letting the custom call reach the TPU compiler and die
// with an opaque linker error (see docs/adapters.md).

#include <dlfcn.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "tensorflow/compiler/tf2xla/xla_op_kernel.h"
#include "tensorflow/compiler/tf2xla/xla_op_registry.h"
#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"
#include "xla/hlo/builder/xla_builder.h"
#include "xla/service/custom_call_target_registry.h"

namespace {

// ---- native core C API (resolved from the already-loaded .so) ----------

typedef int (*enqueue_fn)(const char*, int, const void*, const long long*,
                          int, int, int, int, unsigned int, double, double,
                          const long long*, int);
typedef int (*poll_fn)(int);
typedef int (*copy_fn)(int, void*);
typedef int (*err_fn)(int, char*, int);
typedef void (*release_fn)(int);
typedef int (*init_q_fn)();

struct CoreApi {
  enqueue_fn enqueue = nullptr;
  poll_fn poll = nullptr;
  copy_fn copy_result = nullptr;
  err_fn error_string = nullptr;
  release_fn release = nullptr;
  init_q_fn is_initialized = nullptr;
  bool ok = false;
};

CoreApi& core() {
  static CoreApi api = [] {
    CoreApi a;
    const char* path = std::getenv("HVD_TPU_CORE_LIB");
    void* h = dlopen(path ? path : "libhvdtpu_core.so",
                     RTLD_NOW | RTLD_GLOBAL);
    if (!h) {
      std::fprintf(stderr, "hvd_tf_ops: cannot dlopen core (%s): %s\n",
                   path ? path : "libhvdtpu_core.so", dlerror());
      return a;
    }
    a.enqueue = reinterpret_cast<enqueue_fn>(dlsym(h, "hvd_tcp_enqueue"));
    a.poll = reinterpret_cast<poll_fn>(dlsym(h, "hvd_tcp_poll"));
    a.copy_result = reinterpret_cast<copy_fn>(
        dlsym(h, "hvd_tcp_copy_result"));
    a.error_string = reinterpret_cast<err_fn>(
        dlsym(h, "hvd_tcp_error_string"));
    a.release = reinterpret_cast<release_fn>(dlsym(h, "hvd_tcp_release"));
    a.is_initialized = reinterpret_cast<init_q_fn>(
        dlsym(h, "hvd_tcp_is_initialized"));
    a.ok = a.enqueue && a.poll && a.copy_result && a.error_string &&
           a.release && a.is_initialized;
    return a;
  }();
  return api;
}

// Core wire dtype codes (core/client.py _DTYPES).
int CoreDtype(tensorflow::DataType dt) {
  switch (dt) {
    case tensorflow::DT_UINT8: return 0;
    case tensorflow::DT_INT8: return 1;
    case tensorflow::DT_UINT16: return 2;
    case tensorflow::DT_INT16: return 3;
    case tensorflow::DT_INT32: return 4;
    case tensorflow::DT_INT64: return 5;
    case tensorflow::DT_HALF: return 6;
    case tensorflow::DT_FLOAT: return 7;
    case tensorflow::DT_DOUBLE: return 8;
    case tensorflow::DT_BOOL: return 9;
    case tensorflow::DT_BFLOAT16: return 10;
    default: return -1;
  }
}

// Blocking allreduce through the core; returns empty string on success,
// error text on failure.
std::string RunAllreduce(const std::string& name, const void* data,
                         const long long* dims, int ndim, int dtype,
                         int red_op, unsigned int ps_id, double prescale,
                         double postscale, void* out) {
  CoreApi& c = core();
  if (!c.ok) return "native core library not loadable";
  if (!c.is_initialized())
    return "native core not initialized (call hvd.init() first; the "
           "XLA op path needs a tcp/multihost world)";
  int h = c.enqueue(name.c_str(), /*op_type=allreduce*/ 0, data, dims,
                    ndim, dtype, red_op, /*root_rank=*/0, ps_id, prescale,
                    postscale, nullptr, 0);
  if (h < 0) return "enqueue failed for " + name;
  for (;;) {
    int st = c.poll(h);
    if (st == 1) break;
    if (st == 2) {
      char buf[4096];
      c.error_string(h, buf, sizeof(buf));
      c.release(h);
      return std::string(buf);
    }
    usleep(200);
  }
  int rc = c.copy_result(h, out);
  c.release(h);
  if (rc != 0) return "result copy failed for " + name;
  return "";
}

// ---- XLA host custom-call ----------------------------------------------
//
// Metadata rides constant operands (the ORIGINAL custom-call ABI passes
// no opaque on CPU):
//   ins[0]: i64 params  [name_len, red_op, dtype, ps_id,
//                        prescale_bits, postscale_bits, ndim,
//                        dims[0..ndim)]
//   ins[1]: u8  name bytes
//   ins[2]: payload
void HvdAllreduceHostCallback(void* out, const void** ins) {
  const int64_t* p = static_cast<const int64_t*>(ins[0]);
  const char* nm = static_cast<const char*>(ins[1]);
  std::string name(nm, static_cast<size_t>(p[0]));
  double prescale, postscale;
  std::memcpy(&prescale, &p[4], sizeof(double));
  std::memcpy(&postscale, &p[5], sizeof(double));
  int ndim = static_cast<int>(p[6]);
  std::vector<long long> dims(p + 7, p + 7 + ndim);
  std::string err = RunAllreduce(
      name, ins[2], dims.data(), ndim, static_cast<int>(p[2]),
      static_cast<int>(p[1]), static_cast<unsigned int>(p[3]), prescale,
      postscale, out);
  if (!err.empty()) {
    // The ORIGINAL custom-call ABI has no failure channel; a silently
    // wrong collective is worse than a loud stop (the reference's NCCL
    // ops abort the same way on comm failure).
    std::fprintf(stderr, "hvd_tf_ops: allreduce %s failed: %s\n",
                 name.c_str(), err.c_str());
    std::abort();
  }
}

XLA_REGISTER_CUSTOM_CALL_TARGET_WITH_SYM(
    "hvd_tpu_allreduce_host",
    reinterpret_cast<void*>(&HvdAllreduceHostCallback), "Host");

}  // namespace

// ---- TF op + kernels ----------------------------------------------------

REGISTER_OP("HvdTpuAllreduce")
    .Input("tensor: T")
    .Output("output: T")
    .Attr("T: {uint8, int8, uint16, int16, int32, int64, half, float, "
          "double, bfloat16}")
    .Attr("tensor_name: string")
    .Attr("reduce_op: int = 0")
    .Attr("prescale: float = 1.0")
    .Attr("postscale: float = 1.0")
    .Attr("process_set_id: int = 0")
    .SetShapeFn(tensorflow::shape_inference::UnchangedShape);

namespace {

using tensorflow::OpKernel;
using tensorflow::OpKernelConstruction;
using tensorflow::OpKernelContext;
using tensorflow::Tensor;

class HvdTpuAllreduceOp : public OpKernel {
 public:
  explicit HvdTpuAllreduceOp(OpKernelConstruction* ctx) : OpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("reduce_op", &red_op_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("prescale", &prescale_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("postscale", &postscale_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("process_set_id", &ps_id_));
  }

  void Compute(OpKernelContext* ctx) override {
    const Tensor& in = ctx->input(0);
    Tensor* out = nullptr;
    OP_REQUIRES_OK(ctx, ctx->allocate_output(0, in.shape(), &out));
    int dtype = CoreDtype(in.dtype());
    OP_REQUIRES(ctx, dtype >= 0,
                tensorflow::errors::InvalidArgument(
                    "unsupported dtype for hvd allreduce"));
    std::vector<long long> dims;
    for (int i = 0; i < in.dims(); ++i) dims.push_back(in.dim_size(i));
    std::string err = RunAllreduce(
        name_, in.tensor_data().data(), dims.data(),
        static_cast<int>(dims.size()), dtype, red_op_,
        static_cast<unsigned int>(ps_id_), prescale_, postscale_,
        const_cast<char*>(out->tensor_data().data()));
    OP_REQUIRES(ctx, err.empty(),
                tensorflow::errors::Internal("hvd allreduce ", name_,
                                             ": ", err));
  }

 private:
  std::string name_;
  int red_op_ = 0;
  float prescale_ = 1.0f;
  float postscale_ = 1.0f;
  int ps_id_ = 0;
};

REGISTER_KERNEL_BUILDER(
    Name("HvdTpuAllreduce").Device(tensorflow::DEVICE_CPU),
    HvdTpuAllreduceOp);

using tensorflow::XlaOpKernel;
using tensorflow::XlaOpKernelContext;

class HvdTpuAllreduceXlaOp : public XlaOpKernel {
 public:
  explicit HvdTpuAllreduceXlaOp(OpKernelConstruction* ctx)
      : XlaOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("reduce_op", &red_op_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("prescale", &prescale_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("postscale", &postscale_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("process_set_id", &ps_id_));
  }

  void Compile(XlaOpKernelContext* ctx) override {
    auto shape_or = ctx->InputXlaShape(0);
    OP_REQUIRES_OK(ctx, shape_or.status());
    xla::Shape shape = shape_or.value();
    int dtype = CoreDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, dtype >= 0,
                tensorflow::errors::InvalidArgument(
                    "unsupported dtype for hvd allreduce"));
    double pre = prescale_, post = postscale_;
    int64_t pre_bits, post_bits;
    std::memcpy(&pre_bits, &pre, sizeof(int64_t));
    std::memcpy(&post_bits, &post, sizeof(int64_t));
    std::vector<int64_t> params = {
        static_cast<int64_t>(name_.size()), red_op_, dtype, ps_id_,
        pre_bits, post_bits, shape.dimensions().size()};
    for (auto d : shape.dimensions()) params.push_back(d);
    std::vector<uint8_t> name_bytes(name_.begin(), name_.end());
    xla::XlaBuilder* b = ctx->builder();
    // has_side_effect=true: the call blocks on a rank-synchronizing
    // negotiation, so XLA must not CSE/dedupe or DCE it — divergent
    // scheduling across ranks would deadlock the controller.
    xla::XlaOp out = xla::CustomCall(
        b, "hvd_tpu_allreduce_host",
        {xla::ConstantR1<int64_t>(b, params),
         xla::ConstantR1<uint8_t>(b, name_bytes), ctx->Input(0)},
        shape, /*opaque=*/"", /*has_side_effect=*/true);
    ctx->SetOutput(0, out);
  }

 private:
  std::string name_;
  int red_op_ = 0;
  float prescale_ = 1.0f;
  float postscale_ = 1.0f;
  int ps_id_ = 0;
};

REGISTER_XLA_OP(
    Name("HvdTpuAllreduce").Device(tensorflow::DEVICE_CPU_XLA_JIT),
    HvdTpuAllreduceXlaOp);

// TPU jit: a host custom-call target cannot exist inside a TPU
// executable, so surface a trace-time error that names the supported
// path rather than an opaque compile/link failure deep in XLA.
class HvdTpuAllreduceXlaTpuOp : public XlaOpKernel {
 public:
  explicit HvdTpuAllreduceXlaTpuOp(OpKernelConstruction* ctx)
      : XlaOpKernel(ctx) {}

  void Compile(XlaOpKernelContext* ctx) override {
    ctx->SetStatus(tensorflow::errors::Unimplemented(
        "hvd allreduce inside tf.function(jit_compile=True) is not "
        "supported on TPU: the op lowers to a host custom-call, which "
        "cannot live in a TPU executable. Use the JAX adapter "
        "(horovod_tpu.jax) for compiled TPU collectives, or run the "
        "TF op outside jit_compile (graph/eager kernels work on any "
        "device)."));
  }
};

REGISTER_XLA_OP(
    Name("HvdTpuAllreduce").Device("XLA_TPU_JIT"),
    HvdTpuAllreduceXlaTpuOp);

}  // namespace
