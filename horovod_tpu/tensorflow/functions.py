"""TF variable/object broadcast helpers.

Reference parity: ``horovod/tensorflow/functions.py`` —
``broadcast_variables``, ``broadcast_object``, ``allgather_object``,
plus Keras-model/optimizer broadcast used by the callbacks.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import tensorflow as tf

from ..jax.functions import allgather_object as _allgather_object
from ..jax.functions import broadcast_object as _broadcast_object
from . import mpi_ops


def broadcast_variables(variables: Iterable[tf.Variable],
                        root_rank: int = 0):
    """Assign every variable the root rank's value (reference
    ``hvd.broadcast_variables(model.variables, root_rank=0)``)."""
    handles = []
    for i, v in enumerate(variables):
        name = "broadcast_variables.%d.%s" % (i, getattr(v, "name", ""))
        handles.append((v, mpi_ops.broadcast_async(
            v, root_rank, name=name.replace("/", "_").replace(":", "_"))))
    for v, h in handles:
        v.assign(tf.reshape(h.wait(), tf.shape(v)))


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    return _broadcast_object(obj, root_rank, name=name)


def allgather_object(obj: Any, name: Optional[str] = None):
    return _allgather_object(obj, name=name)
