"""Local gradient aggregation for TF.

Reference parity: ``horovod/tensorflow/gradient_aggregation_eager.py``
(``LocalGradientAggregationHelperEager``) — accumulate gradients
locally for ``backward_passes_per_step`` steps and allreduce only on
the boundary step, trading extra memory for fewer collectives.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence


class LocalGradientAggregationHelper:
    """Accumulates grads for N passes; fires ``allreduce_fn`` on the Nth.

    ``apply(grads)`` returns ``(should_apply, grads)``: on non-boundary
    passes ``should_apply`` is False and the caller must skip the inner
    optimizer update (the reference's helper likewise suppresses
    ``apply_gradients`` between boundaries).
    """

    def __init__(self, backward_passes_per_step: int,
                 allreduce_fn: Callable[[List], List],
                 average_aggregated_gradients: bool = True):
        if backward_passes_per_step <= 0:
            raise ValueError("backward_passes_per_step must be > 0")
        self.backward_passes_per_step = backward_passes_per_step
        self.allreduce_fn = allreduce_fn
        self.average_aggregated_gradients = average_aggregated_gradients
        self.counter = 0
        self._acc: Optional[List] = None

    def apply(self, grads: Sequence):
        import tensorflow as tf
        grads = list(grads)
        if self.backward_passes_per_step == 1:
            return True, self.allreduce_fn(grads)
        if self._acc is None:
            self._acc = [tf.zeros_like(g) if g is not None else None
                         for g in grads]
        self._acc = [a + g if (a is not None and g is not None)
                     else (g if a is None else a)
                     for a, g in zip(self._acc, grads)]
        self.counter += 1
        if self.counter < self.backward_passes_per_step:
            return False, grads
        out = self._acc
        if self.average_aggregated_gradients:
            out = [g / float(self.backward_passes_per_step)
                   if g is not None else None for g in out]
        self.counter = 0
        self._acc = None
        return True, self.allreduce_fn(out)
