"""tf.keras adapter spelling (reference ``horovod.tensorflow.keras``):
identical surface to ``horovod_tpu.keras`` — in the Keras-3 era there
is one keras, so both import paths resolve to the same adapter.
"""

from ...keras import *  # noqa: F401,F403
from ...keras import callbacks, elastic  # noqa: F401
