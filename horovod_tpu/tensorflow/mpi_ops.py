"""TensorFlow collective ops over the native core.

Reference parity: ``horovod/tensorflow/mpi_ops.py`` (+ the custom-op
kernels in ``horovod/tensorflow/mpi_ops.cc``): the eight collectives on
``tf.Tensor`` values, each with a gradient registered so they compose
with ``tf.GradientTape``.  The wire format is the tensor's numpy view
into the same engine the torch adapter uses; on TPU the compute path is
the JAX adapter — this adapter moves host tensors through the
multi-process world, which is exactly the role the reference's CPU
(MPI/Gloo) path plays.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import tensorflow as tf

from ..ops import api as _api
from ..ops.xla_ops import AVERAGE, SUM

__all__ = [
    "allreduce", "grouped_allreduce", "allgather", "broadcast",
    "alltoall", "reducescatter", "barrier", "join",
    "allreduce_async", "allgather_async", "broadcast_async",
    "synchronize", "poll",
]


def _np_view(t) -> np.ndarray:
    """Numpy view of an eager tf.Tensor (bfloat16 rides its ml_dtypes
    representation, which is already the engine's wire format)."""
    t = tf.convert_to_tensor(t)
    return np.asarray(t)


def _run_op(fn, x, out_shape=None):
    """Run ``fn`` (an eager collective) on ``x``; inside a traced
    ``tf.function`` the call is staged as a ``tf.py_function`` so the
    collective still executes on the host at step time — the role the
    reference's registered TF custom kernels play in graph mode
    (``horovod/tensorflow/mpi_ops.cc``)."""
    if tf.is_symbolic_tensor(x):
        y = tf.py_function(fn, [x], Tout=x.dtype)
        y.set_shape(out_shape if out_shape is not None else x.shape)
        return y
    return fn(x)


def _to_tf(arr, like=None):
    t = tf.convert_to_tensor(np.ascontiguousarray(np.asarray(arr)))
    if like is not None and t.dtype != like.dtype:
        t = tf.cast(t, like.dtype)
    return t


class TFHandle:
    """Async handle returning tf tensors (reference: the AsyncOpKernel
    completion callback in mpi_ops.cc)."""

    def __init__(self, inner, like=None):
        self._inner = inner
        self._like = like

    def poll(self) -> bool:
        return self._inner.poll()

    def wait(self, timeout: Optional[float] = None):
        res = self._inner.wait(timeout)
        splits = None
        if isinstance(res, tuple):
            res, splits = res
        t = _to_tf(res, like=self._like)
        return (t, splits) if splits is not None else t


def synchronize(handle: TFHandle):
    return handle.wait()


def poll(handle: TFHandle) -> bool:
    return handle.poll()


# -- allreduce -------------------------------------------------------------

def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set=None) -> TFHandle:
    tensor = tf.convert_to_tensor(tensor)
    h = _api.allreduce_async(_np_view(tensor), average, name, op,
                             prescale_factor, postscale_factor,
                             process_set)
    return TFHandle(h, like=tensor)


def allreduce(tensor, average=None, name: Optional[str] = None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set=None):
    """Sum/average ``tensor`` over all ranks.  Differentiable: the
    gradient of an allreduce is the allreduce of the gradient
    (reference: the ``HorovodAllreduce`` gradient registration in
    ``horovod/tensorflow/mpi_ops.py``)."""
    tensor = tf.convert_to_tensor(tensor)

    @tf.custom_gradient
    def _op(x):
        y = _run_op(
            lambda v: allreduce_async(v, average, name, op,
                                      prescale_factor, postscale_factor,
                                      process_set).wait(), x)

        def grad(dy):
            return _run_op(
                lambda v: allreduce_async(
                    v, average,
                    None if name is None else name + "_grad", op,
                    prescale_factor, postscale_factor,
                    process_set).wait(), dy)

        return y, grad

    return _op(tensor)


def grouped_allreduce(tensors: Sequence, average=None,
                      name: Optional[str] = None, op=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set=None) -> List:
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    hs = _api.grouped_allreduce_async(
        [_np_view(t) for t in tensors], average, name, op,
        prescale_factor, postscale_factor, process_set)
    return [TFHandle(h, like=t).wait() for h, t in zip(hs, tensors)]


# -- allgather -------------------------------------------------------------

def allgather_async(tensor, name: Optional[str] = None,
                    process_set=None) -> TFHandle:
    tensor = tf.convert_to_tensor(tensor)
    h = _api.allgather_async(_np_view(tensor), name, process_set)
    return TFHandle(h, like=tensor)


def allgather(tensor, name: Optional[str] = None, process_set=None):
    tensor = tf.convert_to_tensor(tensor)
    out_shape = tf.TensorShape([None]).concatenate(tensor.shape[1:])
    return _run_op(
        lambda v: allgather_async(v, name, process_set).wait(),
        tensor, out_shape=out_shape)


# -- broadcast -------------------------------------------------------------

def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set=None) -> TFHandle:
    tensor = tf.convert_to_tensor(tensor)
    h = _api.broadcast_async(_np_view(tensor), root_rank, name,
                             process_set)
    return TFHandle(h, like=tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set=None):
    tensor = tf.convert_to_tensor(tensor)
    return _run_op(
        lambda v: broadcast_async(v, root_rank, name,
                                  process_set).wait(), tensor)


# -- alltoall / reducescatter ----------------------------------------------

def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set=None):
    tensor = tf.convert_to_tensor(tensor)
    if splits is not None and isinstance(splits, tf.Tensor):
        splits = splits.numpy().tolist()
    h = _api.alltoall_async(_np_view(tensor), splits, name, process_set)
    res = TFHandle(h, like=tensor).wait()
    if splits is None and isinstance(res, tuple):
        return res[0]
    return res


def reducescatter(tensor, op=SUM, name: Optional[str] = None,
                  process_set=None):
    tensor = tf.convert_to_tensor(tensor)
    out_shape = tf.TensorShape([None]).concatenate(tensor.shape[1:])
    return _run_op(
        lambda v: TFHandle(_api.reducescatter_async(
            _np_view(v), op, name, process_set), like=v).wait(),
        tensor, out_shape=out_shape)


# -- barrier / join --------------------------------------------------------

def barrier(process_set=None):
    return _api.barrier(process_set)


def join(device=None) -> int:
    return _api.join(device)
