"""TensorFlow collective ops over the native core.

Reference parity: ``horovod/tensorflow/mpi_ops.py`` (+ the custom-op
kernels in ``horovod/tensorflow/mpi_ops.cc``): the eight collectives on
``tf.Tensor`` values, each with a gradient registered so they compose
with ``tf.GradientTape`` (reference registrations ``HorovodAllreduce``,
``HorovodAllgather``, ``HorovodBroadcast``, ``HorovodAlltoall``,
``HorovodReducescatter``).  The wire format is the tensor's numpy view
into the same engine the torch adapter uses; on TPU the compute path is
the JAX adapter — this adapter moves host tensors through the
multi-process world, which is exactly the role the reference's CPU
(MPI/Gloo) path plays.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import tensorflow as tf

from ..ops import api as _api
from ..ops.xla_ops import AVERAGE, SUM

__all__ = [
    "allreduce", "grouped_allreduce", "allgather", "broadcast",
    "alltoall", "reducescatter", "barrier", "join",
    "grouped_allgather", "grouped_allgather_async",
    "grouped_reducescatter", "grouped_reducescatter_async",
    "allreduce_async", "grouped_allreduce_async", "allgather_async",
    "broadcast_async", "synchronize", "poll",
    "size_op", "local_size_op", "rank_op", "local_rank_op",
    "process_set_included_op",
]


def _np_view(t) -> np.ndarray:
    """Numpy view of an eager tf.Tensor (bfloat16 rides its ml_dtypes
    representation, which is already the engine's wire format)."""
    t = tf.convert_to_tensor(t)
    return np.asarray(t)


def _tpu_present() -> bool:
    """Whether TF exposes a TPU device (monkeypatchable in tests).

    Only a POSITIVE enumeration is cached: a trace that runs before
    ``initialize_tpu_system`` must not pin False forever and silently
    disable the jit_compile guard for later TPU traces."""
    global _TPU_PRESENT
    if _TPU_PRESENT:
        return True
    try:
        if tf.config.list_logical_devices("TPU"):
            _TPU_PRESENT = True
    except Exception:  # noqa: BLE001 - device enumeration is best-effort
        pass
    return bool(_TPU_PRESENT)


_TPU_PRESENT: Optional[bool] = None


def _tracing_jit_compile() -> bool:
    """True when the current symbolic trace belongs to a
    ``tf.function(jit_compile=True)``: the polymorphic ``Function``
    driving the trace sits on the Python stack with its
    ``_jit_compile`` flag (there is no FuncGraph-level signal).  The
    match is restricted to TF's polymorphic Function type — other
    objects carry a ``_jit_compile`` attribute too (e.g. a Keras
    model after ``compile(jit_compile=True)``) without meaning THIS
    trace is XLA-compiled."""
    import sys
    try:
        from tensorflow.python.eager.polymorphic_function import (
            polymorphic_function as _pf)
        fn_type = _pf.Function
    except Exception:  # noqa: BLE001 - internal layout varies by TF
        fn_type = None
    frame = sys._getframe()
    while frame is not None:
        obj = frame.f_locals.get("self")
        if getattr(obj, "_jit_compile", None) is True:
            if fn_type is not None:
                if isinstance(obj, fn_type):
                    return True
            elif (hasattr(obj, "function_spec")
                  or hasattr(obj, "_variable_creation_config")):
                # Type resolution failed (internal layout varies by TF
                # version): accept the duck-typed match only with
                # polymorphic-Function evidence beyond the bare flag —
                # an arbitrary object carrying _jit_compile=True on the
                # stack (e.g. a Keras model after
                # compile(jit_compile=True)) must not trip the guard
                # for an uncompiled trace.
                return True
        frame = frame.f_back
    return False


def _check_tpu_jit_trace():
    """Actionable trace-time error for ``jit_compile=True``.

    A ``py_function`` is unsupported inside ANY jit-compiled XLA
    executable — without this check the user gets an opaque
    "Detected unsupported operations ... EagerPyFunc" compile error at
    step time.  On TPU (where even the native host custom-call path,
    reference ``xla_mpi_ops.cc``, is structurally impossible) the
    message redirects to the JAX adapter; elsewhere it points at the
    native-op path.  (SURVEY §2.3 TF XLA ops row.)"""
    if not _tracing_jit_compile():
        return
    if _tpu_present():
        raise NotImplementedError(
            "horovod_tpu.tensorflow collectives cannot be compiled "
            "into a tf.function(jit_compile=True) TPU executable: the "
            "collective executes on the host, and a host call cannot "
            "live inside a TPU program. Either drop jit_compile=True "
            "(the collective stages as a py_function at step time), "
            "or use the JAX adapter (horovod_tpu.jax), whose "
            "collectives compile into the TPU program as native XLA "
            "ops over ICI. See docs/adapters.md (jax2tf note).")
    raise NotImplementedError(
        "horovod_tpu.tensorflow collectives stage as a py_function, "
        "which cannot live inside a tf.function(jit_compile=True) "
        "executable. Either drop jit_compile=True, or (allreduce, "
        "tcp/multihost worlds) set HOROVOD_ENABLE_XLA_OPS=1 to route "
        "through the native custom-call op, which jit-compiles on "
        "CPU (reference xla_mpi_ops.cc).")


def _run_op(fn, x, out_shape=None):
    """Run ``fn`` (an eager collective) on ``x``; inside a traced
    ``tf.function`` the call is staged as a ``tf.py_function`` so the
    collective still executes on the host at step time — the role the
    reference's registered TF custom kernels play in graph mode
    (``horovod/tensorflow/mpi_ops.cc``)."""
    if tf.is_symbolic_tensor(x):
        _check_tpu_jit_trace()
        y = tf.py_function(fn, [x], Tout=x.dtype)
        y.set_shape(out_shape if out_shape is not None else x.shape)
        return y
    return fn(x)


def _to_tf(arr, like=None):
    t = tf.convert_to_tensor(np.ascontiguousarray(np.asarray(arr)))
    if like is not None and t.dtype != like.dtype:
        t = tf.cast(t, like.dtype)
    return t


def _ps_rank(process_set) -> int:
    if process_set is not None:
        return process_set.rank()
    from ..common import basics
    return basics.rank()


def _ps_size(process_set) -> int:
    if process_set is not None:
        return process_set.size()
    from ..common import basics
    return basics.size()


class TFHandle:
    """Async handle returning tf tensors (reference: the AsyncOpKernel
    completion callback in mpi_ops.cc)."""

    def __init__(self, inner, like=None):
        self._inner = inner
        self._like = like

    def poll(self) -> bool:
        return self._inner.poll()

    def wait(self, timeout: Optional[float] = None):
        res = self._inner.wait(timeout)
        splits = None
        if isinstance(res, tuple):
            res, splits = res
        if isinstance(res, list):
            # Ragged result (in-process uneven reducescatter, or
            # alltoall with per-rank shapes): one tensor per rank.
            # Keep the (output, recv_splits) contract.
            converted = [_to_tf(r, like=self._like) for r in res]
            return (converted, splits) if splits is not None else converted
        t = _to_tf(res, like=self._like)
        return (t, splits) if splits is not None else t


def synchronize(handle: TFHandle):
    return handle.wait()


def poll(handle: TFHandle) -> bool:
    return handle.poll()


# -- allreduce -------------------------------------------------------------

def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set=None) -> TFHandle:
    tensor = tf.convert_to_tensor(tensor)
    h = _api.allreduce_async(_np_view(tensor), average, name, op,
                             prescale_factor, postscale_factor,
                             process_set)
    return TFHandle(h, like=tensor)


def allreduce(tensor, average=None, name: Optional[str] = None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set=None):
    """Sum/average ``tensor`` over all ranks.  Differentiable: the
    gradient of an allreduce is the allreduce of the gradient
    (reference: the ``HorovodAllreduce`` gradient registration in
    ``horovod/tensorflow/mpi_ops.py``).

    With ``HOROVOD_ENABLE_XLA_OPS=1`` (reference knob) in a
    tcp/multihost world, the call routes through the native
    ``HvdTpuAllreduce`` op, which also works inside
    ``tf.function(jit_compile=True)`` (reference ``xla_mpi_ops.cc``)."""
    tensor = tf.convert_to_tensor(tensor)
    from . import xla_ops as _xla
    if _xla.enabled() and not tf.executing_eagerly():
        # Symbolic tracing only (tf.function / jit_compile): eager
        # calls keep their mode's payload plane (multihost ICI stays
        # ICI) and the op-manager backend walk; the native op exists
        # for graphs where py_function cannot (reference
        # xla_mpi_ops.cc).
        from ..common import basics
        from ..ops.xla_ops import handle_average_backwards_compatibility
        red_op = handle_average_backwards_compatibility(op, average)
        if (basics.is_initialized()
                and not basics._controller_is_spmd()
                and red_op in _xla._RED_OPS
                and _xla.load() is not None):
            return _xla.allreduce(
                tensor, _api._auto_name("allreduce", name), red_op,
                prescale_factor, postscale_factor,
                _api._ps_id(process_set))

    @tf.custom_gradient
    def _op(x):
        y = _run_op(
            lambda v: allreduce_async(v, average, name, op,
                                      prescale_factor, postscale_factor,
                                      process_set).wait(), x)

        def grad(dy):
            return _run_op(
                lambda v: allreduce_async(
                    v, average,
                    _grad_name(name), op,
                    prescale_factor, postscale_factor,
                    process_set).wait(), dy)

        return y, grad

    return _op(tensor)


def _grouped_allreduce_eager(tensors: List, average, name, op,
                             prescale_factor, postscale_factor,
                             process_set) -> List:
    hs = _api.grouped_allreduce_async(
        [_np_view(t) for t in tensors], average, name, op,
        prescale_factor, postscale_factor, process_set)
    return [TFHandle(h, like=t).wait() for h, t in zip(hs, tensors)]


def grouped_allreduce_async(tensors: Sequence, average=None,
                            name: Optional[str] = None, op=None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set=None) -> List[TFHandle]:
    """Async grouped allreduce (eager tensors only; graph mode stages
    through ``grouped_allreduce``)."""
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    hs = _api.grouped_allreduce_async(
        [_np_view(t) for t in tensors], average, name, op,
        prescale_factor, postscale_factor, process_set)
    return [TFHandle(h, like=t) for h, t in zip(hs, tensors)]


def _grad_name(name):
    return None if name is None else name + "_grad"


def _grouped_custom_grad(tensors, fwd_fn, fwd_shapes, grad_fn,
                         grad_shapes):
    """Shared scaffold: grouped forward + grouped backward, both
    stageable into tf.function, list in / list out."""

    @tf.custom_gradient
    def _op(*xs):
        ys = _stage_group(fwd_fn, list(xs), out_shapes=fwd_shapes)

        def grad(*dys):
            return _stage_group(grad_fn, list(dys),
                                out_shapes=grad_shapes)

        return ys, grad

    out = _op(*tensors)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def grouped_allreduce(tensors: Sequence, average=None,
                      name: Optional[str] = None, op=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set=None) -> List:
    """Differentiable like the single op: the gradient of a grouped
    allreduce is the grouped allreduce of the gradients."""
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    shapes = [t.shape for t in tensors]
    return _grouped_custom_grad(
        tensors,
        lambda ts: _grouped_allreduce_eager(
            ts, average, name, op, prescale_factor, postscale_factor,
            process_set),
        shapes,
        lambda ts: _grouped_allreduce_eager(
            ts, average, _grad_name(name), op, prescale_factor,
            postscale_factor, process_set),
        shapes)


def _stage_group(eager_fn, tensors, out_shapes=None):
    """Run a grouped eager fn now, or stage it through py_function when
    any input is symbolic (shapes set when statically known)."""
    if any(tf.is_symbolic_tensor(t) for t in tensors):
        _check_tpu_jit_trace()
        ys = tf.py_function(lambda *xs: eager_fn(list(xs)), tensors,
                            Tout=[t.dtype for t in tensors])
        ys = list(ys) if isinstance(ys, (list, tuple)) else [ys]
        if out_shapes is not None:
            for y, s in zip(ys, out_shapes):
                y.set_shape(s)
        return ys
    return eager_fn(tensors)


def grouped_allgather_async(tensors: Sequence,
                            name: Optional[str] = None,
                            process_set=None) -> List[TFHandle]:
    """Async grouped allgather (eager tensors only)."""
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    hs = _api.grouped_allgather_async(
        [_np_view(t) for t in tensors], name, process_set)
    return [TFHandle(h, like=t) for h, t in zip(hs, tensors)]


def grouped_allgather(tensors: Sequence, name: Optional[str] = None,
                      process_set=None) -> List:
    """Differentiable: each member's gradient is the allreduce-sum of
    the upstream grad sliced to this rank's rows (the single-allgather
    gradient, grouped)."""
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    n_locals = [t.shape[0] for t in tensors]
    gname = _grad_name(name)

    def _g(ts):
        if any(n is None for n in n_locals):
            raise NotImplementedError(
                "grouped_allgather gradient needs static first "
                "dimensions")
        summed = [h.wait() for h in grouped_allreduce_async(
            ts, op=SUM, name=gname, process_set=process_set)]
        sizes = np.asarray(_api.allgather(
            np.asarray([int(n) for n in n_locals],
                       np.int64).reshape(1, -1),
            name=None if gname is None else gname + "_sizes",
            process_set=process_set))
        my = _ps_rank(process_set)
        return [s[int(sizes[:my, i].sum()):
                  int(sizes[:my, i].sum()) + int(n)]
                for i, (s, n) in enumerate(zip(summed, n_locals))]

    return _grouped_custom_grad(
        tensors,
        lambda ts: [h.wait() for h in grouped_allgather_async(
            ts, name, process_set)],
        [tf.TensorShape([None]).concatenate(t.shape[1:])
         for t in tensors],
        _g, [t.shape for t in tensors])


def grouped_reducescatter_async(tensors: Sequence, op=None,
                                name: Optional[str] = None,
                                process_set=None) -> List[TFHandle]:
    """Async grouped reducescatter (eager tensors only)."""
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    hs = _api.grouped_reducescatter_async(
        [_np_view(t) for t in tensors], op, name, process_set)
    return [TFHandle(h, like=t) for h, t in zip(hs, tensors)]


def grouped_reducescatter(tensors: Sequence, op=None,
                          name: Optional[str] = None,
                          process_set=None) -> List:
    """Differentiable: the gradient is the grouped allgather of the
    upstream grads (scaled by 1/size for Average, like the single op)."""
    tensors = [tf.convert_to_tensor(t) for t in tensors]

    def _g(ts):
        gs = [h.wait() for h in grouped_allgather_async(
            ts, _grad_name(name), process_set)]
        if op == AVERAGE:
            gs = [g / tf.cast(_ps_size(process_set), g.dtype)
                  for g in gs]
        return gs

    return _grouped_custom_grad(
        tensors,
        lambda ts: [h.wait() for h in grouped_reducescatter_async(
            ts, op, name, process_set)],
        [tf.TensorShape([None]).concatenate(t.shape[1:])
         for t in tensors],
        _g, [t.shape for t in tensors])


# -- allgather -------------------------------------------------------------

def allgather_async(tensor, name: Optional[str] = None,
                    process_set=None) -> TFHandle:
    tensor = tf.convert_to_tensor(tensor)
    h = _api.allgather_async(_np_view(tensor), name, process_set)
    return TFHandle(h, like=tensor)


def allgather(tensor, name: Optional[str] = None, process_set=None):
    """Concatenate ``tensor`` from all ranks along axis 0.
    Differentiable: the gradient sums upstream grads over ranks and
    slices out this rank's segment (reference ``HorovodAllgather``
    gradient: allreduce + split by the allgathered first dims)."""
    tensor = tf.convert_to_tensor(tensor)
    out_shape = tf.TensorShape([None]).concatenate(tensor.shape[1:])
    n_local = tensor.shape[0]

    @tf.custom_gradient
    def _op(x):
        y = _run_op(
            lambda v: allgather_async(v, name, process_set).wait(),
            x, out_shape=out_shape)

        def grad(dy):
            if n_local is None:
                raise NotImplementedError(
                    "allgather gradient needs a static first dimension")

            def _g(dyv):
                gname = _grad_name(name)
                summed = allreduce_async(dyv, op=SUM, name=gname,
                                         process_set=process_set).wait()
                sizes = np.asarray(_api.allgather(
                    np.asarray([int(n_local)], np.int64),
                    name=None if gname is None else gname + "_sizes",
                    process_set=process_set))
                off = int(sizes[:_ps_rank(process_set)].sum())
                return summed[off:off + int(n_local)]

            return _run_op(_g, dy, out_shape=x.shape)

        return y, grad

    return _op(tensor)


# -- broadcast -------------------------------------------------------------

def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set=None) -> TFHandle:
    tensor = tf.convert_to_tensor(tensor)
    h = _api.broadcast_async(_np_view(tensor), root_rank, name,
                             process_set)
    return TFHandle(h, like=tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set=None):
    """Broadcast from ``root_rank``.  Differentiable: the root's
    gradient is the sum of upstream grads over ranks; non-roots get
    zero (reference ``HorovodBroadcast`` gradient registration)."""
    tensor = tf.convert_to_tensor(tensor)

    @tf.custom_gradient
    def _op(x):
        y = _run_op(
            lambda v: broadcast_async(v, root_rank, name,
                                      process_set).wait(), x)

        def grad(dy):
            g = _run_op(
                lambda v: allreduce_async(
                    v, op=SUM,
                    name=_grad_name(name),
                    process_set=process_set).wait(), dy)
            # root_rank is a GLOBAL rank (core operations.cc broadcast
            # semantics), so compare against the global rank even when
            # scoped to a process set.
            from ..common import basics
            if basics.rank() == root_rank:
                return g
            return tf.zeros_like(g)

        return y, grad

    return _op(tensor)


# -- alltoall / reducescatter ----------------------------------------------

def _alltoall_graph_with_splits(tensor, splits, name, process_set):
    """Explicit-splits alltoall inside ``tf.function``: the staged
    py_function emits BOTH the output rows and the received splits as
    tensors (the reference graph contract — ``HorovodAlltoall``
    returns ``received_splits``), and the backward reverse-routes with
    the forward's recv_splits TENSOR (per-execution correct even under
    tf.while_loop / persistent tapes)."""
    out_shape = tf.TensorShape([None]).concatenate(tensor.shape[1:])
    sp = tf.convert_to_tensor(splits, dtype=tf.int32)

    @tf.custom_gradient
    def _op(x, spv):
        def _fwd(v, s):
            res = TFHandle(_api.alltoall_async(
                _np_view(v), [int(i) for i in np.asarray(s)], name,
                process_set), like=v).wait()
            out, recv = res  # explicit splits -> (out, recv_splits)
            return out, np.asarray([int(i) for i in recv], np.int32)

        _check_tpu_jit_trace()
        y, recv_t = tf.py_function(_fwd, [x, spv],
                                   Tout=(x.dtype, tf.int32))
        y.set_shape(out_shape)
        recv_t.set_shape([None])

        def grad(dy, d_recv):
            # recv_t is the FORWARD's tensor output, so the backward's
            # reverse routing is per-execution correct (a Python cell
            # would hold only the LAST forward's splits — wrong under
            # tf.while_loop or multiple forwards on a persistent tape).
            def _bwd(v, rt):
                res = TFHandle(_api.alltoall_async(
                    _np_view(v), [int(i) for i in np.asarray(rt)],
                    _grad_name(name),
                    process_set), like=v).wait()
                return res[0] if isinstance(res, tuple) else res

            g = tf.py_function(_bwd, [dy, recv_t], Tout=dy.dtype)
            g.set_shape(x.shape)
            return g, None

        return (y, recv_t), grad

    return _op(tensor, sp)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set=None):
    """Exchange row blocks between all ranks.  Differentiable: the
    gradient is the reverse alltoall of the upstream grad, routed by
    the received splits (reference ``HorovodAlltoall`` gradient).

    With explicit ``splits`` the return is ``(output, recv_splits)``;
    inside ``tf.function`` both come back as tensors (reference graph
    contract), eagerly recv_splits is a list."""
    tensor = tf.convert_to_tensor(tensor)
    if splits is not None:
        if tf.is_symbolic_tensor(tensor) or (
                isinstance(splits, tf.Tensor)
                and tf.is_symbolic_tensor(splits)):
            return _alltoall_graph_with_splits(tensor, splits, name,
                                               process_set)
        if isinstance(splits, tf.Tensor):
            splits = splits.numpy().tolist()
    out_shape = tf.TensorShape([None]).concatenate(tensor.shape[1:])
    n_local = tensor.shape[0]
    rcell = {}

    @tf.custom_gradient
    def _op(x):
        def _fwd(v):
            res = TFHandle(_api.alltoall_async(
                _np_view(v), splits, name, process_set), like=v).wait()
            if isinstance(res, tuple):
                res, rcell["recv_splits"] = res
            return res

        y = _run_op(_fwd, x, out_shape=out_shape)

        def grad(dy):
            def _bwd(v):
                if splits is not None:
                    # Eager-only path (explicit splits are rejected in
                    # tf.function above): rcell is private to this one
                    # call and its forward already ran, so the recorded
                    # recv_splits cannot be overwritten by a later
                    # forward.
                    rs = list(rcell["recv_splits"])
                else:
                    # splits=None still permits UNEVEN receives (each
                    # rank splits its OWN rows evenly, but peers may
                    # contribute different totals), and in tf.function
                    # one trace serves many executions — recorded state
                    # from the forward is not per-execution-safe.
                    # Re-derive the reverse routing here instead: peer
                    # j sent n_j // set_size rows, so allgather every
                    # rank's send-count at backward time.
                    if n_local is None:
                        raise NotImplementedError(
                            "alltoall gradient needs a static first "
                            "dimension")
                    gname = (None if name is None
                             else name + "_grad_sizes")
                    per_peer = int(n_local) // _ps_size(process_set)
                    sizes = np.asarray(_api.allgather(
                        np.asarray([per_peer], np.int64), name=gname,
                        process_set=process_set))
                    rs = [int(s) for s in sizes.reshape(-1)]
                res = TFHandle(_api.alltoall_async(
                    _np_view(v), rs,
                    _grad_name(name),
                    process_set), like=v).wait()
                return res[0] if isinstance(res, tuple) else res

            return _run_op(_bwd, dy, out_shape=x.shape)

        return y, grad

    out = _op(tensor)
    if splits is not None:
        rs = rcell.get("recv_splits")
        if rs is not None:
            return out, rs
    return out


def reducescatter(tensor, op=SUM, name: Optional[str] = None,
                  process_set=None):
    """Reduce over ranks and scatter row blocks.  Differentiable: the
    gradient is the allgather of the upstream grad (reference
    ``HorovodReducescatter`` gradient registration)."""
    tensor = tf.convert_to_tensor(tensor)
    out_shape = tf.TensorShape([None]).concatenate(tensor.shape[1:])

    @tf.custom_gradient
    def _op(x):
        y = _run_op(
            lambda v: TFHandle(_api.reducescatter_async(
                _np_view(v), op, name, process_set), like=v).wait(),
            x, out_shape=out_shape)

        def grad(dy):
            def _g(v):
                g = TFHandle(_api.allgather_async(
                    _np_view(v),
                    _grad_name(name),
                    process_set), like=v).wait()
                if op == AVERAGE:
                    # The forward divides the reduction by the set size;
                    # the backward must scale the allgathered grad the
                    # same way or gradients come out size() times too
                    # large.
                    g = g / tf.cast(_ps_size(process_set), g.dtype)
                return g

            return _run_op(_g, dy, out_shape=x.shape)

        return y, grad

    return _op(tensor)


# -- barrier / join --------------------------------------------------------

def barrier(process_set=None):
    return _api.barrier(process_set)


def join(device=None) -> int:
    return _api.join(device)


# -- graph-mode world-info ops ---------------------------------------------
# Reference: size_op/local_size_op/rank_op/local_rank_op/
# process_set_included_op in horovod/tensorflow/mpi_ops.py — tensors
# read at EXECUTION time, so a tf.function traced once keeps seeing the
# current world across elastic re-initialization without retracing.

def _world_read_op(read, name):
    def _read():
        return np.int32(read())
    _check_tpu_jit_trace()
    out = tf.py_function(_read, [], tf.int32, name=name)
    out.set_shape([])
    return out


def size_op(process_set_id: int = 0, name: Optional[str] = None):
    """Current world (or process-set) size as a graph tensor."""
    from ..common.process_sets import process_set_by_id
    return _world_read_op(
        lambda: process_set_by_id(process_set_id).size(),
        name or "HorovodSize")


def local_size_op(name: Optional[str] = None):
    from ..common import basics
    return _world_read_op(basics.local_size, name or "HorovodLocalSize")


def rank_op(name: Optional[str] = None):
    from ..common import basics
    return _world_read_op(basics.rank, name or "HorovodRank")


def local_rank_op(name: Optional[str] = None):
    from ..common import basics
    return _world_read_op(basics.local_rank, name or "HorovodLocalRank")


def process_set_included_op(process_set_id: int = 0,
                            name: Optional[str] = None):
    """1 when this rank belongs to the process set, else 0 (graph
    tensor, execution-time read; uninitialized worlds raise like the
    sibling ops)."""
    from ..common.process_sets import process_set_by_id
    return _world_read_op(
        lambda: 1 if process_set_by_id(process_set_id).included() else 0,
        name or "HorovodProcessSetIncluded")
