"""Synchronized BatchNormalization for tf.keras.

Reference parity: ``horovod/tensorflow/sync_batch_norm.py``
(``SyncBatchNormalization``): training-mode batch statistics are
computed over the GLOBAL batch by allreducing per-rank sums /
square-sums / counts.  The backward pass needs no custom code: the
collective is differentiable (allreduce's registered gradient is an
allreduce of the upstream gradient), so autodiff produces exactly the
synced-BN input gradient — the same two-collective structure the
reference builds by hand in torch.
"""

from __future__ import annotations

import tensorflow as tf

from .mpi_ops import allreduce
from ..ops.xla_ops import SUM


class SyncBatchNormalization(tf.keras.layers.Layer):
    """Drop-in BatchNormalization whose train-mode statistics cover
    the global batch (channels-last; normalizes over all axes but the
    last, like ``BatchNormalization(axis=-1)``)."""

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 center: bool = True, scale: bool = True,
                 name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.momentum = momentum
        self.epsilon = epsilon
        self.center = center
        self.scale = scale

    def build(self, input_shape):
        c = int(input_shape[-1])
        self.gamma = self.add_weight(
            name="gamma", shape=(c,), initializer="ones",
            trainable=self.scale)
        self.beta = self.add_weight(
            name="beta", shape=(c,), initializer="zeros",
            trainable=self.center)
        self.moving_mean = self.add_weight(
            name="moving_mean", shape=(c,), initializer="zeros",
            trainable=False)
        self.moving_variance = self.add_weight(
            name="moving_variance", shape=(c,), initializer="ones",
            trainable=False)
        super().build(input_shape)

    def _global_moments(self, x):
        axes = list(range(x.shape.rank - 1))
        n_local = tf.cast(tf.reduce_prod(tf.shape(x)[:-1]), tf.float32)
        s = tf.reduce_sum(x, axis=axes)
        sq = tf.reduce_sum(tf.square(x), axis=axes)
        packed = tf.concat([s, sq, [n_local]], axis=0)
        packed = allreduce(packed, op=SUM,
                           name="%s.stats" % self.name)
        c = tf.shape(s)[0]
        total = packed[-1]
        mean = packed[:c] / total
        # E[x²]−E[x]² can cancel slightly negative in f32; clamp like
        # the jax sibling (rsqrt of a negative would be NaN).
        var = tf.maximum(packed[c:2 * c] / total - tf.square(mean), 0.0)
        return mean, var

    def _train_moments(self, x):
        mean, var = self._global_moments(tf.cast(x, tf.float32))
        self.moving_mean.assign(
            self.momentum * self.moving_mean
            + (1.0 - self.momentum) * tf.stop_gradient(mean))
        self.moving_variance.assign(
            self.momentum * self.moving_variance
            + (1.0 - self.momentum) * tf.stop_gradient(var))
        return mean, var

    def _infer_moments(self):
        return (tf.identity(self.moving_mean),
                tf.identity(self.moving_variance))

    def call(self, x, training=False):
        x = tf.convert_to_tensor(x)
        # ``training`` may be a symbolic tensor inside tf.function
        # (keras smart_cond contract); resolve it statically when
        # possible, else tf.cond over both branches.  The predicate is
        # rank-uniform (same training flag everywhere), so the
        # collective in the train branch fires on all ranks or none.
        if tf.is_tensor(training):
            static = tf.get_static_value(training)
            training = bool(static) if static is not None else training
        # Frozen layers run in inference mode (keras BatchNormalization
        # contract): batch stats untouched, moving averages preserved.
        if tf.is_tensor(training):
            if self.trainable:
                mean, var = tf.cond(
                    tf.cast(training, tf.bool),
                    lambda: self._train_moments(x),
                    self._infer_moments)
            else:
                mean, var = self._infer_moments()
        elif training and self.trainable:
            mean, var = self._train_moments(x)
        else:
            mean = self.moving_mean
            var = self.moving_variance
        mean = tf.cast(mean, x.dtype)
        var = tf.cast(var, x.dtype)
        inv = tf.math.rsqrt(var + self.epsilon)
        out = (x - mean) * inv
        if self.scale:
            out = out * self.gamma
        if self.center:
            out = out + self.beta
        return out

    def get_config(self):
        cfg = super().get_config()
        cfg.update({"momentum": self.momentum, "epsilon": self.epsilon,
                    "center": self.center, "scale": self.scale})
        return cfg
