"""Native TF op: hvd allreduce inside ``tf.function(jit_compile=True)``.

Reference parity: ``horovod/tensorflow/xla_mpi_ops.cc`` — the XLA
custom-call path that lets collectives live inside a jit-compiled TF
function (the reference's ``HOROVOD_ENABLE_XLA_OPS`` feature; like the
reference, only allreduce is implemented in the XLA path).

The op library is compiled on demand against the installed wheel's
headers/libs (same pattern as the core's ``core/client.py`` build) and
registers:

* ``HvdTpuAllreduce`` CPU kernel — graph/eager execution,
* an ``XLA_CPU_JIT`` kernel lowering to a host custom-call whose
  callback drives the native core (negotiation + wire move) and blocks
  for the result.

Known constraints: the wheel exports no XLA FFI registration symbols,
so the custom call uses the legacy ORIGINAL ABI (XLA:CPU logs a
deprecation notice but executes it); and a "Host" custom-call target
cannot exist inside a TPU executable, so the op is registered for
``XLA_CPU_JIT`` only — on TPU the compiled collective path is
JAX/XLA over ICI (``ops/xla_ops.py``).
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading

LOG = logging.getLogger("horovod_tpu")

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cc")
_SRC = os.path.join(_DIR, "hvd_tf_ops.cc")
_LIB = os.path.join(_DIR, "_hvd_tf_ops.so")

_lock = threading.Lock()
_module = None
_load_error: Exception | None = None


def _build():
    import tensorflow as tf
    tf_dir = os.path.dirname(os.path.abspath(tf.__file__))
    inc = tf.sysconfig.get_include()
    # Build to a per-pid temp then atomically rename: concurrent ranks
    # on one host may build simultaneously.
    tmp = "%s.%d" % (_LIB, os.getpid())
    cmd = ["g++", "-shared", "-fPIC", "-O2", "-w",
           *tf.sysconfig.get_compile_flags(),
           "-I%s/external/highwayhash" % inc,
           "-I%s/external/farmhash_archive/src" % inc,
           _SRC, "-o", tmp,
           "-L%s" % tf_dir,
           "-l:libtensorflow_framework.so.2",
           "-l:libtensorflow_cc.so.2",
           "-Wl,-rpath,%s" % tf_dir, "-ldl"]
    LOG.info("building hvd tf ops: %s", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def load():
    """Build (if stale) and load the op library; returns the module or
    None when the toolchain/runtime cannot support it."""
    global _module, _load_error
    with _lock:
        if _module is not None or _load_error is not None:
            return _module
        try:
            import tensorflow as tf
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_SRC) > os.path.getmtime(_LIB)):
                _build()
            # The custom-call callback reaches the SAME core singleton
            # the Python runtime initialized: point dlopen at it.
            from ..core.client import _LIB_PATH as core_lib
            os.environ.setdefault("HVD_TPU_CORE_LIB", core_lib)
            _module = tf.load_op_library(_LIB)
            _register_gradient()
        except Exception as exc:  # noqa: BLE001 - optional native path
            _load_error = exc
            LOG.warning("hvd tf xla ops unavailable: %s", exc)
        return _module


def _register_gradient():
    from tensorflow.python.framework import ops as tf_ops

    @tf_ops.RegisterGradient("HvdTpuAllreduce")
    def _grad(op, dy):  # noqa: ANN001 - TF registration signature
        # The gradient of an allreduce is the allreduce of the gradient
        # with the same reduce op (reference gradient registration).
        return _module.hvd_tpu_allreduce(
            dy,
            tensor_name=op.get_attr("tensor_name").decode() + "_grad",
            reduce_op=op.get_attr("reduce_op"),
            prescale=op.get_attr("prescale"),
            postscale=op.get_attr("postscale"),
            process_set_id=op.get_attr("process_set_id"))


_RED_OPS = {"Sum": 0, "Average": 1, "Min": 2, "Max": 3, "Product": 4}


def allreduce(tensor, name: str, op: str = "Sum",
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set_id: int = 0):
    """The native-op allreduce (usable inside
    ``tf.function(jit_compile=True)``).  Requires a tcp/multihost world
    (the callback drives the native core)."""
    mod = load()
    if mod is None:
        raise RuntimeError(
            "hvd tf xla ops unavailable: %s" % _load_error)
    return mod.hvd_tpu_allreduce(
        tensor, tensor_name=name, reduce_op=_RED_OPS[op],
        prescale=prescale_factor, postscale=postscale_factor,
        process_set_id=process_set_id)


def enabled() -> bool:
    """The reference's HOROVOD_ENABLE_XLA_OPS knob."""
    return os.environ.get("HOROVOD_ENABLE_XLA_OPS", "0").lower() in (
        "1", "true", "yes")
