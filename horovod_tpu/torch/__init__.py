"""PyTorch adapter: ``import horovod_tpu.torch as hvd``.

Reference parity: ``horovod/torch/__init__.py`` + ``mpi_ops.py`` — the
same surface (init/rank/size, the 8 collectives with ``*_async``/
in-place variants, ``DistributedOptimizer`` with per-parameter gradient
hooks, ``broadcast_parameters`` / ``broadcast_optimizer_state`` /
``broadcast_object``, ``Compression``, ``SyncBatchNorm``, elastic
``TorchState``) routed through this framework's native TCP core instead
of the reference's pybind extension (``horovod/torch/mpi_ops_v2.cc``).

Torch tensors here live on CPU hosts (the TPU compute path is the JAX
adapter); collectives move them through the multi-process world the
launcher spawns.  Without a launcher this adapter initializes a
size-1 tcp world so scripts run unmodified.
"""

from ..common.basics import (shutdown, is_initialized, rank, size,
                             local_rank, local_size, cross_rank,
                             cross_size, is_homogeneous, topology,
                             start_timeline, stop_timeline, xla_built,
                             tcp_built, gloo_built, mpi_built,
                             nccl_built, ccl_built, ddl_built,
                             cuda_built, rocm_built, mpi_enabled,
                             mpi_threads_supported)
from ..common.basics import init as _base_init
from ..common.process_sets import (ProcessSet, global_process_set,
                                   add_process_set, remove_process_set,
                                   process_set_by_id, process_set_ids)
from ..ops.engine import HorovodInternalError
from ..ops.xla_ops import ADASUM, AVERAGE, MAX, MIN, PRODUCT, SUM
from .compression import Compression
from .functions import (allgather_object, broadcast_object,
                        broadcast_optimizer_state, broadcast_parameters)
from .mpi_ops import (allgather, allgather_async, allreduce, allreduce_,
                      allreduce_async, allreduce_async_, alltoall,
                      alltoall_async, barrier, broadcast, broadcast_,
                      broadcast_async, broadcast_async_,
                      grouped_allgather, grouped_allgather_async,
                      grouped_allreduce, grouped_allreduce_async,
                      grouped_reducescatter,
                      grouped_reducescatter_async, join,
                      poll, reducescatter, reducescatter_async,
                      sparse_allreduce, sparse_allreduce_async,
                      synchronize)
from .optimizer import DistributedOptimizer
from .sync_batch_norm import SyncBatchNorm
from . import elastic

Sum = SUM
Average = AVERAGE
Min = MIN
Max = MAX
Product = PRODUCT
Adasum = ADASUM


def init(*args, **kwargs):
    """``hvd.init()`` — defaults to the multi-process (tcp) controller:
    torch semantics are per-process tensors, so even an unlaunched
    script gets a real size-1 world through the native core."""
    kwargs.setdefault("controller", "tcp")
    return _base_init(*args, **kwargs)
