"""Elastic state for torch models.

Reference parity: ``horovod/torch/elastic/state.py`` (``TorchState``) +
``horovod/torch/elastic/sampler.py`` (``ElasticSampler`` — reused from
the framework-free implementation): model/optimizer ``state_dict``s are
cloned to host memory on ``commit()``, restored after failures, and
broadcast from rank 0 on ``sync()`` after a re-rendezvous.
"""

from __future__ import annotations

import copy
from typing import Any, Dict

import torch

from ..common import basics
from ..elastic import run  # noqa: F401 — re-export (hvd.elastic.run)
from ..elastic import ElasticSampler  # noqa: F401 — re-export
from ..elastic.state import ObjectState, State  # noqa: F401
from .functions import broadcast_object, broadcast_parameters


class TorchState(ObjectState):
    """Elastic state holding torch modules/optimizers plus scalars::

        state = hvd.elastic.TorchState(model=model, optimizer=opt,
                                       epoch=0, batch=0)
    """

    def __init__(self, model: torch.nn.Module = None,
                 optimizer: torch.optim.Optimizer = None, **kwargs):
        self._model = model
        self._optimizer = optimizer
        self._saved_model: Dict[str, Any] = {}
        self._saved_opt: Dict[str, Any] = {}
        super().__init__(**kwargs)

    @property
    def model(self):
        return self._model

    @property
    def optimizer(self):
        return self._optimizer

    def save(self):
        super().save()
        if self._model is not None:
            self._saved_model = copy.deepcopy(self._model.state_dict())
        if self._optimizer is not None:
            self._saved_opt = copy.deepcopy(self._optimizer.state_dict())

    def restore(self):
        super().restore()
        if self._model is not None and self._saved_model:
            self._model.load_state_dict(self._saved_model)
        if self._optimizer is not None and self._saved_opt:
            self._optimizer.load_state_dict(self._saved_opt)

    def sync(self):
        super().sync()
        if not basics.is_initialized() or basics.size() <= 1:
            return
        if self._model is not None:
            broadcast_parameters(self._model.state_dict(), root_rank=0)
        if self._optimizer is not None:
            sd = broadcast_object(self._optimizer.state_dict(),
                                  root_rank=0,
                                  name="TorchState.optimizer")
            self._optimizer.load_state_dict(sd)
        self.save()
