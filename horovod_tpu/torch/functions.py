"""Torch parameter/object broadcast helpers.

Reference parity: ``horovod/torch/functions.py`` —
``broadcast_parameters`` (accepts a ``state_dict()`` or
``named_parameters()`` iterable), ``broadcast_optimizer_state``,
``broadcast_object``, ``allgather_object``.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import torch

from ..jax.functions import allgather_object as _allgather_object
from ..jax.functions import broadcast_object as _broadcast_object
from . import mpi_ops


def broadcast_parameters(params, root_rank: int = 0):
    """In-place broadcast of model parameters from ``root_rank``:
    ``hvd.broadcast_parameters(model.state_dict(), root_rank=0)``."""
    if isinstance(params, dict):
        items = sorted(params.items())
    elif isinstance(params, Iterable):
        items = list(params)
    else:
        raise ValueError("invalid params of type %r" % type(params))
    handles = []
    for name, p in items:
        if p is None:
            continue
        if isinstance(p, torch.Tensor):
            handles.append(mpi_ops.broadcast_async_(
                p.data, root_rank, name="broadcast_parameters.%s" % name))
    for h in handles:
        h.wait()


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0):
    """Broadcast the optimizer's ``state_dict`` from root and load it on
    every rank (reference implementation walks tensors; pickling the
    whole dict over the same wire is equivalent for CPU state)."""
    sd = _broadcast_object(optimizer.state_dict(), root_rank,
                           name="broadcast_optimizer_state")
    optimizer.load_state_dict(sd)


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    return _broadcast_object(obj, root_rank, name=name)


def allgather_object(obj: Any, name: Optional[str] = None):
    return _allgather_object(obj, name=name)
