"""Torch collective ops over the native core.

Reference parity: ``horovod/torch/mpi_ops.py`` (+ the handle table in
``mpi_ops_v2.cc`` / ``handle_manager.cc``): every op has a synchronous
form, an ``*_async`` form returning a handle resolved by
``synchronize``/``poll``, and (where the reference has one) an in-place
``*_`` form.  CPU tensors ride as zero-copy numpy views; device
tensors route per ``_payload`` (dlpack into jax where the runtimes
share the device, torch_xla via host materialization) and results
return on the input tensor's device.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import torch

from ..ops import api as _api
from ..ops.xla_ops import AVERAGE, SUM

__all__ = [
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "sparse_allreduce", "sparse_allreduce_async",
    "grouped_allreduce", "grouped_allreduce_async",
    "grouped_allgather", "grouped_allgather_async",
    "grouped_reducescatter", "grouped_reducescatter_async",
    "allgather", "allgather_async", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_", "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "barrier", "join",
    "synchronize", "poll",
]


def _np_view(t: torch.Tensor) -> np.ndarray:
    """CPU tensors as zero-copy numpy views (the wire payload)."""
    if t.dtype == torch.bfloat16:
        # numpy has no native bf16: reinterpret through uint16 onto the
        # ml_dtypes wire representation (same bits, zero copy).
        import ml_dtypes
        return t.detach().contiguous().view(torch.uint16).numpy() \
            .view(ml_dtypes.bfloat16)
    return t.detach().contiguous().numpy()


def _device_to_jax(t: torch.Tensor):
    """Bridge a non-CPU torch tensor into jax without a host round
    trip where the runtimes share the device (dlpack).  The north-star
    routing: device gradients flow through DistributedOptimizer
    unchanged, onto the framework's device payload plane."""
    from jax import dlpack as jdl
    return jdl.from_dlpack(t.detach().contiguous())


def _xla_to_jax(t: torch.Tensor):  # pragma: no cover - needs torch_xla
    """Zero-copy torch_xla -> jax: one ``mark_step`` materializes the
    lazy IR into a device buffer (inherent to lazy tensors — it is the
    host COPY that is eliminated, not the flush), then torch_xla's
    dlpack hands that buffer to jax in place."""
    import torch_xla.core.xla_model as xm
    from torch_xla.utils import dlpack as xdl

    from jax import dlpack as jdl
    xm.mark_step()
    return jdl.from_dlpack(xdl.to_dlpack(t.detach()))


def _payload(t: torch.Tensor):
    """Tensor -> collective payload.

    * CPU tensor: zero-copy numpy view (host/wire plane).
    * torch_xla tensor (``device.type == 'xla'``): shared-buffer dlpack
      bridge into jax (``_xla_to_jax``) so the payload stays on the
      device plane; host materialization only as the fallback for
      torch_xla builds without dlpack.
    * other device tensors (e.g. cuda): dlpack into jax when a device
      payload plane exists — in tcp mode the only backend is host-TCP,
      which would immediately copy a bridged array back to host, so go
      straight to the host view there; host copy is also the fallback
      when jax lacks a matching device backend.
    """
    if t.device.type == "cpu":
        return _np_view(t)
    if t.device.type == "xla":  # pragma: no cover - needs torch_xla
        from ..common import basics
        if basics.is_initialized() and \
                basics._controller_mode() == "tcp":
            # Host-TCP payload plane: bridging to a jax device array
            # would be copied straight back to host — materialize once
            # and ship the host view (same rule as the cuda branch).
            import torch_xla.core.xla_model as xm
            xm.mark_step()
            return _np_view(t.cpu())
        try:
            return _xla_to_jax(t)
        except Exception:
            import torch_xla.core.xla_model as xm
            xm.mark_step()
            return _np_view(t.cpu())
    from ..common import basics
    if basics.is_initialized() and basics._controller_mode() == "tcp":
        return _np_view(t.cpu())  # pragma: no cover - needs a device
    try:
        return _device_to_jax(t)
    except Exception:  # pragma: no cover - runtime-dependent bridge
        return _np_view(t.cpu())


class TorchHandle:
    """Async handle returning torch tensors (reference HandleManager)."""

    def __init__(self, inner, like: Optional[torch.Tensor] = None,
                 out: Optional[torch.Tensor] = None):
        self._inner = inner
        self._like = like
        self._out = out  # in-place target

    def poll(self) -> bool:
        return self._inner.poll()

    def wait(self, timeout: Optional[float] = None):
        res = self._inner.wait(timeout)
        splits = None
        if isinstance(res, tuple):
            res, splits = res
        if isinstance(res, list):
            # Ragged result (in-process uneven reducescatter, or
            # alltoall with per-rank shapes): one tensor per rank; no
            # in-place target applies.  Keep the (output, recv_splits)
            # contract when splits rode along.
            converted = [self._convert(r) for r in res]
            return (converted, splits) if splits is not None else converted
        t = self._convert(res)
        if self._out is not None:
            self._out.data.copy_(t.reshape(self._out.shape))
            t = self._out
        return (t, splits) if splits is not None else t

    def _convert(self, res) -> torch.Tensor:
        if (self._like is not None and self._like.device.type == "xla"
                and not isinstance(res, np.ndarray)):
            # pragma: no cover - needs torch_xla
            # Device-plane result for an xla input: hand the jax buffer
            # back through dlpack — the return leg of the zero-copy
            # bridge.  Host conversion below is the fallback.
            try:
                from torch_xla.utils import dlpack as xdl
                return xdl.from_dlpack(res)
            except Exception:  # noqa: BLE001 - bridge availability
                pass
        arr = np.ascontiguousarray(np.asarray(res))
        if arr.dtype.name == "bfloat16":
            t = torch.from_numpy(arr.view(np.uint16)) \
                .view(torch.bfloat16)
        else:
            t = torch.from_numpy(arr)
        if self._like is not None:
            if t.dtype != self._like.dtype:
                t = t.to(self._like.dtype)
            if self._like.device.type != "cpu":
                # Device tensors come back on their device.
                t = t.to(self._like.device)
        return t


def synchronize(handle: TorchHandle):
    return handle.wait()


def poll(handle: TorchHandle) -> bool:
    return handle.poll()


# -- allreduce -------------------------------------------------------------

def allreduce_async(tensor: torch.Tensor, average=None,
                    name: Optional[str] = None, op=None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set=None) -> TorchHandle:
    h = _api.allreduce_async(_payload(tensor), average, name, op,
                             prescale_factor, postscale_factor,
                             process_set)
    return TorchHandle(h, like=tensor)


def allreduce_async_(tensor: torch.Tensor, average=None,
                     name: Optional[str] = None, op=None,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     process_set=None) -> TorchHandle:
    """In-place async allreduce (reference ``hvd.allreduce_async_``)."""
    h = _api.allreduce_async(_payload(tensor), average, name, op,
                             prescale_factor, postscale_factor,
                             process_set)
    return TorchHandle(h, like=tensor, out=tensor)


# -- autograd integration (reference: the HorovodAllreduce/... autograd
# Functions in horovod/torch/mpi_ops.py — sync collectives are
# differentiable; backward math mirrors the TF gradient registrations)

def _gname(name):
    return None if name is None else name + "_grad"


class _AllreduceFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, average, name, op, prescale, postscale,
                process_set):
        ctx.args = (average, name, op, prescale, postscale, process_set)
        return allreduce_async(tensor, average, name, op, prescale,
                               postscale, process_set).wait()

    @staticmethod
    def backward(ctx, grad):
        average, name, op, prescale, postscale, ps = ctx.args
        g = allreduce_async(grad.contiguous(), average, _gname(name),
                            op, prescale, postscale, ps).wait()
        return g, None, None, None, None, None, None


class _AllgatherFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name, process_set):
        ctx.n_local = int(tensor.shape[0])
        ctx.name = name
        ctx.process_set = process_set
        return allgather_async(tensor, name, process_set).wait()

    @staticmethod
    def backward(ctx, grad):
        gname = _gname(ctx.name)
        summed = allreduce_async(grad.contiguous(), op=SUM, name=gname,
                                 process_set=ctx.process_set).wait()
        sizes = np.asarray(_api.allgather(
            np.asarray([ctx.n_local], np.int64),
            name=None if gname is None else gname + "_sizes",
            process_set=ctx.process_set))
        from ..common import basics
        if ctx.process_set is not None:
            my = ctx.process_set.rank()
        else:
            my = basics.rank()
        off = int(sizes[:my].sum())
        return summed[off:off + ctx.n_local], None, None


class _BroadcastFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name, process_set):
        ctx.root_rank = root_rank
        ctx.name = name
        ctx.process_set = process_set
        return broadcast_async(tensor, root_rank, name,
                               process_set).wait()

    @staticmethod
    def backward(ctx, grad):
        # Sum of upstream grads lands on the root; non-roots get zero
        # (root_rank is a GLOBAL rank, core broadcast semantics).
        g = allreduce_async(grad.contiguous(), op=SUM,
                            name=_gname(ctx.name),
                            process_set=ctx.process_set).wait()
        from ..common import basics
        if basics.rank() != ctx.root_rank:
            g = torch.zeros_like(g)
        return g, None, None, None


class _ReducescatterFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, op, name, process_set):
        ctx.op = op
        ctx.name = name
        ctx.process_set = process_set
        return reducescatter_async(tensor, op, name, process_set).wait()

    @staticmethod
    def backward(ctx, grad):
        g = allgather_async(grad.contiguous(), name=_gname(ctx.name),
                            process_set=ctx.process_set).wait()
        if ctx.op == AVERAGE:
            # The forward divides by the set size; the backward must
            # scale the allgathered grad the same way.
            from ..common import basics
            size = (ctx.process_set.size() if ctx.process_set is not None
                    else basics.size())
            g = g / size
        return g, None, None, None


class _AlltoallFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, splits_list, name, process_set):
        ctx.name = name
        ctx.process_set = process_set
        # With explicit splits the handle always resolves to
        # (output, recv_splits).
        out, recv = alltoall_async(tensor, splits_list, name,
                                   process_set).wait()
        ctx.recv = [int(i) for i in recv]
        recv_t = torch.as_tensor(ctx.recv, dtype=torch.int64)
        ctx.mark_non_differentiable(recv_t)
        return out, recv_t

    @staticmethod
    def backward(ctx, grad, _grad_recv):
        # Reverse routing with the FORWARD's receive splits.
        g, _ = alltoall_async(grad.contiguous(), ctx.recv,
                              _gname(ctx.name), ctx.process_set).wait()
        return g, None, None, None


class _GroupedAllreduceFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, average, name, op, prescale, postscale,
                process_set, *tensors):
        ctx.args = (average, name, op, prescale, postscale, process_set)
        hs = grouped_allreduce_async(list(tensors), average, name, op,
                                     prescale, postscale, process_set)
        return tuple(h.wait() for h in hs)

    @staticmethod
    def backward(ctx, *grads):
        average, name, op, prescale, postscale, ps = ctx.args
        hs = grouped_allreduce_async(
            [g.contiguous() for g in grads], average, _gname(name), op,
            prescale, postscale, ps)
        return (None,) * 6 + tuple(h.wait() for h in hs)


class _GroupedAllgatherFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, name, process_set, *tensors):
        ctx.name = name
        ctx.process_set = process_set
        ctx.n_locals = [int(t.shape[0]) for t in tensors]
        hs = grouped_allgather_async(list(tensors), name, process_set)
        return tuple(h.wait() for h in hs)

    @staticmethod
    def backward(ctx, *grads):
        gname = _gname(ctx.name)
        hs = grouped_allreduce_async(
            [g.contiguous() for g in grads], op=SUM, name=gname,
            process_set=ctx.process_set)
        summed = [h.wait() for h in hs]
        # One tiny sizes allgather covers every member's offsets.
        sizes = np.asarray(_api.allgather(
            np.asarray(ctx.n_locals, np.int64).reshape(1, -1),
            name=None if gname is None else gname + "_sizes",
            process_set=ctx.process_set))
        from ..common import basics
        my = (ctx.process_set.rank() if ctx.process_set is not None
              else basics.rank())
        outs = []
        for i, (s, n) in enumerate(zip(summed, ctx.n_locals)):
            off = int(sizes[:my, i].sum())
            outs.append(s[off:off + n])
        return (None, None) + tuple(outs)


class _GroupedReducescatterFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, op, name, process_set, *tensors):
        ctx.op = op
        ctx.name = name
        ctx.process_set = process_set
        hs = grouped_reducescatter_async(list(tensors), op, name,
                                         process_set)
        return tuple(h.wait() for h in hs)

    @staticmethod
    def backward(ctx, *grads):
        hs = grouped_allgather_async(
            [g.contiguous() for g in grads], _gname(ctx.name),
            ctx.process_set)
        gs = [h.wait() for h in hs]
        if ctx.op == AVERAGE:
            from ..common import basics
            size = (ctx.process_set.size()
                    if ctx.process_set is not None else basics.size())
            gs = [g / size for g in gs]
        return (None, None, None) + tuple(gs)


def _wants_grad(tensor) -> bool:
    return (torch.is_grad_enabled()
            and isinstance(tensor, torch.Tensor)
            and tensor.requires_grad)


def allreduce(tensor, average=None, name=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              process_set=None) -> torch.Tensor:
    if _wants_grad(tensor):
        return _AllreduceFn.apply(tensor, average, name, op,
                                  prescale_factor, postscale_factor,
                                  process_set)
    return allreduce_async(tensor, average, name, op, prescale_factor,
                           postscale_factor, process_set).wait()


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0,
               process_set=None) -> torch.Tensor:
    return allreduce_async_(tensor, average, name, op, prescale_factor,
                            postscale_factor, process_set).wait()


class SparseTorchHandle:
    """Handle for a sparse allreduce: two ragged allgathers (indices,
    values) resolved into a coalesced sparse tensor (reference
    ``sparse_allreduce_async`` in horovod/torch/mpi_ops.py)."""

    def __init__(self, h_idx, h_val, shape, dtype, device, divisor):
        self._h_idx = h_idx
        self._h_val = h_val
        self._shape = shape
        self._dtype = dtype
        self._device = device
        self._divisor = divisor

    def poll(self) -> bool:
        return self._h_idx.poll() and self._h_val.poll()

    def wait(self, timeout: Optional[float] = None) -> torch.Tensor:
        idx = self._h_idx.wait(timeout)   # (sum nnz, ndim)
        val = self._h_val.wait(timeout)   # (sum nnz, *dense_dims)
        out = torch.sparse_coo_tensor(
            idx.t().contiguous(), val, self._shape,
            dtype=self._dtype).coalesce()  # coalesce sums duplicates
        if self._divisor != 1:
            out = out / self._divisor
        return out.to(self._device) if self._device.type != "cpu" else out


def sparse_allreduce_async(tensor: torch.Tensor,
                           name: Optional[str] = None, op=None,
                           process_set=None) -> SparseTorchHandle:
    """Reduce a ``torch.sparse_coo`` tensor across ranks without
    densifying: allgather each rank's (indices, values) and sum
    duplicates via coalesce.  Sum and Average only."""
    if op is None:
        op = AVERAGE
    if op not in (SUM, AVERAGE):
        raise ValueError("sparse allreduce supports Sum/Average only")
    if not tensor.is_sparse:
        raise ValueError("sparse_allreduce_async needs a sparse tensor")
    t = tensor.coalesce()
    # Wire layouts gather on dim 0: indices ride transposed (nnz, ndim).
    idx = t.indices().t().contiguous()
    val = t.values().contiguous()
    # Deterministic cross-rank auto-name (negotiation is keyed by exact
    # name match; id() would differ per process).
    base = _api._auto_name("sparse_allreduce", name)
    h_i = allgather_async(idx, name=base + ".indices",
                          process_set=process_set)
    h_v = allgather_async(val, name=base + ".values",
                          process_set=process_set)
    from ..common import basics
    if process_set is not None:
        world = process_set.size()
    else:
        world = basics.size()
    return SparseTorchHandle(h_i, h_v, tuple(t.shape), t.dtype,
                             tensor.device,
                             world if op == AVERAGE else 1)


def sparse_allreduce(tensor: torch.Tensor, name: Optional[str] = None,
                     op=None, process_set=None) -> torch.Tensor:
    return sparse_allreduce_async(tensor, name, op, process_set).wait()


def grouped_allreduce_async(tensors: Sequence[torch.Tensor], average=None,
                            name: Optional[str] = None, op=None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set=None) -> List[TorchHandle]:
    hs = _api.grouped_allreduce_async(
        [_payload(t) for t in tensors], average, name, op,
        prescale_factor, postscale_factor, process_set)
    return [TorchHandle(h, like=t) for h, t in zip(hs, tensors)]


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set=None) -> List[torch.Tensor]:
    if any(_wants_grad(t) for t in tensors):
        return list(_GroupedAllreduceFn.apply(
            average, name, op, prescale_factor, postscale_factor,
            process_set, *tensors))
    return [h.wait() for h in grouped_allreduce_async(
        tensors, average, name, op, prescale_factor, postscale_factor,
        process_set)]


# -- allgather -------------------------------------------------------------

def allgather_async(tensor: torch.Tensor, name: Optional[str] = None,
                    process_set=None) -> TorchHandle:
    h = _api.allgather_async(_payload(tensor), name, process_set)
    return TorchHandle(h, like=tensor)


def allgather(tensor, name=None, process_set=None) -> torch.Tensor:
    if _wants_grad(tensor):
        return _AllgatherFn.apply(tensor, name, process_set)
    return allgather_async(tensor, name, process_set).wait()


def grouped_allgather_async(tensors: Sequence[torch.Tensor],
                            name: Optional[str] = None,
                            process_set=None) -> List[TorchHandle]:
    hs = _api.grouped_allgather_async(
        [_payload(t) for t in tensors], name, process_set)
    return [TorchHandle(h, like=t) for h, t in zip(hs, tensors)]


def grouped_allgather(tensors, name=None,
                      process_set=None) -> List[torch.Tensor]:
    if any(_wants_grad(t) for t in tensors):
        return list(_GroupedAllgatherFn.apply(
            name, process_set, *tensors))
    return [h.wait() for h in grouped_allgather_async(
        tensors, name, process_set)]


def grouped_reducescatter_async(tensors: Sequence[torch.Tensor],
                                op=None, name: Optional[str] = None,
                                process_set=None) -> List[TorchHandle]:
    hs = _api.grouped_reducescatter_async(
        [_payload(t) for t in tensors], op, name, process_set)
    return [TorchHandle(h, like=t) for h, t in zip(hs, tensors)]


def grouped_reducescatter(tensors, op=None, name=None,
                          process_set=None) -> List[torch.Tensor]:
    if any(_wants_grad(t) for t in tensors):
        return list(_GroupedReducescatterFn.apply(
            op, name, process_set, *tensors))
    return [h.wait() for h in grouped_reducescatter_async(
        tensors, op, name, process_set)]


# -- broadcast -------------------------------------------------------------

def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None,
                    process_set=None) -> TorchHandle:
    h = _api.broadcast_async(_payload(tensor), root_rank, name,
                             process_set)
    return TorchHandle(h, like=tensor)


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None,
                     process_set=None) -> TorchHandle:
    h = _api.broadcast_async(_payload(tensor), root_rank, name,
                             process_set)
    return TorchHandle(h, like=tensor, out=tensor)


def broadcast(tensor, root_rank: int, name=None,
              process_set=None) -> torch.Tensor:
    if _wants_grad(tensor):
        return _BroadcastFn.apply(tensor, root_rank, name, process_set)
    return broadcast_async(tensor, root_rank, name, process_set).wait()


def broadcast_(tensor, root_rank: int, name=None,
               process_set=None) -> torch.Tensor:
    return broadcast_async_(tensor, root_rank, name, process_set).wait()


# -- alltoall / reducescatter ----------------------------------------------

def alltoall_async(tensor: torch.Tensor, splits=None,
                   name: Optional[str] = None,
                   process_set=None) -> TorchHandle:
    if splits is not None and isinstance(splits, torch.Tensor):
        splits = splits.tolist()
    h = _api.alltoall_async(_payload(tensor), splits, name, process_set)
    return TorchHandle(h, like=tensor)


def alltoall(tensor, splits=None, name=None, process_set=None):
    # Differentiable when splits are explicit (the backward reverse-
    # routes with the forward's receive splits); the splits-less form
    # may return ragged per-rank results and stays non-differentiable.
    if _wants_grad(tensor) and splits is not None:
        if isinstance(splits, torch.Tensor):
            splits = splits.tolist()
        out, recv_t = _AlltoallFn.apply(tensor, splits, name,
                                        process_set)
        return out, recv_t
    res = alltoall_async(tensor, splits, name, process_set).wait()
    if splits is None:
        return res[0] if isinstance(res, tuple) else res
    out, recv = res
    # recv_splits is a torch tensor on both the grad and no-grad paths.
    return out, torch.as_tensor([int(i) for i in recv],
                                dtype=torch.int64)


def reducescatter_async(tensor: torch.Tensor, op=SUM,
                        name: Optional[str] = None,
                        process_set=None) -> TorchHandle:
    h = _api.reducescatter_async(_payload(tensor), op, name, process_set)
    return TorchHandle(h, like=tensor)


def reducescatter(tensor, op=SUM, name=None,
                  process_set=None) -> torch.Tensor:
    if _wants_grad(tensor):
        return _ReducescatterFn.apply(tensor, op, name, process_set)
    return reducescatter_async(tensor, op, name, process_set).wait()


# -- barrier / join --------------------------------------------------------

def barrier(process_set=None):
    return _api.barrier(process_set)


def join(device=None) -> int:
    return _api.join(device)
